"""Headline benchmark: training throughput on the available chip(s).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.

Round-1 metric: GPT-2 125M training tokens/sec/chip (driver config #1).
``vs_baseline`` reports measured MFU / 0.45 — the north-star is >=45% MFU
(BASELINE.md); >1.0 means the target is beaten on this metric.
"""

from __future__ import annotations

import argparse
import json
import os
import time


def peak_flops_per_chip(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    # bf16 peak matmul flops
    table = {
        "v5e": 197e12, "v5 lite": 197e12, "v5litepod": 197e12,
        "v5p": 459e12, "v4": 275e12, "v3": 123e12, "v6e": 918e12,
    }
    for k, v in table.items():
        if k in kind:
            return v
    if "tpu" in kind:
        return 197e12
    return 1e12  # CPU-sim: nominal


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--flash", dest="flash", default=None,
                    action="store_true", help="force the Pallas flash kernel")
    ap.add_argument("--no-flash", dest="flash", action="store_false")
    ap.add_argument("--remat", dest="remat", default=None, action="store_true")
    ap.add_argument("--no-remat", dest="remat", action="store_false")
    ap.add_argument("--micro-bs", type=int, default=None)
    ap.add_argument("--gas", type=int, default=None,
                    help="gradient accumulation steps")
    ap.add_argument("--seq", type=int, default=None)
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--bq", type=int, default=None, help="flash block_q")
    ap.add_argument("--bk", type=int, default=None, help="flash block_k")
    ap.add_argument("--remat-policy", default=None,
                    choices=["full", "dots", "dots_flash"])
    ap.add_argument("--multi", type=int, default=None,
                    help="global steps per dispatch (train_batches); "
                         "1 = per-step dispatch")
    ap.add_argument("--no-scan-layers", dest="scan_layers",
                    action="store_false", default=None,
                    help="unroll the layer loop (no scan residual stacking)")
    args = ap.parse_args()

    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    n_chips = len(jax.devices())
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = gpt2.GPT2Config.gpt2_125m()
        # measured-best v5e config (PROFILE.md): selective remat with the
        # flash kernel's o pinned, unrolled layer loop (no scan
        # residual-stacking copies), the fused v2 flash backward with
        # 1024-row q blocks, and gas=16 so the optimizer/step overhead
        # amortizes over 16 microbatches
        cfg.remat = True
        cfg.use_flash = True
        cfg.remat_policy = "dots_flash"
        cfg.scan_layers = False
        cfg.flash_block_q, cfg.flash_block_k = 1024, 1024
        micro_bs, seq, steps = 32, 1024, 16
        gas = 16
    else:  # CPU smoke mode
        cfg = gpt2.GPT2Config(vocab_size=2048, max_seq_len=256, num_layers=4,
                              num_heads=8, hidden_size=256)
        micro_bs, seq, steps = 2, 128, 5
        gas = 1
    if args.flash is not None:
        cfg.use_flash = args.flash
    if args.remat is not None:
        cfg.remat = args.remat
    if args.bq:
        cfg.flash_block_q = args.bq
    if args.bk:
        cfg.flash_block_k = args.bk
    if args.remat_policy:
        cfg.remat_policy = args.remat_policy
    if args.scan_layers is not None:
        cfg.scan_layers = args.scan_layers
    if args.seq and args.micro_bs is None and on_tpu:
        # keep tokens/microbatch constant so long sequences fit HBM
        micro_bs = max(1, micro_bs * seq // args.seq)
    micro_bs = args.micro_bs or micro_bs
    seq = args.seq or seq
    steps = args.steps or steps
    cfg.max_seq_len = max(cfg.max_seq_len, seq)

    gas = args.gas or gas
    config = {
        "train_micro_batch_size_per_gpu": micro_bs,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1 if n_chips > 1 else 0},
    }
    model = gpt2.build(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)

    rng = np.random.default_rng(0)

    def batch():
        return {"input_ids": rng.integers(
            0, cfg.vocab_size, size=(engine.train_batch_size(), seq + 1)
        ).astype(np.int32)}

    # warmup / compile (both the single-step and the multi-step programs)
    multi = args.multi if args.multi is not None else \
        (5 if (on_tpu and gas == 1) else 1)
    multi = max(1, min(multi, steps))
    steps -= steps % multi
    for _ in range(2):
        _, m = engine.train_batch(batch())
    if multi > 1:
        _, m = engine.train_batches([batch() for _ in range(multi)])
    # NOTE: sync by fetching a metric VALUE, not jax.block_until_ready —
    # on the tunneled axon backend block_until_ready returns without
    # waiting, which would time only dispatch.  The final loss depends on
    # the whole step chain, so fetching it bounds all 20 steps.
    float(m["loss"])
    t0 = time.perf_counter()
    if multi > 1:
        for _ in range(steps // multi):
            _, m = engine.train_batches([batch() for _ in range(multi)])
    else:
        for _ in range(steps):
            _, m = engine.train_batch(batch())
    float(m["loss"])
    dt = time.perf_counter() - t0

    tokens = engine.train_batch_size() * seq * steps
    tok_per_sec_per_chip = tokens / dt / n_chips
    flops_per_token = 6.0 * cfg.num_params() + 12 * cfg.num_layers * \
        cfg.hidden_size * seq  # attention term
    mfu = tok_per_sec_per_chip * flops_per_token / peak_flops_per_chip(
        jax.devices()[0])
    print(json.dumps({
        "metric": "gpt2_125m_train_tokens_per_sec_per_chip" if on_tpu else
                  "gpt2_smoke_train_tokens_per_sec_per_chip",
        "value": round(tok_per_sec_per_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.45, 4),
    }))


if __name__ == "__main__":
    main()
