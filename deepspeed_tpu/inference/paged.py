"""Host-side management for the block-paged KV cache: free-list block
allocator + prefix cache (token-trie over full blocks).

The serving engine's KV pool is a single ``[L, num_blocks, HKV, block_size,
hd]`` buffer; each sequence owns an ``int32`` *block table* mapping its
logical block index (position // block_size) to a physical block.  This
module owns the host-side bookkeeping:

 - :class:`BlockAllocator` — fixed pool of refcounted blocks with a FIFO
   free list.  Physical block 0 is RESERVED as scratch: pad rows, inactive
   slots, and masked-out prefill tokens write their (discarded) KV there, so
   every device program keeps a fixed shape without a dedicated pad slot.
 - :class:`PrefixCache` — vLLM automatic-prefix-caching / SGLang
   RadixAttention at block granularity.  Keys are ``(parent entry id, block
   token tuple)`` chains, so a lookup walks the trie block by block: a new
   request whose prompt shares a block-aligned prefix with any previously
   prefilled sequence reuses those physical blocks with zero recompute.
   The cache holds one reference on each registered block; when the
   allocator runs dry the engine evicts least-recently-used leaf entries
   whose block nobody else holds (``evict_one``).

Copy-on-write is never needed: lookups are capped below the full prompt
(at least one tail token is always recomputed) and reuse is full-block
only, so a sequence's next write position always lands in a privately
owned block — shared blocks are read-only by construction.

Speculative decoding adds no allocator state: the scheduler allocates a
slot's table entries up to ``min(committed + K + 1, pos_cap)`` before each
verify round — ``pos_cap`` (prompt + completion budget) bounds demand, and
window positions past it scatter to the scratch block instead of
allocating.  Rolling back rejected draft tokens therefore never increfs,
decrefs, or frees anything; the scheduler's host-side length simply stays
at the committed value and the same blocks are rewritten in place next
round.  Preempting mid-window releases the slot's blocks exactly like the
non-speculative path (refcounts make prefix-shared blocks survive).

All of this is plain Python/numpy on the host; the device-side scatter /
gather twins live in ``ops/paged_kv.py`` and ``ops/decode_attention.py``.

**Tensor parallelism**: everything in this module is per-host and
head-sharding-invariant.  Block ids, refcounts, and trie keys index
PHYSICAL BLOCKS (position spans), never attention heads — when the
serving engine shards the device pool over the KV-head dim
(``NamedSharding(mesh, P(None, None, "tp"))``), every chip holds the same
``num_blocks`` blocks, just a head slice of each, and the SAME block
table drives every shard's scatter/gather.  Allocator and trie state
therefore needs no replication, synchronization, or tp-aware branching:
one host-side instance is correct at any tp degree, and scheduling
decisions (admission, eviction, preemption) are bit-identical across
topologies.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict, deque
from typing import List, Optional, Sequence

#: physical block 0 is never allocated; discarded writes are routed there
SCRATCH_BLOCK = 0


class BlockAllocator:
    """Refcounted free-list allocator over ``num_blocks`` KV blocks.

    Block ids are ``1 .. num_blocks-1`` (:data:`SCRATCH_BLOCK` is reserved).
    ``alloc`` hands out a block with refcount 1; sharing (prefix reuse, the
    prefix cache's own hold) goes through ``incref``/``decref``; a block
    returns to the free list when its count reaches zero.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (1 scratch + 1 usable), got "
                f"{num_blocks}")
        self.num_blocks = int(num_blocks)
        self._free = deque(range(1, num_blocks))
        self._ref = [0] * num_blocks
        #: bumped on every alloc/incref/decref — anything derived from
        #: refcounts (free counts, prefix-cache evictability) is stale iff
        #: this moved, which lets the scheduler memoize its admission gate
        #: while the queue head is blocked
        self.version = 0

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        """Blocks currently held by at least one owner (excludes scratch)."""
        return self.num_blocks - 1 - len(self._free)

    def refcount(self, block: int) -> int:
        return self._ref[block]

    def snapshot(self):
        """(refcounts copy, free-list copy) for the invariant checker
        (``analysis/invariants.py``) — read-only view of allocator state."""
        return list(self._ref), list(self._free)

    def alloc(self) -> Optional[int]:
        """A fresh block with refcount 1, or ``None`` when the pool is dry
        (the caller then evicts from the prefix cache / preempts)."""
        if not self._free:
            return None
        b = self._free.popleft()
        assert self._ref[b] == 0, f"block {b} on free list with refs"
        self._ref[b] = 1
        self.version += 1
        return b

    def incref(self, block: int) -> None:
        assert self._ref[block] > 0, f"incref on unowned block {block}"
        self._ref[block] += 1
        self.version += 1

    def decref(self, block: int) -> None:
        assert self._ref[block] > 0, f"decref on unowned block {block}"
        self._ref[block] -= 1
        self.version += 1
        if self._ref[block] == 0:
            self._free.append(block)


@dataclasses.dataclass
class _PrefixEntry:
    uid: int                    # stable id for child keys (never reused)
    key: tuple                  # (parent uid | 0, token tuple)
    block: int                  # physical block holding this token span's KV
    parent: Optional["_PrefixEntry"]
    children: int = 0


class PrefixCache:
    """Token-trie over FULL KV blocks: chained ``(parent, tokens)`` keys.

    ``lookup`` walks a prompt block by block and claims (increfs) the
    longest cached block-aligned prefix; ``register`` inserts a freshly
    prefilled prompt's full blocks, with the cache itself holding one
    reference so the blocks outlive the sequence.  ``evict_one`` releases
    the least-recently-used *leaf* entry whose block only the cache still
    holds — parents are only evictable once all their children are gone, so
    every cached chain stays walkable from the root.
    """

    def __init__(self, block_size: int):
        self.block_size = int(block_size)
        self._entries: "OrderedDict[tuple, _PrefixEntry]" = OrderedDict()
        self._next_uid = 1
        # counters for ServingEngine.stats()
        self.lookups = 0
        self.hit_blocks = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def entries(self):
        """The live trie entries (insertion/LRU order) — read-only view for
        the invariant checker (``analysis/invariants.py``)."""
        return list(self._entries.values())

    def probe(self, tokens: Sequence[int], max_tokens: int) -> int:
        """Number of leading full blocks of ``tokens[:max_tokens]`` present
        in the trie — no refcounts touched (admission-gate peek)."""
        bs = self.block_size
        parent_uid, n = 0, 0
        for i in range(min(len(tokens), max_tokens) // bs):
            e = self._entries.get(
                (parent_uid, tuple(int(t) for t in
                                   tokens[i * bs:(i + 1) * bs])))
            if e is None:
                break
            parent_uid, n = e.uid, n + 1
        return n

    def lookup(self, tokens: Sequence[int], max_tokens: int,
               allocator: BlockAllocator) -> List[int]:
        """Claim the longest cached block-aligned prefix of
        ``tokens[:max_tokens]``: increfs and returns the physical block ids
        (the caller owns one reference per returned block)."""
        bs = self.block_size
        blocks: List[int] = []
        parent_uid = 0
        self.lookups += 1
        for i in range(min(len(tokens), max_tokens) // bs):
            key = (parent_uid,
                   tuple(int(t) for t in tokens[i * bs:(i + 1) * bs]))
            e = self._entries.get(key)
            if e is None:
                break
            self._entries.move_to_end(key)      # LRU touch
            allocator.incref(e.block)
            blocks.append(e.block)
            parent_uid = e.uid
        self.hit_blocks += len(blocks)
        return blocks

    def register(self, tokens: Sequence[int], blocks: Sequence[int],
                 allocator: BlockAllocator) -> None:
        """Insert the chain ``tokens[i*bs:(i+1)*bs] -> blocks[i]``.  Existing
        entries win (the first prefill of a shared prompt is the canonical
        copy; a duplicate block simply isn't cached and frees with its
        sequence) — the chain continues through them either way."""
        bs = self.block_size
        parent: Optional[_PrefixEntry] = None
        for i, b in enumerate(blocks):
            key = ((parent.uid if parent else 0),
                   tuple(int(t) for t in tokens[i * bs:(i + 1) * bs]))
            e = self._entries.get(key)
            if e is None:
                e = _PrefixEntry(uid=self._next_uid, key=key, block=int(b),
                                 parent=parent)
                self._next_uid += 1
                allocator.incref(int(b))
                if parent is not None:
                    parent.children += 1
                self._entries[key] = e
            self._entries.move_to_end(key)
            parent = e

    def evictable(self, allocator: BlockAllocator) -> int:
        """Blocks reclaimable by repeated :meth:`evict_one` calls.  A block
        whose refcount is exactly 1 is held only by the cache; any live
        sequence using a child of an entry also holds the parent's block
        (prefix chains are claimed whole), so refcount-1 entries always
        drain leaf-first."""
        return sum(1 for e in self._entries.values()
                   if allocator.refcount(e.block) == 1)

    def evict_one(self, allocator: BlockAllocator) -> Optional[int]:
        """Release the LRU leaf entry only the cache still holds; returns
        the freed block id (truthy — block 0 is scratch and never cached)
        or ``None``.  The id lets the caller retire per-block side state in
        lockstep with the free (the serving engine's int8-KV scale ledger,
        ``serving.py``)."""
        for key, e in self._entries.items():    # oldest first
            if e.children == 0 and allocator.refcount(e.block) == 1:
                del self._entries[key]
                if e.parent is not None:
                    e.parent.children -= 1
                allocator.decref(e.block)
                self.evictions += 1
                return int(e.block)
        return None
