"""Host-side management for the block-paged KV cache: free-list block
allocator + prefix cache (token-trie over full blocks).

The serving engine's KV pool is a single ``[L, num_blocks, HKV, block_size,
hd]`` buffer; each sequence owns an ``int32`` *block table* mapping its
logical block index (position // block_size) to a physical block.  This
module owns the host-side bookkeeping:

 - :class:`BlockAllocator` — fixed pool of refcounted blocks with a FIFO
   free list.  Physical block 0 is RESERVED as scratch: pad rows, inactive
   slots, and masked-out prefill tokens write their (discarded) KV there, so
   every device program keeps a fixed shape without a dedicated pad slot.
 - :class:`PrefixCache` — vLLM automatic-prefix-caching / SGLang
   RadixAttention at block granularity.  Keys are ``(parent entry id, block
   token tuple)`` chains, so a lookup walks the trie block by block: a new
   request whose prompt shares a block-aligned prefix with any previously
   prefilled sequence reuses those physical blocks with zero recompute.
   The cache holds one reference on each registered block; when the
   allocator runs dry the engine evicts least-recently-used leaf entries
   whose block nobody else holds (``evict_one``).

Copy-on-write is never needed: lookups are capped below the full prompt
(at least one tail token is always recomputed) and reuse is full-block
only, so a sequence's next write position always lands in a privately
owned block — shared blocks are read-only by construction.

Speculative decoding adds no allocator state: the scheduler allocates a
slot's table entries up to ``min(committed + K + 1, pos_cap)`` before each
verify round — ``pos_cap`` (prompt + completion budget) bounds demand, and
window positions past it scatter to the scratch block instead of
allocating.  Rolling back rejected draft tokens therefore never increfs,
decrefs, or frees anything; the scheduler's host-side length simply stays
at the committed value and the same blocks are rewritten in place next
round.  Preempting mid-window releases the slot's blocks exactly like the
non-speculative path (refcounts make prefix-shared blocks survive).

All of this is plain Python/numpy on the host; the device-side scatter /
gather twins live in ``ops/paged_kv.py`` and ``ops/decode_attention.py``.

**Tiered KV (host-DRAM block tier)**: :class:`HostBlockStore` is the tier
below the device pool — a host numpy arena (the pinned-staging analog of
``runtime/zero/offload.py``'s moment buffers) sized in whole KV blocks,
with its own free list and LRU entry table.  Entries are
content-addressed by :func:`chain_key` — a fixed-width rolling digest
over ALL tokens from position 0 through the end of the block — so the
same key that
names a block span in the prefix trie names its host copy, and a chain
demoted block-by-block is re-discoverable block-by-block (each key
stands alone; no host-side parent pointers).  Residency is exclusive by
construction: demotion MOVES a block's bytes device→host (the device
block frees), promotion moves them back (the host slot frees), and a
``staged`` entry (``in_flight``) is a promotion whose ``device_put`` has
been issued but whose pool scatter has not landed — the
``residency-conservation`` audit in ``analysis/invariants.py`` checks
that every arena slot is exactly one of free / resident / in-flight and
that in-flight flags stay in lockstep with the engine's staged-prefetch
records.  Every entry additionally carries a :func:`block_checksum`
integrity record computed when the bytes enter the tier and re-verified
whenever they leave it (promotion staging, cross-replica export/import)
— a host-DRAM bit flip is detected at the exit point, the corrupt entry
is dropped, and the chain recomputes from tokens instead of serving
corrupt KV (docs/reliability.md).

**NVMe third tier** (ZeRO-Infinity's HBM↔DRAM↔NVMe ladder, serving
edition): :class:`NvmeBlockStore` is a fixed-slot spill FILE below the
host arena, driven by ``ops/aio.py`` (``async_pwrite`` spill /
``async_pread`` load, batched per chain with per-op status).  A
:class:`HostBlockStore` built with an attached NVMe store spills its LRU
tail past a high watermark instead of discarding it, adding a FOURTH
residency state — *spilled*: the entry's ``chain_key`` + ``checksum``
stay in the host-side table but its bytes live only in the spill file.
Spilled entries still answer :meth:`HostBlockStore.probe_run` (a
returning session's prefix survives arbitrary idle), and
:meth:`HostBlockStore.promote_spilled` moves them back into arena slots
before staging — verifying the checksum at the NVMe exit exactly like
every arena exit, so an NVMe bit flip (or a failed read, surfaced per-op
by ``AsyncIOHandle.wait_statuses``) drops the entry and recomputes
instead of serving stale or corrupt staging bytes.  Residency stays
exclusive across all three tiers: a key is device-resident, arena-
resident/in-flight, or spilled — never two at once (the
``residency-conservation`` audit covers the ladder end to end).

**Tensor parallelism**: everything in this module is per-host and
head-sharding-invariant.  Block ids, refcounts, and trie keys index
PHYSICAL BLOCKS (position spans), never attention heads — when the
serving engine shards the device pool over the KV-head dim
(``NamedSharding(mesh, P(None, None, "tp"))``), every chip holds the same
``num_blocks`` blocks, just a head slice of each, and the SAME block
table drives every shard's scatter/gather.  Allocator and trie state
therefore needs no replication, synchronization, or tp-aware branching:
one host-side instance is correct at any tp degree, and scheduling
decisions (admission, eviction, preemption) are bit-identical across
topologies.
"""

from __future__ import annotations

import dataclasses
import hashlib
import zlib
from collections import OrderedDict, deque
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..ops.aio import AsyncIOHandle, swap_chain_read, swap_chain_write

#: physical block 0 is never allocated; discarded writes are routed there
SCRATCH_BLOCK = 0


class TransportError(RuntimeError):
    """A KV transport operation (demote / promote / cross-replica
    export / import) failed.  ``transient=True`` means the caller may
    retry with backoff; ``transient=False`` is a permanent fault — the
    caller must fall back to local recompute (the contents are always
    recomputable from tokens, just not for free).  Raised by the
    fault-injection harness (``serving/faults.py``) in CPU-sim and by a
    real RPC/RDMA fabric in a multi-host deployment."""

    def __init__(self, op: str, transient: bool = True,
                 detail: str = ""):
        super().__init__(
            f"KV transport '{op}' failed "
            f"({'transient' if transient else 'permanent'})"
            + (f": {detail}" if detail else ""))
        self.op = op
        self.transient = bool(transient)


def block_checksum(block_arrays: Sequence[np.ndarray]) -> int:
    """Integrity checksum of one KV block's per-leaf byte content
    (crc32 chained across leaves — xxhash-style speed from zlib's C
    loop, no new dependency).  Computed when bytes enter the host tier
    and re-verified whenever they leave it (promotion staging,
    cross-replica export/import), so a bit flip in host DRAM is caught
    BEFORE the corrupt KV can reach a device pool — corrupt chains are
    dropped and recomputed, never served (docs/reliability.md)."""
    c = 0
    for a in block_arrays:
        c = zlib.crc32(np.ascontiguousarray(a).tobytes(), c)
    return c & 0xFFFFFFFF


#: chain keys are fixed-width blake2b digests; 16 bytes keeps the alias
#: probability below 2^-64 even across billions of cached blocks
CHAIN_KEY_BYTES = 16

#: seed digest for block 0 of every chain (the "empty prefix" state)
_CHAIN_SEED = b"\x00" * CHAIN_KEY_BYTES


def chain_key(tokens, block_index: int, block_size: int) -> bytes:
    """Content address of the ``block_index``-th KV block of a sequence:
    a rolling blake2b digest chained over every token from position 0
    through the end of that block.  Cumulative on purpose — KV at a
    position attends over the whole prefix, so two blocks hold identical
    KV iff their full leading token chains match, and each key stands
    alone (a host-resident run is probed block-by-block with no parent
    pointers).

    Keys are a FIXED :data:`CHAIN_KEY_BYTES` bytes regardless of chain
    depth.  Earlier builds used the raw int32 byte string of the whole
    leading chain, which grew without bound (block ``i``'s key was
    ``4 * block_size * (i + 1)`` bytes — quadratic total at 128k-token
    contexts) and made key handling depend on chain position; the rolling
    digest keeps the prefix-dependence property (``h_i = H(h_{i-1} ||
    tokens of block i)``) with O(1) keys.  MIGRATION: host/NVMe stores
    persisted by a pre-digest build hold raw-chain keys that will never
    match — drop such stores (entries are caches; chains recompute from
    tokens) rather than carrying them across the format change."""
    return chain_keys(tokens, int(block_index) + 1, block_size)[-1]


def chain_keys(tokens, n_blocks: int, block_size: int) -> List[bytes]:
    """:func:`chain_key` for blocks ``0..n_blocks-1`` in one pass:
    serialize the tokens once and roll the digest forward block by block
    — O(len) total.  Byte-for-byte equal to per-block :func:`chain_key`
    calls (pinned by a tier-1 test)."""
    bs = int(block_size)
    n = int(n_blocks) * bs
    buf = np.ascontiguousarray(np.asarray(tokens[:n], np.int32)).tobytes()
    keys: List[bytes] = []
    h = _CHAIN_SEED
    for i in range(int(n_blocks)):
        h = hashlib.blake2b(h + buf[4 * bs * i:4 * bs * (i + 1)],
                            digest_size=CHAIN_KEY_BYTES).digest()
        keys.append(h)
    return keys


class BlockAllocator:
    """Refcounted free-list allocator over ``num_blocks`` KV blocks.

    Block ids are ``1 .. num_blocks-1`` (:data:`SCRATCH_BLOCK` is reserved).
    ``alloc`` hands out a block with refcount 1; sharing (prefix reuse, the
    prefix cache's own hold) goes through ``incref``/``decref``; a block
    returns to the free list when its count reaches zero.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (1 scratch + 1 usable), got "
                f"{num_blocks}")
        self.num_blocks = int(num_blocks)
        self._free = deque(range(1, num_blocks))
        self._ref = [0] * num_blocks
        #: bumped on every alloc/incref/decref — anything derived from
        #: refcounts (free counts, prefix-cache evictability) is stale iff
        #: this moved, which lets the scheduler memoize its admission gate
        #: while the queue head is blocked
        self.version = 0

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        """Blocks currently held by at least one owner (excludes scratch)."""
        return self.num_blocks - 1 - len(self._free)

    def refcount(self, block: int) -> int:
        return self._ref[block]

    def snapshot(self):
        """(refcounts copy, free-list copy) for the invariant checker
        (``analysis/invariants.py``) — read-only view of allocator state."""
        return list(self._ref), list(self._free)

    def alloc(self) -> Optional[int]:
        """A fresh block with refcount 1, or ``None`` when the pool is dry
        (the caller then evicts from the prefix cache / preempts)."""
        if not self._free:
            return None
        b = self._free.popleft()
        assert self._ref[b] == 0, f"block {b} on free list with refs"
        self._ref[b] = 1
        self.version += 1
        return b

    def incref(self, block: int) -> None:
        assert self._ref[block] > 0, f"incref on unowned block {block}"
        self._ref[block] += 1
        self.version += 1

    def decref(self, block: int) -> None:
        assert self._ref[block] > 0, f"decref on unowned block {block}"
        self._ref[block] -= 1
        self.version += 1
        if self._ref[block] == 0:
            self._free.append(block)


class GroupedBlockAllocator:
    """:class:`BlockAllocator` partitioned into ``groups`` contiguous
    spans of ``num_blocks // groups`` physical blocks — one span per dp
    shard of a ``dp_tp``-mode pool (``inference/serving.py``).

    Global block ids stay the currency everywhere (tables, audits,
    telemetry); internally each group runs its own refcounted free list
    over local ids and allocation is group-scoped, so a sequence's blocks
    all land inside its dp shard's pool chunk.  Each group's local block 0
    (global ``g * group_size``) is that group's scratch and is never
    handed out; global block 0 doubles as the table-wide "unset" sentinel,
    exactly as in the flat allocator.
    """

    def __init__(self, num_blocks: int, groups: int):
        if groups < 1:
            raise ValueError(f"groups must be >= 1, got {groups}")
        if num_blocks % groups:
            raise ValueError(
                f"num_blocks ({num_blocks}) must divide evenly over "
                f"{groups} groups")
        self.num_blocks = int(num_blocks)
        self.groups = int(groups)
        self.group_size = self.num_blocks // self.groups
        if self.group_size < 2:
            raise ValueError(
                f"{num_blocks} blocks over {groups} groups leaves "
                f"{self.group_size} per group — need >= 2 (scratch + 1)")
        self._groups = [BlockAllocator(self.group_size)
                        for _ in range(self.groups)]

    @property
    def version(self) -> int:
        return sum(g.version for g in self._groups)

    @property
    def free_blocks(self) -> int:
        return sum(g.free_blocks for g in self._groups)

    @property
    def blocks_in_use(self) -> int:
        """Blocks held by at least one owner (excludes every group's
        scratch)."""
        return self.num_blocks - self.groups - self.free_blocks

    def group_of(self, block: int) -> int:
        return int(block) // self.group_size

    def group_free(self, group: int) -> int:
        """Free blocks remaining in ``group`` (admission placement)."""
        return self._groups[group].free_blocks

    def refcount(self, block: int) -> int:
        g, l = divmod(int(block), self.group_size)
        return self._groups[g].refcount(l)

    def snapshot(self):
        """Merged global-id view, same shape as
        :meth:`BlockAllocator.snapshot`: (refcounts list indexed by global
        id, free list of global ids)."""
        refs: List[int] = []
        free: List[int] = []
        for g, alloc in enumerate(self._groups):
            r, f = alloc.snapshot()
            refs.extend(r)
            free.extend(g * self.group_size + l for l in f)
        return refs, free

    def alloc(self, group: int = 0) -> Optional[int]:
        """A fresh block from ``group`` (global id), or ``None`` when that
        group's span is dry — capacity pressure is per-group by design."""
        local = self._groups[group].alloc()
        return None if local is None else group * self.group_size + local

    def incref(self, block: int) -> None:
        g, l = divmod(int(block), self.group_size)
        self._groups[g].incref(l)

    def decref(self, block: int) -> None:
        g, l = divmod(int(block), self.group_size)
        self._groups[g].decref(l)


@dataclasses.dataclass
class _PrefixEntry:
    uid: int                    # stable id for child keys (never reused)
    key: tuple                  # (parent uid | 0, token tuple)
    block: int                  # physical block holding this token span's KV
    parent: Optional["_PrefixEntry"]
    children: int = 0


class PrefixCache:
    """Token-trie over FULL KV blocks: chained ``(parent, tokens)`` keys.

    ``lookup`` walks a prompt block by block and claims (increfs) the
    longest cached block-aligned prefix; ``register`` inserts a freshly
    prefilled prompt's full blocks, with the cache itself holding one
    reference so the blocks outlive the sequence.  ``evict_one`` releases
    the least-recently-used *leaf* entry whose block only the cache still
    holds — parents are only evictable once all their children are gone, so
    every cached chain stays walkable from the root.
    """

    def __init__(self, block_size: int):
        self.block_size = int(block_size)
        self._entries: "OrderedDict[tuple, _PrefixEntry]" = OrderedDict()
        self._next_uid = 1
        # counters for ServingEngine.stats()
        self.lookups = 0
        self.hit_blocks = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def entries(self):
        """The live trie entries (insertion/LRU order) — read-only view for
        the invariant checker (``analysis/invariants.py``)."""
        return list(self._entries.values())

    def probe(self, tokens: Sequence[int], max_tokens: int) -> int:
        """Number of leading full blocks of ``tokens[:max_tokens]`` present
        in the trie — no refcounts touched (admission-gate peek).
        Exactly ``len(chain_blocks(...))`` so the admission gate and the
        router/export peek can never walk the trie differently."""
        return len(self.chain_blocks(tokens, max_tokens))

    def chain_blocks(self, tokens: Sequence[int],
                     max_tokens: int) -> List[int]:
        """Physical block ids of the cached leading chain of
        ``tokens[:max_tokens]`` — :meth:`probe`'s block-id twin: no
        refcounts touched and no LRU recency (a router/export peek must
        not perturb eviction order)."""
        bs = self.block_size
        parent_uid, out = 0, []
        for i in range(min(len(tokens), int(max_tokens)) // bs):
            e = self._entries.get(
                (parent_uid, tuple(int(t) for t in
                                   tokens[i * bs:(i + 1) * bs])))
            if e is None:
                break
            parent_uid = e.uid
            out.append(int(e.block))
        return out

    def lookup(self, tokens: Sequence[int], max_tokens: int,
               allocator: BlockAllocator) -> List[int]:
        """Claim the longest cached block-aligned prefix of
        ``tokens[:max_tokens]``: increfs and returns the physical block ids
        (the caller owns one reference per returned block)."""
        bs = self.block_size
        blocks: List[int] = []
        parent_uid = 0
        self.lookups += 1
        for i in range(min(len(tokens), max_tokens) // bs):
            key = (parent_uid,
                   tuple(int(t) for t in tokens[i * bs:(i + 1) * bs]))
            e = self._entries.get(key)
            if e is None:
                break
            self._entries.move_to_end(key)      # LRU touch
            allocator.incref(e.block)
            blocks.append(e.block)
            parent_uid = e.uid
        self.hit_blocks += len(blocks)
        return blocks

    def register(self, tokens: Sequence[int], blocks: Sequence[int],
                 allocator: BlockAllocator, start: int = 0) -> None:
        """Insert the chain ``tokens[(start+i)*bs:(start+i+1)*bs] ->
        blocks[i]``.  Existing entries win (the first prefill of a shared
        prompt is the canonical copy; a duplicate block simply isn't cached
        and frees with its sequence) — the chain continues through them
        either way.  ``start > 0`` (tiered-KV promotion) grafts the chain
        onto the entries for blocks ``0..start-1``, which must already be
        live — the caller just claimed them via :meth:`lookup`."""
        bs = self.block_size
        parent: Optional[_PrefixEntry] = None
        for i in range(start):
            parent = self._entries[
                ((parent.uid if parent else 0),
                 tuple(int(t) for t in tokens[i * bs:(i + 1) * bs]))]
        for i, b in enumerate(blocks, start=start):
            key = ((parent.uid if parent else 0),
                   tuple(int(t) for t in tokens[i * bs:(i + 1) * bs]))
            e = self._entries.get(key)
            if e is None:
                e = _PrefixEntry(uid=self._next_uid, key=key, block=int(b),
                                 parent=parent)
                self._next_uid += 1
                allocator.incref(int(b))
                if parent is not None:
                    parent.children += 1
                self._entries[key] = e
            self._entries.move_to_end(key)
            parent = e

    def evictable(self, allocator: BlockAllocator) -> int:
        """Blocks reclaimable by repeated :meth:`evict_one` calls.  A block
        whose refcount is exactly 1 is held only by the cache; any live
        sequence using a child of an entry also holds the parent's block
        (prefix chains are claimed whole), so refcount-1 entries always
        drain leaf-first."""
        return sum(1 for e in self._entries.values()
                   if allocator.refcount(e.block) == 1)

    def evict_one(self, allocator: BlockAllocator) -> Optional[int]:
        """Release the LRU leaf entry only the cache still holds; returns
        the freed block id (truthy — block 0 is scratch and never cached)
        or ``None``.  The id lets the caller retire per-block side state in
        lockstep with the free (the serving engine's int8-KV scale ledger,
        ``serving.py``)."""
        for e in self.evictable_leaves(allocator, 1):
            self.evict_entry(e, allocator)
            return int(e.block)
        return None

    def evictable_leaves(self, allocator: BlockAllocator,
                         limit: int) -> List[_PrefixEntry]:
        """Up to ``limit`` LRU-first leaf entries whose block only the
        cache still holds — the next eviction victims, exposed as a batch
        so the tiered-KV engine can demote their contents to host DRAM in
        ONE device round trip before releasing them."""
        out: List[_PrefixEntry] = []
        for e in self._entries.values():        # oldest first
            if e.children == 0 and allocator.refcount(e.block) == 1:
                out.append(e)
                if len(out) >= limit:
                    break
        return out

    def evict_entry(self, entry: _PrefixEntry,
                    allocator: BlockAllocator) -> None:
        """Release one specific (evictable-leaf) entry — the targeted twin
        of :meth:`evict_one` for batch demotion."""
        assert entry.children == 0 and \
            allocator.refcount(entry.block) == 1, \
            f"evict_entry on a non-evictable entry uid={entry.uid}"
        del self._entries[entry.key]
        if entry.parent is not None:
            entry.parent.children -= 1
        allocator.decref(entry.block)
        self.evictions += 1

    def chain_tokens(self, entry: _PrefixEntry) -> Tuple[int, ...]:
        """The FULL leading token chain of an entry (root span through the
        entry's own span) — exactly the tokens :func:`chain_key` hashes,
        recovered by walking the parent links."""
        spans = []
        e: Optional[_PrefixEntry] = entry
        while e is not None:
            spans.append(e.key[1])
            e = e.parent
        out: List[int] = []
        for span in reversed(spans):
            out.extend(span)
        return tuple(out)


@dataclasses.dataclass
class _NvmeEntry:
    key: bytes                  # chain_key of the block's content
    slot: int                   # file slot (byte offset = slot * nbytes)
    checksum: int = 0           # block_checksum of the spilled bytes


class NvmeBlockStore:
    """NVMe spill file below the host arena — the third rung of the
    serving KV ladder (module docstring "NVMe third tier").

    A fixed-slot file of ``num_blocks`` whole-KV-block records (slot
    ``i`` at byte offset ``i * block_nbytes``) driven by
    :class:`~deepspeed_tpu.ops.aio.AsyncIOHandle` — batched chain writes
    on spill, batched chain reads on load, each op's success surfaced
    individually (``wait_statuses``) so one failed read drops exactly
    one entry.  Entries keep the block's :func:`chain_key` and
    :func:`block_checksum`; :meth:`swap_in` re-hashes the bytes read
    back and refuses a mismatch — the NVMe exit is gated exactly like
    every arena exit.  LRU within the tier: spilling onto a full store
    discards the oldest spilled entry (the coldest bytes in the whole
    ladder — recomputable from tokens, just not for free)."""

    def __init__(self, num_blocks: int,
                 block_specs: Sequence[Tuple[tuple, object]],
                 path: str, *, io_threads: int = 4):
        if num_blocks < 1:
            raise ValueError(
                f"nvme tier num_blocks must be >= 1, got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self.path = str(path)
        self._specs: List[Tuple[tuple, np.dtype]] = [
            (tuple(shape), np.dtype(dtype)) for shape, dtype in block_specs]
        self._leaf_nbytes = [
            int(np.prod(shape, dtype=np.int64)) * dt.itemsize
            for shape, dt in self._specs]
        self.block_nbytes = int(sum(self._leaf_nbytes))
        self._io = AsyncIOHandle(num_threads=int(io_threads))
        self._free = deque(range(self.num_blocks))
        self._entries: "OrderedDict[bytes, _NvmeEntry]" = OrderedDict()
        # counters (the engine folds these into stats()/metrics)
        self.spills = 0
        self.loads = 0
        self.evictions = 0
        self.write_failures = 0
        self.read_failures = 0
        self.checksum_rejects = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def blocks_in_use(self) -> int:
        return self.num_blocks - len(self._free)

    def has(self, key: bytes) -> bool:
        return key in self._entries

    def touch(self, key: bytes) -> None:
        self._entries.move_to_end(key)

    def checksum_of(self, key: bytes) -> int:
        return self._entries[key].checksum

    def pop(self, key: bytes) -> None:
        """Release an entry (its bytes now live in a tier above, or are
        being discarded): the file slot frees for reuse."""
        e = self._entries.pop(key)
        self._free.append(e.slot)

    def nvme_snapshot(self):
        """(free-list copy, ``{key: slot}``) for the residency audit."""
        return list(self._free), {
            k: e.slot for k, e in self._entries.items()}

    # ------------------------------------------------------- serialization
    def _flatten(self, block_arrays: Sequence[np.ndarray]) -> np.ndarray:
        buf = np.empty(self.block_nbytes, np.uint8)
        off = 0
        for (shape, dt), arr, n in zip(self._specs, block_arrays,
                                       self._leaf_nbytes):
            flat = np.ascontiguousarray(arr, dtype=dt).reshape(-1)
            buf[off:off + n] = flat.view(np.uint8)
            off += n
        return buf

    def _unflatten(self, buf: np.ndarray) -> List[np.ndarray]:
        out: List[np.ndarray] = []
        off = 0
        for (shape, dt), n in zip(self._specs, self._leaf_nbytes):
            out.append(buf[off:off + n].view(dt).reshape(shape))
            off += n
        return out

    # ---------------------------------------------------------- transfers
    def swap_out(self, key: bytes, block_arrays: Sequence[np.ndarray],
                 checksum: int) -> bool:
        """Spill one block's bytes to the file under ``key``; ``False``
        when the write failed (the caller discards the block instead —
        never trust a slot whose write may not have landed).  A duplicate
        key keeps the existing record (content-addressed: same key, same
        bytes) and refreshes recency."""
        if key in self._entries:
            self._entries.move_to_end(key)
            return True
        if not self._free:
            old_key, old = next(iter(self._entries.items()))
            del self._entries[old_key]
            self._free.append(old.slot)
            self.evictions += 1
        slot = self._free.popleft()
        ok = swap_chain_write(self._io, self.path, [self._flatten(
            block_arrays)], [slot * self.block_nbytes])[0]
        if not ok:
            self._free.append(slot)
            self.write_failures += 1
            return False
        self._entries[key] = _NvmeEntry(key=key, slot=slot,
                                        checksum=int(checksum))
        self.spills += 1
        return True

    def swap_in(self, key: bytes) -> Optional[List[np.ndarray]]:
        """Read one spilled block's bytes back; verifies the per-op aio
        status AND the stored checksum at this NVMe exit.  On success the
        per-leaf arrays return and the entry REMAINS (the caller pops it
        once the bytes land in the tier above); on a failed read or a
        checksum mismatch the entry is dropped — the chain truncates
        there and recomputes from tokens."""
        e = self._entries[key]
        buf = np.empty(self.block_nbytes, np.uint8)
        ok = swap_chain_read(self._io, self.path, [buf],
                             [e.slot * self.block_nbytes])[0]
        if not ok:
            self.read_failures += 1
            self.pop(key)
            return None
        arrays = self._unflatten(buf)
        if block_checksum(arrays) != e.checksum:
            self.checksum_rejects += 1
            self.pop(key)
            return None
        self.loads += 1
        return arrays

    def close(self) -> None:
        self._io.close()


@dataclasses.dataclass
class _HostEntry:
    key: bytes                  # chain_key of the block's content
    slot: int                   # arena slot holding the block's bytes
    in_flight: bool = False     # promotion staged (device_put issued)
    checksum: int = 0           # block_checksum of the stored bytes


class HostBlockStore:
    """Host-DRAM tier below the device block pool (tiered KV).

    A numpy arena of ``num_blocks`` whole-KV-block slots per pool leaf
    (module docstring "Tiered KV") with a free list and an LRU entry
    table keyed by :func:`chain_key`.  The serving engine demotes cold
    blocks here instead of discarding their contents (prefix-cache
    eviction, preemption) and promotes them back when an admitted
    sequence's chain probes resident — the transfer machinery itself
    (fixed-shape gather/scatter programs, ``device_get``/``device_put``)
    lives in ``ops/paged_kv.py`` / ``serving.py``; this class is pure
    host bookkeeping plus the arena bytes.

    ``block_specs`` gives one ``(per_block_shape, dtype)`` per flattened
    pool leaf — a quantized pool's codes and scale rows are separate
    leaves, so they demote/promote together by construction.

    Entry states: *resident* (bytes live in the arena, slot owned),
    *in-flight* (a staged promotion — the engine has issued the H2D
    ``device_put`` but not yet scattered into the pool), or — with an
    attached :class:`NvmeBlockStore` — *spilled* (bytes live only in the
    spill file; the key + checksum stay discoverable).  In-flight
    entries are never LRU-evicted (the staged transfer would read freed
    bytes) and are released either by :meth:`pop` (promotion landed) or
    :meth:`mark_in_flight(key, False)`` (stale prefetch discarded).

    ``nvme`` + ``nvme_watermark``: past the watermark (a fraction of
    ``num_blocks``), :meth:`put` spills the arena's LRU tail to the NVMe
    store instead of discarding it — demotion past a FULL arena likewise
    spills the LRU victim.  :meth:`promote_spilled` is the way back up.
    """

    def __init__(self, num_blocks: int,
                 block_specs: Sequence[Tuple[tuple, object]],
                 *, nvme: Optional[NvmeBlockStore] = None,
                 nvme_watermark: float = 1.0):
        if num_blocks < 1:
            raise ValueError(
                f"host tier num_blocks must be >= 1, got {num_blocks}")
        if not (0.0 < float(nvme_watermark) <= 1.0):
            raise ValueError(
                f"nvme_watermark must be in (0, 1], got {nvme_watermark}")
        self._nvme = nvme
        #: arena occupancy above which put() spills LRU entries down
        self._hi_blocks = max(1, int(float(nvme_watermark)
                                     * int(num_blocks)))
        self.num_blocks = int(num_blocks)
        self.arenas: List[np.ndarray] = [
            np.zeros((self.num_blocks,) + tuple(shape), dtype)
            for shape, dtype in block_specs]
        self.block_nbytes = int(sum(a[0].nbytes for a in self.arenas))
        self._free = deque(range(self.num_blocks))
        self._entries: "OrderedDict[bytes, _HostEntry]" = OrderedDict()
        # counters for ServingEngine.stats()
        self.evictions = 0
        #: blocks refused by :meth:`import_chain`'s checksum gate (the
        #: engine folds deltas into serving_checksum_failures_total)
        self.checksum_rejects = 0
        #: bumped whenever the resident KEY SET changes (put/pop/LRU
        #: eviction) — probe results are stale iff this moved, which lets
        #: the engine memoize empty prefetch probes across idle
        #: iterations (same trick as BlockAllocator.version)
        self.version = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def blocks_in_use(self) -> int:
        return self.num_blocks - len(self._free)

    @property
    def arena_bytes(self) -> int:
        return int(sum(a.nbytes for a in self.arenas))

    def snapshot(self):
        """(free-list copy, ``{key: (slot, in_flight)}``) for the
        residency-conservation audit (``analysis/invariants.py``)."""
        return list(self._free), {
            k: (e.slot, e.in_flight) for k, e in self._entries.items()}

    def has(self, key: bytes) -> bool:
        """Tier membership: arena-resident, in-flight, OR spilled — the
        key's bytes are somewhere in the host/NVMe ladder and a demotion
        of the same content would be redundant."""
        return key in self._entries or (
            self._nvme is not None and self._nvme.has(key))

    def is_spilled(self, key: bytes) -> bool:
        """True when the key's bytes live only in the NVMe spill file
        (they must :meth:`promote_spilled` before staging can read
        them)."""
        return key not in self._entries and \
            self._nvme is not None and self._nvme.has(key)

    def promote_spilled(self, keys: Sequence[bytes]) -> int:
        """Load the spilled entries of a probed run back into arena
        slots (NVMe → arena, the ladder's way up); returns the length of
        the leading run that is arena-resident afterwards.  Each load is
        verified at the NVMe exit (per-op aio status + checksum —
        :meth:`NvmeBlockStore.swap_in`); a failed or corrupt load drops
        that entry and truncates the run there, exactly like a failed
        arena-exit verify.  Loading may itself spill OTHER cold arena
        entries past the watermark (``put`` path) — never the run being
        promoted, whose entries are the arena's newest: the promoted
        count is capped at the watermark budget, because one more load
        would start re-spilling this very run's head (thrash).  Longer
        chains promote incrementally — the engine pops staged entries to
        the device as it goes, freeing budget for the tail.

        In-flight entries are subtracted from the budget: they occupy
        arena slots but can never spill, so ``_spill_to_watermark``
        skips them and would otherwise re-spill this run's own head the
        moment promoted-count + pinned-count crossed the watermark."""
        n = 0
        if self._nvme is not None:
            pinned = sum(1 for e in self._entries.values()
                         if e.in_flight)
            budget = max(0, self._hi_blocks - pinned)
        else:
            budget = self.num_blocks
        for key in keys:
            if n >= budget:
                break
            if key in self._entries:
                self._entries.move_to_end(key)
                n += 1
                continue
            if self._nvme is None or not self._nvme.has(key):
                break
            checksum = self._nvme.checksum_of(key)
            arrays = self._nvme.swap_in(key)
            if arrays is None:
                # dropped at the NVMe exit gate — probe results naming
                # this key are stale now
                self.version += 1
                break
            if self.put(key, arrays, checksum=checksum) is None:
                break       # arena saturated in-flight; entry stays spilled
            n += 1
        return n

    # ------------------------------------------------- nvme tier surface
    @property
    def nvme_blocks(self) -> int:
        return 0 if self._nvme is None else self._nvme.num_blocks

    @property
    def nvme_blocks_in_use(self) -> int:
        return 0 if self._nvme is None else self._nvme.blocks_in_use

    @property
    def nvme_spills(self) -> int:
        return 0 if self._nvme is None else self._nvme.spills

    @property
    def nvme_loads(self) -> int:
        return 0 if self._nvme is None else self._nvme.loads

    @property
    def nvme_evictions(self) -> int:
        return 0 if self._nvme is None else self._nvme.evictions

    @property
    def nvme_read_failures(self) -> int:
        return 0 if self._nvme is None else self._nvme.read_failures

    @property
    def nvme_write_failures(self) -> int:
        return 0 if self._nvme is None else self._nvme.write_failures

    @property
    def nvme_checksum_rejects(self) -> int:
        return 0 if self._nvme is None else self._nvme.checksum_rejects

    def nvme_snapshot(self):
        """(file free-list copy, ``{key: slot}``) — empty without an
        attached NVMe store; the residency audit checks spilled/resident
        exclusivity and file-slot conservation against it."""
        if self._nvme is None:
            return [], {}
        return self._nvme.nvme_snapshot()

    def put(self, key: bytes,
            block_arrays: Sequence[np.ndarray],
            checksum: Optional[int] = None) -> Optional[int]:
        """Store one demoted block's per-leaf arrays under ``key``;
        returns the arena slot, or ``None`` when every slot is pinned by
        in-flight entries (the caller then simply drops the demotion —
        the block's contents are recomputable, just not for free).  A
        duplicate key keeps the existing copy (first-writer-wins, same
        dedup rule as the trie) and refreshes its recency.  ``checksum``
        pins the entry's integrity record (cross-replica import reuses
        the exporter's sum); ``None`` computes it from the bytes."""
        if key in self._entries:
            self._entries.move_to_end(key)
            if self._nvme is not None and self._nvme.has(key):
                # exclusive residency: the arena copy wins (same bytes —
                # content-addressed), the file slot frees
                self._nvme.pop(key)
            return self._entries[key].slot
        if not self._free:
            if not self._evict_oldest(spill=self._nvme is not None):
                return None
        slot = self._free.popleft()
        for arena, arr in zip(self.arenas, block_arrays):
            arena[slot] = arr
        self._entries[key] = _HostEntry(
            key=key, slot=slot,
            checksum=(block_checksum(block_arrays)
                      if checksum is None else int(checksum)))
        if self._nvme is not None and self._nvme.has(key):
            self._nvme.pop(key)
        self.version += 1
        self._spill_to_watermark()
        return slot

    def _evict_oldest(self, spill: bool) -> bool:
        """LRU-evict the oldest non-in-flight entry; with ``spill`` its
        bytes move DOWN the ladder (``NvmeBlockStore.swap_out`` keeps
        key + checksum) instead of being discarded.  ``False`` when every
        entry is pinned in-flight."""
        for k, e in self._entries.items():  # oldest first
            if e.in_flight:
                continue
            if spill and self._nvme is not None:
                # a failed write counts on the nvme store and the block
                # simply discards — recomputable, never trusted half-spilled
                self._nvme.swap_out(
                    k, [arena[e.slot] for arena in self.arenas],
                    e.checksum)
            del self._entries[k]
            self._free.append(e.slot)
            self.evictions += 1
            self.version += 1
            return True
        return False

    def _spill_to_watermark(self) -> None:
        """Demote the LRU tail to NVMe until arena occupancy is back at
        the high watermark (no-op without an attached NVMe store)."""
        if self._nvme is None:
            return
        while self.blocks_in_use > self._hi_blocks:
            if not self._evict_oldest(spill=True):
                break

    def read(self, key: bytes) -> List[np.ndarray]:
        """Per-leaf views of a resident block's bytes (no copy)."""
        e = self._entries[key]
        return [arena[e.slot] for arena in self.arenas]

    def pop(self, key: bytes) -> None:
        """Release a block (promotion landed on device): the slot frees,
        the entry dies — residency moves back to the device tier."""
        e = self._entries.pop(key)
        self._free.append(e.slot)
        self.version += 1

    def mark_in_flight(self, key: bytes, flag: bool = True) -> None:
        self._entries[key].in_flight = bool(flag)

    def checksum_of(self, key: bytes) -> int:
        """The integrity record stored when the block entered the tier."""
        return self._entries[key].checksum

    def verify(self, key: bytes) -> bool:
        """Recompute the resident bytes' checksum against the stored
        record — ``False`` means the arena bytes were corrupted after the
        store (host-DRAM bit flip).  O(block bytes); called at the points
        bytes LEAVE the arena (promotion staging, export), never on the
        per-iteration probe path."""
        e = self._entries[key]
        return block_checksum([arena[e.slot] for arena in self.arenas]) \
            == e.checksum

    def drop_corrupt(self, key: bytes) -> None:
        """Discard an entry whose bytes failed :meth:`verify`: the slot
        frees and the chain truncates here — the contents recompute from
        tokens on the next admission (corrupt KV is never served)."""
        e = self._entries.pop(key)
        self._free.append(e.slot)
        self.version += 1

    def export_chain(self, keys: Sequence[bytes]) -> List[List[np.ndarray]]:
        """Per-block, per-leaf byte COPIES of resident blocks — the
        cross-replica KV-pull wire format: a snapshot, so later LRU
        eviction or promotion on THIS store cannot tear the exported
        bytes mid-transfer.  Quantized pools' int8 codes and scale rows
        are separate leaves of the same block, so they export together
        by construction."""
        return [[np.array(a) for a in self.read(k)] for k in keys]

    def export_checksums(self, keys: Sequence[bytes]) -> List[int]:
        """The stored integrity records for an exported chain — travels
        beside :meth:`export_chain`'s bytes so the importer can verify
        the transfer end-to-end (``import_chain``)."""
        return [self.checksum_of(k) for k in keys]

    def import_chain(self, keys: Sequence[bytes],
                     blocks: Sequence[Sequence[np.ndarray]],
                     checksums: Optional[Sequence[int]] = None) -> int:
        """Store an exported chain (same order as :meth:`export_chain`);
        stops at the first refused ``put`` (arena saturated with
        in-flight entries) so the imported run stays contiguous — a
        holed chain would be unreachable past the hole anyway
        (``probe_run`` walks contiguously).  With ``checksums`` (the
        exporter's :meth:`export_checksums`), every block's bytes are
        re-hashed on arrival and a mismatch STOPS the import there —
        bytes corrupted in the exporter's arena or in transit never
        enter this tier (``checksum_rejects`` counts them; the engine
        surfaces the total as ``serving_checksum_failures_total``).
        Returns blocks stored."""
        n = 0
        sums = list(checksums) if checksums is not None else None
        for i, (key, arrs) in enumerate(zip(keys, blocks)):
            want = sums[i] if sums is not None else None
            if want is not None and block_checksum(arrs) != int(want):
                self.checksum_rejects += 1
                break
            if self.put(key, arrs, checksum=want) is None:
                break
            n += 1
        return n

    def probe_run(self, tokens, start_block: int, max_tokens: int,
                  block_size: int) -> List[bytes]:
        """Keys of the longest host-resident run of full blocks
        ``start_block, start_block+1, ...`` of ``tokens[:max_tokens]`` —
        the continuation probe admission uses after the device trie's own
        hits end.  Spilled entries count as resident — their bytes are
        still in the ladder (``promote_spilled`` brings them up before
        staging).  No state is touched beyond LRU recency."""
        keys: List[bytes] = []
        n = min(len(tokens), int(max_tokens)) // int(block_size)
        if n <= int(start_block):
            return keys
        run = chain_keys(tokens, n, block_size)
        for i in range(int(start_block), n):
            key = run[i]
            if key in self._entries:
                self._entries.move_to_end(key)
            elif self._nvme is not None and self._nvme.has(key):
                self._nvme.touch(key)
            else:
                break
            keys.append(key)
        return keys
