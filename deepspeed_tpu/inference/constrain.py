"""Constrained decoding: host-side incremental logit-mask builders.

The serving engine's constrained lane (``ServingEngine(logit_masks=True)``)
threads ONE fixed-shape ``[slots, vocab]`` bool operand through the same
compiled decode/fused/verify programs everything else uses — a slot
switching between free and constrained decoding only changes operand
*values*, never program shapes (zero recompiles).  The mask itself is
built HERE, on the host, once per scheduler iteration: the engine calls
``mask_builder.allowed(generated_tokens, remaining_budget)`` for every
constrained slot and scatters the returned allow-vector into the operand
row (``ServingEngine._refresh_masks``).  On device the mask is applied
as ``-inf`` *before* temperature/top-k/top-p, so the slot samples (or
argmaxes, at ``temperature == 0``) from the renormalized allowed set.

The protocol is deliberately tiny — anything with an ``allowed(tokens,
remaining) -> bool[vocab]`` method plugs in (regex automata, grammar
tables, tool-call schemas).  :class:`JsonMaskBuilder` is the shipped
reference: a character-level valid-JSON-prefix machine with budget-aware
closing, strong enough to *guarantee* every constrained request's output
parses as JSON:

- a token is allowed iff appending its characters keeps the text a valid
  prefix of a JSON value AND the minimal number of closing characters
  still fits in the remaining token budget (so the stream can always
  finish inside ``max_new_tokens``);
- once the value is complete, ONLY eos is allowed — generation ends at
  a parseable document, never trailing garbage.

By induction the allowed set is never empty before completion: the
closing characters themselves always qualify (each strictly decreases
the minimal-completion count).  The budget arithmetic assumes closing
characters are emittable one per token — true whenever the vocabulary
maps the single JSON punctuation characters to single tokens, which the
char-level tokenization this builder targets does by construction.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

__all__ = ["LogitMaskBuilder", "JsonMaskBuilder", "ascii_token_strings"]


class LogitMaskBuilder:
    """Protocol for ``Request.mask_builder`` objects (duck-typed — the
    engine never isinstance-checks): one method, called once per
    scheduler iteration per constrained slot."""

    def allowed(self, tokens: Sequence[int],
                remaining: int) -> np.ndarray:
        """Bool ``[vocab]`` allow-vector given the tokens generated so
        far (resume-folded: preempted/re-homed requests see their full
        generated stream) and the remaining token budget (including the
        token this mask gates)."""
        raise NotImplementedError


def ascii_token_strings(vocab_size: int) -> List[str]:
    """The char-level token table the toy serving models imply: token id
    ``i`` renders as ``chr(i)`` for printable ASCII, empty (= never
    allowed by mask builders) otherwise."""
    return [chr(i) if 32 <= i < 127 else "" for i in range(vocab_size)]


# _JsonPrefix stack frames (top = end of list).  Each frame's CLOSE cost
# is the minimal characters to discharge it: the per-frame costs sum to
# the minimal completion length because continuation frames stay on the
# stack (e.g. a key string's '"' is 1 here; the ':' + value + '}' it
# leads to are billed to the OBJ_COLON frame beneath it).
_CLOSE_COST = {
    "VAL": 1,                  # minimal value: a single digit
    "STR_VAL": 1,              # closing '"'
    "STR_KEY": 1,
    "NUM": 0,                  # already a complete integer
    "NUM-": 1,                 # bare '-': one digit
    "OBJ_KEY_OR_CLOSE": 1,     # '}'
    "OBJ_COLON": 3,            # ':' + minimal value + '}'
    "OBJ_COMMA_OR_CLOSE": 1,   # '}'
    "OBJ_KEY": 5,              # '""' + ':' + minimal value + '}'
    "ARR_FIRST": 1,            # ']'
    "ARR_COMMA_OR_CLOSE": 1,   # ']'
}
_STRING_CHARS = frozenset(
    chr(c) for c in range(32, 127) if chr(c) not in ('"', "\\"))
_LIT_STARTS = {"t": "rue", "f": "alse", "n": "ull"}


class _JsonPrefix:
    """Incremental valid-JSON-prefix machine over the grammar subset
    {object, array, string-without-escapes, integer, true, false, null}.
    ``feed`` returns False on the first character that cannot extend any
    valid JSON value (state is then undefined); ``min_close`` is the
    minimal completion length in characters."""

    __slots__ = ("stack",)

    def __init__(self, stack: Optional[List[str]] = None):
        self.stack = ["VAL"] if stack is None else stack

    def copy(self) -> "_JsonPrefix":
        return _JsonPrefix(list(self.stack))

    @property
    def done(self) -> bool:
        return not self.stack

    def min_close(self) -> int:
        return sum(len(f) - 4 if f.startswith("LIT:") else _CLOSE_COST[f]
                   for f in self.stack)

    def feed(self, ch: str) -> bool:
        stack = self.stack
        while True:
            if not stack:
                return False               # complete value: no trailing chars
            top = stack[-1]
            if top == "VAL":
                stack.pop()
                if ch == "{":
                    stack.append("OBJ_KEY_OR_CLOSE")
                elif ch == "[":
                    stack.append("ARR_FIRST")
                elif ch == '"':
                    stack.append("STR_VAL")
                elif ch.isdigit():
                    if ch != "0":          # JSON bans leading zeros:
                        stack.append("NUM")  # "0" is a complete integer
                elif ch == "-":
                    stack.append("NUM-")
                elif ch in _LIT_STARTS:
                    stack.append("LIT:" + _LIT_STARTS[ch])
                else:
                    return False
                return True
            if top in ("STR_VAL", "STR_KEY"):
                if ch == '"':
                    stack.pop()
                    return True
                return ch in _STRING_CHARS
            if top == "NUM":
                if ch.isdigit():
                    return True
                stack.pop()                # number ends; reprocess ch
                continue
            if top == "NUM-":
                if ch.isdigit():
                    if ch == "0":
                        stack.pop()        # "-0" is a complete integer
                    else:
                        stack[-1] = "NUM"
                    return True
                return False
            if top.startswith("LIT:"):
                rest = top[4:]
                if ch != rest[0]:
                    return False
                if len(rest) == 1:
                    stack.pop()
                else:
                    stack[-1] = "LIT:" + rest[1:]
                return True
            if top == "OBJ_KEY_OR_CLOSE":
                if ch == "}":
                    stack.pop()
                    return True
                if ch == '"':
                    stack[-1] = "OBJ_COLON"
                    stack.append("STR_KEY")
                    return True
                return False
            if top == "OBJ_COLON":
                if ch == ":":
                    stack[-1] = "OBJ_COMMA_OR_CLOSE"
                    stack.append("VAL")
                    return True
                return False
            if top == "OBJ_COMMA_OR_CLOSE":
                if ch == "}":
                    stack.pop()
                    return True
                if ch == ",":
                    stack[-1] = "OBJ_KEY"
                    return True
                return False
            if top == "OBJ_KEY":
                if ch == '"':
                    stack[-1] = "OBJ_COLON"
                    stack.append("STR_KEY")
                    return True
                return False
            if top == "ARR_FIRST":
                if ch == "]":
                    stack.pop()
                    return True
                stack[-1] = "ARR_COMMA_OR_CLOSE"
                stack.append("VAL")
                continue                   # reprocess ch as a value start
            if top == "ARR_COMMA_OR_CLOSE":
                if ch == "]":
                    stack.pop()
                    return True
                if ch == ",":
                    stack.append("VAL")
                    return True
                return False
            raise AssertionError(f"unknown frame {top!r}")


class JsonMaskBuilder(LogitMaskBuilder):
    """Budget-aware valid-JSON mask builder over a char-level token
    table (``token_strings[i]`` is token ``i``'s text; empty strings are
    never allowed).  Incremental: consecutive ``allowed`` calls over a
    growing token stream feed only the new tokens through the prefix
    machine, so per-iteration cost is O(vocab × max_token_chars)."""

    def __init__(self, token_strings: Sequence[str], eos_token_id: int):
        self.tokens = [str(t) for t in token_strings]
        self.vocab = len(self.tokens)
        self.eos = int(eos_token_id)
        if not 0 <= self.eos < self.vocab:
            raise ValueError(
                f"eos_token_id {eos_token_id} outside vocab "
                f"[0, {self.vocab})")
        self._seen: List[int] = []
        self._machine = _JsonPrefix()

    def _advance(self, tokens: Sequence[int]) -> _JsonPrefix:
        toks = [int(t) for t in tokens]
        if toks[:len(self._seen)] != self._seen:
            self._seen, self._machine = [], _JsonPrefix()  # resume/rewind
        for t in toks[len(self._seen):]:
            if t == self.eos:
                break                      # eos ends the stream
            for ch in self.tokens[t]:
                if not self._machine.feed(ch):
                    raise ValueError(
                        f"generated token {t} ({self.tokens[t]!r}) broke "
                        "the JSON prefix — the mask lane must gate every "
                        "emission of a constrained request")
        self._seen = toks
        return self._machine

    def allowed(self, tokens: Sequence[int],
                remaining: int) -> np.ndarray:
        machine = self._advance(tokens)
        mask = np.zeros(self.vocab, bool)
        if machine.done:
            mask[self.eos] = True          # complete document: stop
            return mask
        budget_chars = max(int(remaining) - 1, 0)
        for t, text in enumerate(self.tokens):
            if t == self.eos or not text:
                continue
            m = machine.copy()
            if all(m.feed(ch) for ch in text) \
                    and m.min_close() <= budget_chars:
                mask[t] = True
        return mask
