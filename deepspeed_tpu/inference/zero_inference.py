"""ZeRO-Inference analog: serve models bigger than device HBM.

Reference parity: ZeRO-Inference (zero stage-3 ``offload_param: cpu``
driving inference-only forwards; the OPT-30B-on-one-V100 configuration in
BASELINE.md, driven by ``benchmarks/inference/gpt-bench.py``).  The
reference keeps full weights in CPU DRAM and streams each layer's
partition to the GPU as its forward runs, amortizing the traffic with
large batches.

TPU design: the stacked transformer blocks stay HOST-resident (numpy —
int8 records when ``quant`` is on, so the wire and DRAM footprint is
~1 byte/param) and stream through HBM one layer at a time.  Unlike the
training-side ZeRO-Infinity param streamer (runtime/zero/param_stream.py,
an in-jit ``io_callback`` custom_vjp), the serving loop runs OUTSIDE jit:
a python loop dispatches one jitted per-layer step per block and issues
the next layer's ``device_put`` while the current layer computes (JAX
dispatch is async — transfers overlap compute naturally).  That keeps
the whole model's KV cache device-resident with static shapes, needs no
host callbacks inside traced code (which tunneled dev backends cannot
run), and makes the HBM high-water mark ``pinned layers + ~2 streamed
layers + caches``.

Throughput model (why big batches): a decode step must move every
streamed layer's bytes over the host link, so
``tokens/sec ~= batch * link_GB_s / streamed_GB``.  The reference's 43
tok/s OPT-30B number is the same arithmetic on PCIe with fp16 weights;
int8 records halve the streamed bytes.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import log_dist

PyTree = Any


def _split_layers(blocks: PyTree, num_layers: int) -> List[PyTree]:
    """[L, ...]-stacked host subtree -> per-layer subtrees (numpy views,
    zero-copy)."""
    return [
        jax.tree_util.tree_map(lambda a: a[i], blocks)
        for i in range(num_layers)
    ]


class StreamedGenerator:
    """Per-layer streamed prefill + decode over host-resident blocks.

    Built by :class:`~deepspeed_tpu.inference.engine.InferenceEngine` when
    ``zero_inference.enabled``; mirrors the resident engine's ``generate``
    semantics (greedy/sampling, eos fill, fold_in seeding) so the two
    paths are token-compatible at the same weights.
    """

    def __init__(self, *, resident_params, host_blocks, num_layers: int,
                 stream_hooks: Dict[str, Any], init_cache, cache_dtype,
                 pin_layers: int = 0, prefetch: int = 1, sync_every: int = 1,
                 picker_factory=None):
        self.resident = resident_params
        self.num_layers = num_layers
        self.hooks = stream_hooks
        self._init_cache = init_cache
        self.cache_dtype = cache_dtype
        self.prefetch = max(1, int(prefetch))
        self.sync_every = max(1, int(sync_every))
        self._picker_factory = picker_factory
        self.host_layers = _split_layers(host_blocks, num_layers)
        self.pin_layers = min(max(0, int(pin_layers)), num_layers)
        # pinned prefix lives in HBM permanently
        self._pinned = [jax.device_put(self.host_layers[i])
                        for i in range(self.pin_layers)]
        #: bytes that cross the host->device link per full layer pass
        self.streamed_bytes = sum(
            leaf.nbytes for i in range(self.pin_layers, num_layers)
            for leaf in jax.tree_util.tree_leaves(self.host_layers[i]))
        #: per-phase wall clock of the LAST completed generate (the
        #: model_times analog for the streamed path)
        self.last_timings = {"prefill_s": None, "decode_step_s": []}
        log_dist(
            f"zero-inference: {num_layers} layers, {self.pin_layers} "
            f"pinned, {self.streamed_bytes / 2**30:.2f} GiB streamed per "
            f"step", ranks=[0])
        self._embed_j = jax.jit(self.hooks["embed"])
        self._block_j = jax.jit(self.hooks["block"])
        self._head_j = jax.jit(self.hooks["head"])
        self._pickers: Dict[Any, Any] = {}

    # ------------------------------------------------------------------ layers
    def _layer_stream(self):
        """Yield device-resident per-layer weight trees, prefetching
        ``prefetch`` transfers ahead of compute."""
        window: List[Any] = []
        nxt = self.pin_layers
        for i in range(self.pin_layers):
            yield self._pinned[i]
        while nxt < self.num_layers or window:
            while nxt < self.num_layers and len(window) < self.prefetch + 1:
                window.append(jax.device_put(self.host_layers[nxt]))
                nxt += 1
            if window:
                yield window.pop(0)

    def _sync(self, x):
        # bound in-flight work: fetch one element (block_until_ready
        # no-ops on tunneled dev backends, a value fetch does not)
        jax.device_get(jax.tree_util.tree_leaves(x)[0].ravel()[0])

    def _run_layers(self, x, caches, pos):
        """One full pass over all layers (prefill T=prompt or decode T=1)."""
        for i, layer in enumerate(self._layer_stream()):
            x, ck, cv = self._block_j(layer, x, caches[i][0], caches[i][1],
                                      pos)
            caches[i] = (ck, cv)
            if (i + 1) % self.sync_every == 0 and i >= self.pin_layers:
                self._sync(x)
        return x

    def _make_caches(self, b: int, cache_len: int):
        """Per-layer device caches from the model's stacked init_cache
        spec (allocated unstacked so no [L, ...] double-buffer exists)."""
        spec = jax.eval_shape(
            lambda: self._init_cache(b, cache_len, self.cache_dtype))
        k, v = spec["k"], spec["v"]
        return [(jnp.zeros(k.shape[1:], k.dtype),
                 jnp.zeros(v.shape[1:], v.dtype))
                for _ in range(self.num_layers)]

    # ---------------------------------------------------------------- generate
    def generate(self, input_ids, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0,
                 seed: Optional[int] = None):
        input_ids = np.asarray(input_ids)
        b, prompt_len = input_ids.shape
        total = prompt_len + max_new_tokens
        cache_len = -(-total // 128) * 128
        from .engine import _auto_seed, _fill_after_eos

        sample_cfg = (do_sample, float(temperature), int(top_k),
                      float(top_p)) if do_sample else None
        if sample_cfg not in self._pickers:
            self._pickers[sample_cfg] = jax.jit(
                self._picker_factory(sample_cfg))
        pick = self._pickers[sample_cfg]
        rng = jax.random.PRNGKey(_auto_seed(self, seed))

        if max_new_tokens <= 0:
            # resident path clamps silently; mirror it without streaming
            # (int32 output + cleared timings, like a real streamed call)
            self.last_timings = {"prefill_s": None, "decode_step_s": []}
            return np.array(input_ids, dtype=np.int32)
        # reset BEFORE streaming so a mid-prefill failure can't leave a
        # previous run's timings looking current
        self.last_timings = {"prefill_s": None, "decode_step_s": []}
        caches = self._make_caches(b, cache_len)
        out = np.zeros((b, total), np.int32)
        out[:, :prompt_len] = input_ids

        # positions are TRACED args (jnp scalars): one jit trace serves
        # prefill (T=prompt) and one serves every decode step
        zero = jnp.asarray(0, jnp.int32)
        # prefill: one streamed pass over the whole prompt
        t0 = time.perf_counter()
        x = self._embed_j(self.resident, jnp.asarray(input_ids), zero)
        x = self._run_layers(x, caches, zero)
        logits = self._head_j(self.resident, x[:, -1])
        tok = pick(logits, jax.random.fold_in(rng, prompt_len))
        out[:, prompt_len] = np.asarray(tok)
        # the per-token np.asarray sync makes each entry meaningful
        self.last_timings["prefill_s"] = time.perf_counter() - t0

        for pos in range(prompt_len, total - 1):
            t0 = time.perf_counter()
            pos_a = jnp.asarray(pos, jnp.int32)
            x = self._embed_j(self.resident, tok[:, None], pos_a)
            x = self._run_layers(x, caches, pos_a)
            logits = self._head_j(self.resident, x[:, -1])
            tok = pick(logits, jax.random.fold_in(rng, pos + 1))
            out[:, pos + 1] = np.asarray(tok)
            self.last_timings["decode_step_s"].append(
                time.perf_counter() - t0)

        return _fill_after_eos(out, prompt_len, eos_token_id)
