"""Speculative decoding building blocks: draft proposers + greedy
verification (Leviathan et al., "Fast Inference from Transformers via
Speculative Decoding"; vLLM's draft–verify scheduler).

The serving engine's decode steps are memory-bound single-token passes, so
N sequential target-model steps cost N full weight reads.  Speculative
decoding spends one cheap *proposal* (a small draft model, or a model-free
n-gram lookup over the request's own tokens) to guess K tokens, then
scores all K+1 positions in ONE batched target forward (the paged
chunked-prefill T>1 path).  Greedy verification keeps exactly the tokens
the target itself would have produced — the accepted prefix of the draft
plus the target's correction token — so the output stream is token-exact
with plain greedy decode at any acceptance rate.

Two verifiers share the walker: :func:`greedy_accept` (token-exact with
plain greedy decode) and :func:`rejection_accept` (distribution-exact
with plain SAMPLED decode — the engine's rejection-sampling verify
program computes the per-position accept verdicts plus two tail-draw
lanes (plain target draw / residual draw) on device and the walker picks
between them by stop reason; greedy is its ``temperature == 0``
degenerate case).

This module is the host-side, device-free part: the n-gram proposer and
the accept/rollback arithmetic.  Device wiring (the draft-model K-step
program, the K+1 verify program, block accounting) lives in
``inference/serving.py``; the KV-layout properties that make rollback free
are documented in ``ops/paged_kv.py``.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


def greedy_accept(window: Sequence[int], scored: Sequence[int],
                  max_accept: int, eos_token_id: Optional[int],
                  budget: int) -> Tuple[List[int], int, bool]:
    """Greedy draft verification for one sequence.

    window: the K+1 tokens fed to the verify pass — ``[pending, d_1 ..
            d_K]`` (the pending token is the last committed-but-unfed
            output token; ``d_i`` are draft proposals).
    scored: the target's greedy argmax at each window position —
            ``scored[i]`` is the target's next token after ``window[:i+1]``
            plus the committed history.
    max_accept: cap on accepted drafts.  ``K`` for model-free proposers;
            ``K - 1`` when a draft KV cache must stay position-aligned (the
            K-th draft's KV was never written, so accepting it would leave
            a hole at the draft's next feed position).
    eos_token_id / budget: emission stops at the first eos or when
            ``budget`` (the request's remaining ``max_new_tokens``) runs
            out — eos *inside* an accepted window truncates it.

    Returns ``(emitted, accepted, finished)``: the tokens to append to the
    request's output this round, the number of accepted draft tokens
    actually EMITTED — eos/budget truncation caps it, so the cache
    commit never advances over draft matches past the stopping point
    (where ``scored`` may even be scratch-routed garbage: positions
    past the request's block budget never allocate) — (cache-commit
    advance is ``accepted + 1``: the pending token plus the accepted
    drafts; when not finished, ``emitted[-1]`` is the new pending token —
    the target's correction, whose KV is not yet written), and whether the
    request is done.  Every emitted token equals what plain greedy decode
    would produce, by construction: token ``i`` of the round is the
    target's argmax given the identical committed prefix.
    """
    if budget < 1:
        raise ValueError(f"budget must be >= 1, got {budget}")
    k1 = len(window)
    if len(scored) != k1:
        raise ValueError(f"scored has {len(scored)} entries for a "
                         f"{k1}-token window")
    a = 0
    while a < max_accept and a + 1 < k1 and \
            int(window[a + 1]) == int(scored[a]):
        a += 1
    candidate = [int(t) for t in window[1:a + 1]] + [int(scored[a])]
    emitted: List[int] = []
    finished = False
    for tok in candidate:
        emitted.append(tok)
        if (eos_token_id is not None and tok == eos_token_id) or \
                len(emitted) >= budget:
            finished = True
            break
    return emitted, min(a, len(emitted)), finished


def rejection_accept(window: Sequence[int], accept: Sequence[bool],
                     plain: Sequence[int], resid: Sequence[int],
                     max_accept: int, eos_token_id: Optional[int],
                     budget: int) -> Tuple[List[int], int, bool]:
    """Distribution-exact draft verification for one sequence (the
    delta-proposal form of Leviathan/Chen rejection sampling).

    Same walker shape and emission semantics as :func:`greedy_accept`,
    but the per-position equality test is replaced by the verify
    program's device-computed verdicts, and the correction token is
    picked from one of two device-drawn lanes BY STOP REASON:

    accept: ``accept[i]`` is the rejection-sampler verdict for draft
            ``d_{i+1}`` — ``u_i < p_target(d_{i+1})`` with ``u_i`` keyed
            to the position's absolute emission index (the proposer is
            treated as a point mass at its proposal, so this marginal is
            exact for ANY proposer — draft model or n-gram — without
            draft probabilities).
    plain:  ``plain[i]`` (``K + 1`` entries) is an unconditional draw
            from the (filtered) target distribution at position ``i`` —
            emitted when the walk stops WITHOUT consuming a rejection:
            the ``max_accept`` cap, or the all-accepted bonus position
            ``K``.
    resid:  ``resid[i]`` (``K`` entries) is a draw from the
            ``d_{i+1}``-zeroed renormalized residual — emitted only when
            the walk stopped because ``accept[i]`` is False.

    The stop reason matters: at a cap stop ``accept[a]`` was never
    consumed, so conditioning the emission on it (e.g. a
    ``where(accept, plain, resid)`` blend) would skew the marginal to
    ``p(x)(1 + q)`` for non-draft tokens and ``q^2`` for the draft
    (``q = p_target(d)``) instead of the target ``p`` — picking the lane
    by stop reason is what keeps every emission exactly
    target-distributed.

    ``temperature == 0`` rows are bit-identical to :func:`greedy_accept`:
    the verify program's one-hot algebra makes ``accept[i]`` the argmax
    equality test and both lanes the argmax itself.

    Returns ``(emitted, accepted, finished)`` with identical semantics
    (and identical eos/budget truncation) to :func:`greedy_accept`.
    """
    if budget < 1:
        raise ValueError(f"budget must be >= 1, got {budget}")
    k1 = len(window)
    if len(plain) != k1:
        raise ValueError(f"plain has {len(plain)} entries for a "
                         f"{k1}-token window")
    if len(resid) != k1 - 1:
        raise ValueError(f"resid has {len(resid)} entries for a "
                         f"{k1}-token window (need K = {k1 - 1})")
    if len(accept) != k1 - 1:
        raise ValueError(f"accept has {len(accept)} verdicts for a "
                         f"{k1}-token window (need K = {k1 - 1})")
    a = 0
    while a < max_accept and a + 1 < k1 and bool(accept[a]):
        a += 1
    # stopped by a rejection (verdict consumed) -> residual resample;
    # stopped by the cap / bonus position (verdict NOT consumed) -> an
    # unconditional fresh draw from the target
    rejected = a < max_accept and a + 1 < k1
    tail = int(resid[a]) if rejected else int(plain[a])
    candidate = [int(t) for t in window[1:a + 1]] + [tail]
    emitted: List[int] = []
    finished = False
    for tok in candidate:
        emitted.append(tok)
        if (eos_token_id is not None and tok == eos_token_id) or \
                len(emitted) >= budget:
            finished = True
            break
    return emitted, min(a, len(emitted)), finished


class NGramProposer:
    """Model-free prompt-lookup drafting (Saxena's prompt-lookup decoding;
    vLLM's ``[ngram]`` speculator): propose the continuation of the most
    recent earlier occurrence of the sequence's current tail n-gram.

    Greedy decoding loves to quote — retrieval answers copy prompt spans,
    code repeats identifiers, and degenerate loops repeat themselves — and
    every quoted span is a free draft: no second model, no extra compiled
    program, no device memory.  Wrong guesses cost nothing but wasted
    verify lanes (verification keeps output token-exact regardless).
    """

    #: compiled programs this proposer adds to the serving trace
    programs = 0

    def __init__(self, k: int, max_n: int = 3, min_n: int = 1):
        if k < 1:
            raise ValueError(f"draft length k must be >= 1, got {k}")
        if min_n < 1 or max_n < min_n:
            raise ValueError(
                f"need 1 <= min_n <= max_n, got min_n={min_n} max_n={max_n}")
        self.k = int(k)
        self.max_n = int(max_n)
        self.min_n = int(min_n)

    def propose(self, ctx) -> np.ndarray:
        """Draft ``k`` tokens continuing ``ctx`` (int array, prompt +
        generated output, most recent last).

        Longest-match-first: try the tail ``max_n``-gram, back off to
        shorter n-grams, and take the MOST RECENT earlier occurrence (the
        repetition most likely to still be live).  With no match anywhere,
        fall back to repeating the final token — never wrong, just
        low-yield draft slots."""
        ctx = np.asarray(ctx, np.int32).reshape(-1)
        m = int(ctx.size)
        if m == 0:
            return np.zeros(self.k, np.int32)
        out = np.full(self.k, int(ctx[-1]), np.int32)
        for n in range(min(self.max_n, m - 1), self.min_n - 1, -1):
            pat = ctx[m - n:]
            wins = np.lib.stride_tricks.sliding_window_view(ctx[:-1], n)
            hits = np.nonzero((wins == pat).all(axis=1))[0]
            if hits.size:
                start = int(hits[-1]) + n
                cont = ctx[start:start + self.k]
                out[:cont.size] = cont
                break
        return out
