"""Inference config (reference ``deepspeed/inference/config.py``).

Same user-facing keys; TPU notes:
 - ``enable_cuda_graph`` is accepted and ignored — ``jit`` *is* the graph capture
   (reference captures CUDA graphs at ``inference/engine.py:479``).
 - ``tensor_parallel.tp_size`` maps to the ``tp`` mesh axis.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from pydantic import Field

from ..runtime.config_utils import DeepSpeedConfigModel


class DeepSpeedTPConfig(DeepSpeedConfigModel):
    """Reference ``inference/config.py:44``."""
    enabled: bool = True
    tp_size: int = 1
    mpu: Optional[Any] = None
    tp_group: Optional[Any] = None


class DeepSpeedMoEConfig(DeepSpeedConfigModel):
    """Reference ``inference/config.py:62``."""
    enabled: bool = True
    ep_size: int = 1
    moe_experts: list = Field(default_factory=lambda: [1])
    type: str = "standard"


class QuantizationConfig(DeepSpeedConfigModel):
    enabled: bool = False
    # 128 = the TPU lane width: groups at lane multiples let the fused
    # dequant-matmul kernel (ops/quantized_matmul) serve the weights
    # straight from int8; other sizes (the reference GroupQuantizer
    # default is 64) are honored via the dequant+matmul path
    group_size: int = 128
    num_bits: int = 8
    # "weight": int8 weight-only storage, bf16 math (default).
    # "w8a8": K-grouped weights + in-kernel activation quantization on the
    # s8 MXU for decode (reference analog: MoQ weight+activation INT8);
    # requires a quant-aware model with stacked blocks.
    type: str = "weight"
    # w8a8 group-size alignment target: quant groups are refined so a
    # row-parallel K shard over this many devices never splits a group
    # (ops/quantization.pick_k_group).  None = the engine's tp degree.
    # Pin it (e.g. to the largest tp you'll serve) to get bit-identical
    # weight records across tp degrees.
    shard_multiple: Optional[int] = None


class ZeroInferenceConfig(DeepSpeedConfigModel):
    """ZeRO-Inference analog (reference: zero stage-3 ``offload_param`` to
    CPU driving inference-only forwards — the OPT-30B-on-one-GPU
    configuration of BASELINE.md): the stacked transformer blocks stay
    HOST-resident and stream through HBM one layer at a time during
    prefill/decode, so the servable model size is bounded by host DRAM,
    not device HBM.  Large batches amortize the per-step weight traffic
    (the reference's throughput recipe).  See
    inference/zero_inference.py."""
    enabled: bool = False
    #: first N layers stay device-resident (use spare HBM to cut traffic)
    pin_layers: int = 0
    #: host->device transfers issued ahead of compute (double buffering)
    prefetch: int = 1
    #: dispatch-throttle period, in layers: every N layers the host waits
    #: on a 1-element activation fetch so in-flight transfers stay
    #: bounded instead of racing the whole model into HBM
    sync_every: int = 1


class InferenceCheckpointConfig(DeepSpeedConfigModel):
    checkpoint_dir: Optional[str] = None
    save_mp_checkpoint_path: Optional[str] = None
    base_dir: Optional[str] = None


class DeepSpeedInferenceConfig(DeepSpeedConfigModel):
    """Reference ``inference/config.py:123`` key set."""
    kernel_inject: bool = Field(False, alias="replace_with_kernel_inject")
    dtype: str = "bfloat16"
    tensor_parallel: DeepSpeedTPConfig = Field(
        default_factory=DeepSpeedTPConfig, alias="tp")
    enable_cuda_graph: bool = False
    zero: Dict[str, Any] = Field(default_factory=dict)
    triangular_masking: bool = True
    moe: DeepSpeedMoEConfig = Field(default_factory=DeepSpeedMoEConfig)
    quant: QuantizationConfig = Field(default_factory=QuantizationConfig)
    zero_inference: ZeroInferenceConfig = Field(
        default_factory=ZeroInferenceConfig)
    checkpoint: Optional[Any] = None
    base_dir: str = ""
    max_tokens: int = Field(1024, alias="max_out_tokens")
    min_out_tokens: int = Field(1, alias="min_tokens")
    replace_method: str = "auto"
    injection_policy: Optional[Dict] = Field(None, alias="injection_dict")
    return_tuple: bool = True
    training_mp_size: int = 1
    max_batch_size: int = Field(1, alias="max_out_batch")
    # Ulysses-style sequence parallelism: size of the mesh ``sp`` axis.
    # Prefill shards the prompt over it (ops/sp_attention); the reference
    # has no analog (pre-Ulysses) — TPU-native extension.
    sequence_parallel: int = Field(1, alias="sp")

    @property
    def jnp_dtype(self):
        import jax.numpy as jnp

        return {
            "float16": jnp.float16, "fp16": jnp.float16, "half": jnp.float16,
            "bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
            "float32": jnp.float32, "fp32": jnp.float32, "float": jnp.float32,
            "int8": jnp.int8,
        }[str(self.dtype).replace("torch.", "")]
