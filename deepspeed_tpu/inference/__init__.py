from .serving import Request, ServingEngine, default_buckets  # noqa: F401
