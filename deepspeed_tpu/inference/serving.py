"""Continuous-batching serving: slot-based KV-cache pool + iteration-level
scheduler.

The one-shot :meth:`InferenceEngine.generate` path holds a whole batch until
its *longest* request finishes and jit-compiles a fresh program for every exact
``(batch, prompt_len, max_new_tokens)`` tuple — Orca's head-of-line-blocking
problem.  This module serves mixed-length traffic the way Orca/vLLM do:

 - **Slot pool**: one statically-shaped KV cache of ``SLOTS`` sequence slots
   (plus one scratch slot that absorbs pad rows), allocated once via the
   model's ``init_cache`` hook.  A finished sequence frees its slot
   *immediately*; the next waiting request is prefilled into it on the
   following iteration.
 - **Iteration-level scheduling**: every engine iteration admits waiting
   requests into free slots (strict FIFO — no starvation), runs one bucketed
   prefill per prompt bucket for the joiners, then one single-token decode
   step over *all* slots.  Each slot carries its own position: the decode
   contract is the per-sequence ``lengths: int32[B]`` vector threaded through
   ``forward_cached`` down to ``ops/decode_attention``.
 - **Bucketed compilation**: prompts are right-padded to a small bucket
   ladder and joiners to a fixed prefill batch, so the whole serving loop
   compiles ``O(#buckets) + 1`` XLA programs regardless of how many request
   shapes the trace contains.  ``compile_count`` / ``compiled_programs`` are
   the probe the tests assert against.

Greedy decoding only: per-request outputs are token-identical to sequential
``generate`` (pinned in ``tests/unit/test_serving.py``).  Sampling needs
per-request RNG lanes and is left to a follow-up.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..utils.logging import log_dist


def default_buckets(max_seq_len: int, lo: int = 32) -> Tuple[int, ...]:
    """Power-of-two prompt-bucket ladder ``[lo, .., max_seq_len]``."""
    buckets = []
    b = lo
    while b < max_seq_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_seq_len)
    return tuple(buckets)


@dataclasses.dataclass
class Request:
    """One serving request: prompt token ids + a completion budget."""
    uid: Any
    prompt: np.ndarray                      # int32 [prompt_len]
    max_new_tokens: int = 32

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError(f"request {self.uid!r}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(f"request {self.uid!r}: max_new_tokens must "
                             "be >= 1")


@dataclasses.dataclass
class _SlotState:
    req: Request
    out: List[int] = dataclasses.field(default_factory=list)


class ServingEngine:
    """Iteration-level (continuous-batching) scheduler over an
    :class:`~deepspeed_tpu.inference.engine.InferenceEngine`'s KV-decode path.

    Parameters
    ----------
    engine:        an ``init_inference`` engine whose model carries
                   ``decode_hooks`` with ``supports_lengths`` (gpt2 / llama /
                   opt / mixtral families).
    slots:         KV-cache pool size = max concurrently-decoding sequences.
    max_seq_len:   per-slot cache length (prompt + completion budget);
                   rounded up to a multiple of 128 for the Pallas block_k,
                   clamped to the model context length.
    prompt_buckets: ascending prompt-length ladder; prompts pad up to the
                   smallest fitting bucket.  Default: powers of two.
    prefill_batch: fixed number of joiner rows per prefill program (shorter
                   groups pad into the scratch slot), so joiner count never
                   forces a recompile.
    """

    def __init__(self, engine, *, slots: int = 8,
                 max_seq_len: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 prefill_batch: int = 4):
        hooks = engine.module.decode_hooks
        if not hooks:
            raise ValueError(
                f"continuous batching needs decode_hooks; model "
                f"{engine.module.name} has none")
        if not hooks.get("supports_lengths"):
            raise ValueError(
                f"model {engine.module.name}'s decode hooks predate "
                "per-sequence lengths (supports_lengths) — update its "
                "forward_cached to the lengths contract first")
        self.engine = engine
        self._fwd = hooks["forward_cached"]
        self._init_cache = hooks["init_cache"]
        max_ctx = hooks.get("max_seq_len")
        if max_seq_len is None:
            max_seq_len = max_ctx or 512
        if max_ctx is not None and max_seq_len > max_ctx:
            raise ValueError(
                f"max_seq_len {max_seq_len} exceeds the model context "
                f"length {max_ctx}")
        self.max_seq_len = int(max_seq_len)
        # the CACHE may be longer than the logical context: round up so the
        # Pallas decode kernel's block_k divides it (same rounding as
        # InferenceEngine._build_kv_cache_gen)
        self._cache_len = -(-self.max_seq_len // 128) * 128
        self.slots = int(slots)
        buckets = tuple(sorted(prompt_buckets)) if prompt_buckets \
            else default_buckets(self.max_seq_len)
        if any(b > self.max_seq_len for b in buckets):
            raise ValueError(
                f"prompt bucket(s) {buckets} exceed max_seq_len "
                f"{self.max_seq_len}")
        self.prompt_buckets = buckets
        self.prefill_batch = int(prefill_batch)
        # slot `slots` is SCRATCH: pad rows of short prefill groups write
        # their (discarded) KV there so every prefill program has a fixed
        # [prefill_batch] shape.  Committed replicated on the engine mesh so
        # the very first step sees the same placement as every later one
        # (an uncommitted pool would cost each program a second trace).
        rep = NamedSharding(engine.mesh, P())
        self._cache = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, rep),
            self._init_cache(self.slots + 1, self._cache_len,
                             engine._config.jnp_dtype))
        self._prefill_fns: Dict[int, Any] = {}
        self._decode_fn = None
        #: compile probe — one entry per traced program; the serving loop
        #: stays at O(#buckets)+1 entries for an entire trace
        self.compiled_programs: List[Any] = []
        # decode stats for the bench
        self.iterations = 0
        self.decode_steps = 0
        self.prefill_calls = 0
        log_dist(
            f"ServingEngine: slots={self.slots}, cache_len="
            f"{self._cache_len}, buckets={self.prompt_buckets}, "
            f"prefill_batch={self.prefill_batch}", ranks=[0])

    # ------------------------------------------------------------ compiled fns
    @property
    def compile_count(self) -> int:
        return len(self.compiled_programs)

    def _donate(self):
        # donating the pool avoids a full cache copy per step; XLA:CPU
        # ignores donation with a warning, so only ask for it on TPU
        return (1,) if jax.default_backend() == "tpu" else ()

    def _get_decode_fn(self):
        if self._decode_fn is None:
            fwd, prepare = self._fwd, self.engine._prepare

            def step(params, cache, tokens, lengths):
                logits, cache = fwd(prepare(params), tokens[:, None], cache,
                                    0, lengths=lengths)
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

            self._decode_fn = jax.jit(step, donate_argnums=self._donate())
            self.compiled_programs.append(("decode", self.slots + 1))
        return self._decode_fn

    def _get_prefill_fn(self, bucket: int):
        if bucket not in self._prefill_fns:
            fwd, prepare = self._fwd, self.engine._prepare
            init_cache = self._init_cache
            dtype = self.engine._config.jnp_dtype

            def prefill(params, cache, ids, slot_idx, lengths):
                """ids [J, bucket] right-padded; slot_idx int32 [J] (pad rows
                point at the scratch slot); lengths int32 [J]."""
                params = prepare(params)
                # fresh slots have no history: prefill into a zeroed
                # bucket-length sub-cache (no pool gather) and scatter only
                # the first ``bucket`` positions of each joiner's slot row.
                # Cache leaves are [L, B, ..., S, hd]: batch dim 1, length
                # dim -2.  Stale KV beyond ``bucket`` from a previous
                # occupant is never read — decode masks by each row's
                # length and overwrites position L before attending it.
                sub = init_cache(ids.shape[0], bucket, dtype)
                logits, sub = fwd(params, ids, sub, 0, lengths=lengths)
                cache = jax.tree_util.tree_map(
                    lambda c, s: c.at[:, slot_idx, ..., :bucket, :].set(
                        s.astype(c.dtype)), cache, sub)
                return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

            self._prefill_fns[bucket] = jax.jit(
                prefill, donate_argnums=self._donate())
            self.compiled_programs.append(("prefill", bucket,
                                           self.prefill_batch))
        return self._prefill_fns[bucket]

    # --------------------------------------------------------------- schedule
    def _bucket_for(self, prompt_len: int) -> int:
        for b in self.prompt_buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt length {prompt_len} exceeds the largest bucket "
            f"{self.prompt_buckets[-1]}")

    def _admit(self, pending, active, admission_log):
        """FIFO admission of waiting requests into free slots.  Returns the
        joiners admitted this iteration as (slot, request) pairs."""
        joiners = []
        free = [s for s in range(self.slots) if s not in active]
        while pending and free:
            req = pending.popleft()
            slot = free.pop(0)
            active[slot] = _SlotState(req)
            joiners.append((slot, req))
            admission_log.append((req.uid, slot))
        return joiners

    def serve(self, requests: Sequence[Request],
              eos_token_id: Optional[int] = None,
              admission_log: Optional[list] = None) -> Dict[Any, np.ndarray]:
        """Run a request trace to completion; returns ``uid -> [prompt +
        completion]`` int32 arrays, padded to ``prompt + max_new_tokens``
        with eos back-fill (HF semantics, same as ``generate``).

        ``admission_log``, when given, collects ``(uid, slot)`` in admission
        order — the scheduler-determinism tests read it.
        """
        for r in requests:
            total = len(r.prompt) + r.max_new_tokens
            if total > self.max_seq_len:
                raise ValueError(
                    f"request {r.uid!r}: prompt ({len(r.prompt)}) + "
                    f"max_new_tokens ({r.max_new_tokens}) = {total} exceeds "
                    f"max_seq_len {self.max_seq_len}")
            self._bucket_for(len(r.prompt))  # raises if no bucket fits
        uids = [r.uid for r in requests]
        if len(set(uids)) != len(uids):
            raise ValueError("duplicate request uids")

        params = self.engine.params
        pending = deque(requests)
        active: Dict[int, _SlotState] = {}
        if admission_log is None:
            admission_log = []
        results: Dict[Any, np.ndarray] = {}
        # host-side mirrors of the device step inputs: the token each slot
        # feeds next, and how many tokens its cache already holds
        tokens = np.zeros(self.slots + 1, np.int32)
        lengths = np.zeros(self.slots + 1, np.int32)

        def finish(slot):
            st = active.pop(slot)
            req = st.req
            out = np.full(req.max_new_tokens, 0, np.int32)
            gen = np.asarray(st.out, np.int32)
            out[:gen.size] = gen
            if eos_token_id is not None and gen.size and \
                    gen[-1] == eos_token_id:
                out[gen.size:] = eos_token_id  # back-fill (HF semantics)
            results[req.uid] = np.concatenate([req.prompt, out])
            tokens[slot] = 0
            lengths[slot] = 0

        while pending or active:
            self.iterations += 1
            joiners = self._admit(pending, active, admission_log)

            # bucketed prefill, fixed-J groups per bucket
            by_bucket: Dict[int, list] = {}
            for slot, req in joiners:
                by_bucket.setdefault(self._bucket_for(len(req.prompt)),
                                     []).append((slot, req))
            for bucket in sorted(by_bucket):
                group = by_bucket[bucket]
                for i in range(0, len(group), self.prefill_batch):
                    chunk = group[i:i + self.prefill_batch]
                    first = self._run_prefill(bucket, chunk, params)
                    self.prefill_calls += 1
                    for row, (slot, req) in enumerate(chunk):
                        tok = int(first[row])
                        active[slot].out.append(tok)
                        tokens[slot] = tok
                        lengths[slot] = len(req.prompt)
                        if (eos_token_id is not None
                                and tok == eos_token_id) \
                                or req.max_new_tokens <= 1:
                            finish(slot)

            # one decode step over every slot (per-sequence positions)
            if active:
                nxt, self._cache = self._get_decode_fn()(
                    params, self._cache, jnp.asarray(tokens),
                    jnp.asarray(lengths))
                nxt = np.asarray(nxt)
                self.decode_steps += 1
                for slot in sorted(active):
                    st = active[slot]
                    lengths[slot] += 1       # the fed token is now cached
                    tok = int(nxt[slot])
                    st.out.append(tok)
                    if (eos_token_id is not None and tok == eos_token_id) \
                            or len(st.out) >= st.req.max_new_tokens:
                        finish(slot)
                    else:
                        tokens[slot] = tok
        return results

    def _run_prefill(self, bucket, chunk, params):
        """Prefill one fixed-J group of joiners into their slots; returns
        the first generated token per row (np.int32 [J])."""
        j = self.prefill_batch
        ids = np.zeros((j, bucket), np.int32)
        slot_idx = np.full(j, self.slots, np.int32)      # pad -> scratch
        lens = np.ones(j, np.int32)
        for row, (slot, req) in enumerate(chunk):
            plen = len(req.prompt)
            ids[row, :plen] = req.prompt
            slot_idx[row] = slot
            lens[row] = plen
        first, self._cache = self._get_prefill_fn(bucket)(
            params, self._cache, jnp.asarray(ids), jnp.asarray(slot_idx),
            jnp.asarray(lens))
        return np.asarray(first)
