"""Continuous-batching serving: block-paged KV cache, prefix reuse, and
chunked prefill over an iteration-level scheduler.

PR 1's slot pool reserved one contiguous ``max_seq_len`` KV region per slot
and re-ran a full bucketed prefill for every admitted prompt — worst-case
memory per sequence, and shared prompt prefixes (system prompts, few-shot
headers) recomputed on every request.  This engine layers the two highest-
leverage serving optimisations on top of continuous batching:

 - **Block-paged KV pool** (vLLM PagedAttention): one statically-shaped
   ``[L, num_blocks, HKV, block_size, hd]`` cache plus per-slot ``int32``
   block tables mapping each sequence's logical block index (``position //
   block_size``) to a physical block.  Blocks are handed out by a
   refcounted free-list allocator (``inference/paged.py``); physical block
   0 is reserved scratch — pad rows and inactive slots write their
   discarded KV there, so every device program keeps a fixed shape.
   Attention reaches the pool through the table: a gather-based XLA path
   for prefill/CPU and a Pallas kernel that walks the table in-kernel via
   scalar prefetch for TPU decode (``ops/decode_attention.py``).
 - **Prefix cache** (SGLang RadixAttention at block granularity): a token
   trie over *full* blocks.  A new request whose prompt shares a
   block-aligned prefix with any previously prefilled sequence reuses
   those physical blocks with zero recompute — only the tail is prefilled.
   Reuse is capped below the full prompt (>= 1 tail token always runs) and
   is full-block only, so a sequence's next write always lands in a
   privately owned block: shared blocks are read-only, no copy-on-write.
   When the allocator runs dry, least-recently-used cache entries are
   evicted first; if that is not enough, the *latest-admitted* sequence is
   preempted — its blocks are freed and it re-enters the queue front with
   its generated tokens folded into the prompt (greedy decoding makes the
   recompute token-exact, and its re-prefill usually hits its own cached
   blocks).
 - **Chunked prefill**: prompts advance through the cache in fixed-size
   windows (``prefill_chunk`` tokens, ``prefill_batch`` sequences per
   call) interleaved with decode steps, replacing the bucket ladder — the
   whole serving loop compiles exactly **1 prefill + 1 decode program**
   regardless of trace shape.  The bucket ladder survives as a fallback
   (``chunked_prefill=False``, auto-selected when ``prompt_buckets`` is
   passed): per-bucket programs over the same paged pool, no prefix reuse.

Scheduling is iteration-level and strict-FIFO as before: every iteration
admits waiting requests into free slots (gated on block availability —
the queue head blocks admission, no starvation), advances every
prefilling slot by one chunk, then runs one single-token decode step over
all slots with per-sequence positions (``lengths: int32[B]``).
``compile_count`` / ``compiled_programs`` remain the compile probe;
``stats()`` adds prefix-hit, block-occupancy, and preemption counters.

**Speculative decoding** (``spec_tokens=K > 0``, chunked mode only)
replaces the single-token decode step with a draft–verify round
(``inference/spec.py``): a proposer guesses K tokens per decode slot — a
small same-family draft model running K greedy steps in ONE compiled
program over its own paged pool (sharing the target's block tables, so
allocation/preemption/prefix-reuse bookkeeping is written once), or the
model-free n-gram prompt-lookup fallback (zero programs) — and the target
scores the K+1-token window in one fixed-shape paged forward through the
chunked-prefill T>1 path (``all_positions`` verify head).  Greedy
verification commits the longest target-matching draft prefix plus the
target's correction token, so outputs stay token-exact with plain greedy
decode; rejected tokens roll back for free (host lengths stay at the
committed value — stale KV is position-masked and overwritten in place,
blocks stay allocated, refcounts never move).  Block demand past a
request's remaining completion budget is never allocated: those window
positions scatter to the scratch block instead.  The whole trace compiles
at most **3 programs** — prefill (fused target+draft in draft mode), the
draft K-step rollout, and the verify pass.  ``stats()`` adds drafted/
accepted counters and acceptance rate, plus per-request TTFT/TPOT
percentiles (recorded for plain serving too).

**Tensor parallelism** (``shard_kv``, default auto): when the engine's
mesh carries a ``tp`` axis and the model's KV head count divides it, the
paged pool (and the draft pool) is committed sharded over the KV-HEAD dim
— ``NamedSharding(mesh, P(None, None, "tp"))`` on the stacked ``[L, NB,
HKV, bs, hd]`` buffer — so each chip stores ``HKV/tp`` heads (pool
capacity and decode memory bandwidth scale with the tp degree instead of
being replicated).  Every device call runs under ``ops/paged_kv
.tp_context``: the paged scatter/gather/attention ops trace inside
``shard_map`` on their head shard with ZERO per-step KV collectives (the
one tensor-parallel all-reduce stays after the output projection, exactly
like the Megatron matmul path).  Block tables, the allocator, the prefix
trie, and all scheduler state are host-side and head-sharding-invariant —
they index blocks, never heads — so scheduling is bit-identical at any tp
degree and the compile contract is unchanged.  GQA pools whose ``HKV``
does not divide tp fall back to the replicated tp=1 layout (head groups
are shared across chips); ``shard_kv=True`` then raises instead of
silently replicating.

**Quantized serving** (``quantize=``, default off): decode is memory-
bandwidth-bound, and the KV pool plus the weights are the traffic.
``"kv8"`` stores the paged pool (and the speculative draft pool) as int8
with a per-block scale table (``ops/paged_kv.py`` quantized pool
records): the scatter quantizes on write, the gather and all three paged
Pallas kernels dequantize on read, so HBM moves codes + scales — ~2x
servable blocks per chip and ~2x decode KV bandwidth, multiplicative
with the tp head-shard.  ``"w8a8"`` requires the wrapped engine to carry
K-grouped int8 weights (``init_serving(quantize=...)`` wires the config;
``quant: {enabled, type: "w8a8"}``) — decode matmuls then run the s8-MXU
stacked kernels (``ops/quantized_matmul``), while prefill/verify rows
fall back to the exact dequant path.  ``"w8a8+kv8"`` composes both.  The
host side is quant-invariant: allocator, trie, tables, and scheduling
are byte-identical to the float pool (scales ride under block ids), the
≤2/≤3-program compile contracts hold unchanged, and ``quantize=None``
traces the exact pre-quantization programs bit-for-bit.  Greedy parity
becomes a *bounded-divergence* contract on quantized lanes (int8
rounding can flip near-tie argmaxes): ``tests/unit/test_quant_serving.py``
pins token-match-rate and logit-RMSE bounds instead of bit equality.
A host-side ledger tracks which blocks own live scale rows; the
``debug_checks`` audit enforces it (``scale-lockstep`` invariant,
``analysis/invariants.py``).

**Tiered KV cache** (``host_blocks=N``, default off): under block
pressure the engine above threw computed state away — LRU prefix-cache
eviction, then preemption with full greedy recompute.  The tier adds a
host-DRAM arena below the device pool (:class:`~deepspeed_tpu.inference
.paged.HostBlockStore`): eviction and preemption **demote** cold blocks —
one fixed-shape block gather (``ops/paged_kv.paged_block_gather``) plus
ONE ``jax.device_get`` per ``swap_batch``-sized batch — instead of
freeing their contents, and admission of a sequence whose prefix (or
preempted state — generated tokens fold into the resume prompt, so the
same content-addressed chain keys cover both) is host-resident
**promotes** the run back: an async ``jax.device_put`` issued at least
one scheduler iteration ahead (double-buffered prefetch over the pending
queue head, exactly the ``param_stream.py`` overlap trick) so the H2D
transfer hides behind the decode step, then one fixed-shape scatter
(``paged_block_scatter``) commits the staged blocks and the chain
re-registers in the trie.  Promoted bytes are bit-identical to what was
demoted (under ``kv8`` the int8 codes and their scale rows travel as
separate leaves of the same swap tree; with a draft model the draft pool
demotes/promotes alongside the target's so speculative acceptance
survives a swap), so parity contracts are unchanged.  The two swap
programs are fixed-shape and sentry-registered — the compile budget
grows by exactly 2 and transfers can never introduce further programs.
Scheduling stays host-side and sharding/quant-invariant: under tp the
``device_get``/``device_put`` travel per addressable shard of the
head-sharded pool.  The residency state machine (device-free /
device-held / host-resident / in-flight) is audited by
``analysis/invariants.py`` (``residency-conservation``) under
``debug_checks``.

**Telemetry** (``telemetry/``, always on — the registry IS the stats
store): every scheduler counter and the TTFT/TPOT latency distributions
live in a :class:`~deepspeed_tpu.telemetry.MetricsRegistry`
(``engine.metrics`` — Prometheus text + JSON snapshot for scrapes and
bench artifacts), and ``stats()`` is a backward-compatible view over it.
Latencies are fixed-bucket streaming histograms — bounded memory for a
serve session of any length, replacing the old unbounded raw-sample
lists — with a small ``deque`` of recent per-request records kept for
debugging.  A bounded ring of scheduler events (admit, prefill chunk,
decode step, spec propose/verify/accept, prefix hit, block eviction,
preemption, finish, plus the sentry's trace/retrace and the invariant-
audit events from ``analysis/``) records a per-request timeline
(``trace_capacity=``, 0 = off) exportable as Chrome ``trace_event`` JSON
via ``dump_trace(path)`` — open it in Perfetto to see exactly where a
slow request spent its time.  ``serve(profile_dir=...)`` additionally
brackets the first ``profile_iters`` scheduler iterations with a
``jax.profiler`` trace window for device-level deep dives.  PR 12 adds
the per-``slo_class`` attainment accounting behind ``slo_report()``
(``telemetry/slo.py``; ``slo_targets=``), the FLOPs/MFU profiler behind
``flops_report()`` (``telemetry/flops.py``; raw program bodies lowered
for ``cost_analysis`` — zero new compiled programs), and the router's
cross-ring flow linkage (``note_flow`` → admission emits the Chrome
flow finish).  Overhead contract: near-free when idle, ≤2% aggregate
tok/s when fully enabled (pinned by the ``--telemetry-bench``
serving-bench lane, BENCH_r08; re-verified fleet-wide in BENCH_r12).

**Incremental serving API** (PR 11): the scheduler state (pending queue,
active slots) lives on the engine, not inside one ``serve()`` call.
``submit(request, priority=, slo_class=)`` enqueues a request and returns
a :class:`RequestHandle` with per-token streaming (``next_token``,
``tokens``), blocking ``result()``, and ``cancel()``; ``step()`` runs ONE
scheduler iteration (admit → prefill → decode → prefetch → audit) and
returns whether work remains; ``cancel(uid)`` drops a queued request
immediately and releases an active slot (blocks decref'd, ``cancelled``
timeline event) at the next iteration boundary — the only point the
paged-state invariants are guaranteed to hold.  ``drain()`` quiesces the
engine for a replica handoff: every active slot preempts (with the host
tier, committed blocks demote first), the remaining prefix-cache content
demotes, and the whole pending queue is handed back for re-submission
elsewhere (``deepspeed_tpu/serving/`` routes it).  The batch
``serve(list)`` entry point survives as a thin wrapper — submit all,
loop ``step()``, gather results — with byte-identical scheduling, and
tolerates an empty request list without tracing anything.  Admission
stays head-of-queue-gated (no starvation) but the queue is now
priority-ordered: higher ``priority`` (or an ``slo_class`` mapped
through ``SLO_PRIORITY``) admits first; preemption resumes still jump
to the very front regardless of class (they hold admission recency).

Greedy decoding only: per-request outputs are token-identical to
sequential ``generate`` (pinned in ``tests/unit/test_serving.py``,
``tests/unit/test_paged_serving.py``, ``tests/unit/test_spec_decode.py``,
and — across tp degrees — ``tests/unit/test_tp_serving.py``); quantized
lanes are bounded-divergence instead (above).
"""

from __future__ import annotations

import contextlib
import dataclasses
import inspect
import os
import tempfile
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..analysis.concurrency import (LockSanitizer, caller_site,
                                    ordered_condition)
from ..analysis.invariants import audit_serving_engine
from ..analysis.sentry import (RecompileSentry, backend_compiles,
                               install_compile_listener)
from ..ops import decode_attention, paged_kv, sp_attention
from ..ops import sampling as sampling_ops
from ..ops.decode_attention import VERIFY_T_MAX
from ..ops.paged_kv import blocks_for
from ..parallel.topology import DP_AXIS, SP_AXIS, TP_AXIS
from ..telemetry import MetricsRegistry, ProfilerWindow, TraceTimeline
from ..telemetry.slo import SLOTracker
from ..utils.logging import log_dist
from ..utils.lru import LRUCache
from .paged import (SCRATCH_BLOCK, BlockAllocator, GroupedBlockAllocator,
                    HostBlockStore, NvmeBlockStore, PrefixCache,
                    TransportError, chain_key, chain_keys)
from .spec import NGramProposer, greedy_accept, rejection_accept


class RequestFailedError(RuntimeError):
    """A request was permanently failed by the serving fleet: its
    replica crashed and the re-home retry budget was exhausted, or no
    live replica remained to take it (``ReplicaRouter.fail``).  Raised
    by :meth:`RequestHandle.result`; tokens streamed before the failure
    stay readable via :meth:`RequestHandle.tokens`."""

    def __init__(self, uid, reason: str):
        super().__init__(f"request {uid!r} failed: {reason}")
        self.uid = uid
        self.reason = reason


#: legal ``quantize=`` values (order-normalized; ``None`` = full precision)
_QUANT_MODES = ("kv8", "w8a8", "w8a8+kv8")


def _parse_quantize(quantize):
    """Normalize the ``quantize=`` knob -> ``(normalized str | None,
    kv_quant bool, want_w8a8 bool)``; raises naming the legal values."""
    if quantize is None or quantize == "":
        return None, False, False
    parts = sorted(str(quantize).split("+"))
    if not set(parts) <= {"kv8", "w8a8"} or len(set(parts)) != len(parts):
        raise ValueError(
            f"quantize={quantize!r} — expected one of {_QUANT_MODES} "
            "(or None for full precision)")
    norm = "+".join(p for p in ("w8a8", "kv8") if p in parts)
    return norm, "kv8" in parts, "w8a8" in parts


def _validate_decode_hooks(module, *, speculative: bool = False,
                           kv_quant: bool = False, sampling: bool = False,
                           role: str = "model"):
    """Fail fast at engine construction, naming the exact missing hook,
    instead of a TypeError deep inside the first prefill call.  Checks the
    hook dict AND the ``forward_cached`` signature (a family can carry a
    stale flag without the matching kwarg)."""
    hooks = getattr(module, "decode_hooks", None)
    name = getattr(module, "name", "<model>")
    if not hooks:
        raise ValueError(
            f"continuous batching needs decode_hooks; {role} {name} has "
            "none")
    for key in ("init_cache", "forward_cached"):
        if key not in hooks:
            raise ValueError(
                f"{role} {name}: decode_hooks is missing the '{key}' hook")
    if not hooks.get("supports_lengths"):
        raise ValueError(
            f"{role} {name}'s decode hooks predate per-sequence lengths "
            "(supports_lengths) — update its forward_cached to the lengths "
            "contract first")
    if not hooks.get("supports_paged"):
        raise ValueError(
            f"{role} {name}'s decode hooks predate the block-paged cache "
            "(supports_paged) — thread block_tables through its "
            "forward_cached first")
    if speculative and not hooks.get("supports_verify"):
        raise ValueError(
            f"{role} {name}'s decode hooks lack the speculative verify "
            "head (supports_verify) — add all-position logits "
            "(all_positions=True) to its forward_cached first")
    if sampling and not hooks.get("supports_sampling"):
        raise ValueError(
            f"{role} {name}'s decode hooks do not declare sampling "
            "support (supports_sampling) — a family qualifies when its "
            "forward_cached returns full-vocab logits the on-device "
            "sampler can filter; set the flag after verifying that, or "
            "build the engine with sampling=False (greedy-only)")
    if kv_quant and not hooks.get("supports_kv_quant"):
        raise ValueError(
            f"{role} {name}'s decode hooks do not declare int8-KV support "
            "(supports_kv_quant) — a family qualifies when every pool "
            "read/write goes through ops/paged_kv (record-aware); set the "
            "flag after verifying that, or drop quantize='kv8'")
    try:
        sig = inspect.signature(hooks["forward_cached"])
    except (TypeError, ValueError):  # graft: noqa(GL013) predicate: builtins / C callables have no signature — trust flags
        sig = None
    if sig is not None:
        need = ["lengths", "block_tables"] + \
            (["all_positions"] if speculative else [])
        missing = [kw for kw in need if kw not in sig.parameters]
        if missing:
            raise ValueError(
                f"{role} {name}: forward_cached does not accept the "
                f"{missing} keyword(s) its hook flags promise "
                f"(signature: forward_cached{sig})")
    return hooks


def default_buckets(max_seq_len: int, lo: int = 32) -> Tuple[int, ...]:
    """Power-of-two prompt-bucket ladder ``[lo, .., max_seq_len]``.

    Robust to the edges the serving engine can hand it: ``lo`` above
    ``max_seq_len`` clamps to a single ``(max_seq_len,)`` bucket, a
    non-power-of-two ``max_seq_len`` gets exactly one tail entry (no
    duplicates), and ``lo < 1`` raises instead of looping forever."""
    if max_seq_len < 1:
        raise ValueError(f"max_seq_len must be >= 1, got {max_seq_len}")
    if lo < 1:
        raise ValueError(f"bucket floor lo must be >= 1, got {lo}")
    b = min(lo, max_seq_len)
    buckets = []
    while b < max_seq_len:
        buckets.append(b)
        b *= 2
    if not buckets or buckets[-1] != max_seq_len:
        buckets.append(max_seq_len)
    return tuple(buckets)


@dataclasses.dataclass
class Request:
    """One serving request: prompt token ids + a completion budget, plus
    the per-request sampling contract (PR 20).  ``temperature=0`` (the
    default) is greedy — bit-identical to every prior PR — and rides the
    SAME compiled programs as sampled traffic; ``seed`` keys the
    counter-based PRNG (``ops/sampling.py``), so a request's sampled
    stream is a pure function of ``(prompt, params, seed)`` — replayable
    across crash re-homes, preemptions, and fused/plain decode paths.
    ``mask_builder`` (a :class:`~deepspeed_tpu.inference.constrain
    .LogitMaskBuilder`) opens the constrained-decoding lane; it needs an
    engine built with ``logit_masks=True``."""
    uid: Any
    prompt: np.ndarray                      # int32 [prompt_len]
    max_new_tokens: int = 32
    temperature: float = 0.0                # 0 = greedy (the default row)
    top_k: int = 0                          # 0 = off
    top_p: float = 1.0                      # 1 = off
    seed: int = 0                           # counter-based PRNG root
    mask_builder: Optional[Any] = None      # constrained-decoding hook

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError(f"request {self.uid!r}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(f"request {self.uid!r}: max_new_tokens must "
                             "be >= 1")
        if self.temperature < 0:
            raise ValueError(f"request {self.uid!r}: temperature must be "
                             f">= 0 (0 = greedy), got {self.temperature}")
        if self.top_k < 0:
            raise ValueError(f"request {self.uid!r}: top_k must be >= 0 "
                             f"(0 = off), got {self.top_k}")
        if not 0 < self.top_p <= 1:
            raise ValueError(f"request {self.uid!r}: top_p must be in "
                             f"(0, 1] (1 = off), got {self.top_p}")
        # the PRNG root is materialized as np.uint32 at admission —
        # validate here so a bad seed is refused at submit() rather than
        # exploding the scheduler loop mid-trace (NumPy >= 2 raises
        # OverflowError on out-of-range uint32 casts)
        if not 0 <= self.seed < 2 ** 32:
            raise ValueError(f"request {self.uid!r}: seed must be in "
                             f"[0, 2**32), got {self.seed}")

    @property
    def sampled(self) -> bool:
        """True when this request draws from the sampler (any nonzero
        temperature); greedy requests never touch the PRNG streams."""
        return self.temperature > 0


#: ``slo_class`` -> default admission priority (``submit``): an SLO class
#: is a coarse priority band with a stable name — explicit ``priority=``
#: (nonzero) always wins over the class default
SLO_PRIORITY = {"realtime": 2, "interactive": 1, "standard": 0, "batch": -1,
                "giant_context": 0}

#: resident-window serving: leading blocks that stay device-resident and
#: attention-visible forever (attention-sink landmarks — the softmax needs
#: the early positions to stay numerically sane once the middle of the
#: context is masked out).  One block is enough for the sink effect; the
#: knob is a module constant rather than a ctor parameter to keep the
#: config surface at a single ``resident_window_blocks`` dial.
_LANDMARK_BLOCKS = 1


class RequestHandle:
    """Live view of one submitted request (``ServingEngine.submit`` /
    ``ReplicaRouter.submit``): per-token streaming, completion, and
    cancellation.

    The engine side appends committed tokens as the scheduler emits them
    (prefill first token, decode steps, speculative accepts); the caller
    side reads them — ``tokens()`` for everything so far, ``next_token``
    for a streaming cursor (blocking when a worker thread drives the
    engine, ``timeout=0`` when the caller drives ``step()`` itself), and
    ``result()`` for the final padded ``[prompt + completion]`` array
    (``None`` if the request was cancelled).  A preemption keeps the
    handle: already-streamed tokens stand (greedy resume recomputes the
    identical sequence), and fresh tokens continue on the same handle —
    including across a replica drain handoff.  All state transitions run
    under one condition variable, so the handle is safe to read from a
    different thread than the scheduler's.

    Under ``debug_checks`` the engine passes its lock sanitizer
    (``analysis/concurrency.py``) and the condition becomes an
    instrumented ``ordered_condition``: the handle participates in the
    declared fleet lock order (fleet -> replica -> handle), and the
    blocking accessors (``result`` / blocking ``next_token``) raise
    :class:`~deepspeed_tpu.analysis.concurrency.BlockingUnderLockError`
    when entered while the calling thread holds any sanitized lock —
    waiting on a handle under the fleet or a replica lock is a deadlock
    (the scheduler that would finish the request can never run)."""

    def __init__(self, request: Request, *, priority: int = 0,
                 slo_class: Optional[str] = None, canceller=None,
                 lock_sanitizer: Optional[LockSanitizer] = None):
        self.request = request
        self.uid = request.uid
        self.priority = int(priority)
        self.slo_class = slo_class
        # "queued" -> "active" -> "finished" | "cancelled" | "failed"
        # (failed = crash re-homing exhausted; result() raises the
        # recorded RequestFailedError instead of returning)
        self.status = "queued"
        self._tokens: List[int] = []
        self._result: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None
        self._sanitizer = lock_sanitizer
        self._cond = ordered_condition("serving.handle", lock_sanitizer) \
            if lock_sanitizer is not None else threading.Condition()
        self._cursor = 0
        self._canceller = canceller

    # ---- engine-side transitions (scheduler thread)
    def _on_active(self) -> None:
        with self._cond:
            if self.status == "queued":
                self.status = "active"
            self._cond.notify_all()

    def _on_tokens(self, toks) -> None:
        with self._cond:
            self._tokens.extend(int(t) for t in toks)
            self._cond.notify_all()

    def _on_finish(self, result: np.ndarray) -> None:
        with self._cond:
            self._result = result
            self.status = "finished"
            self._cond.notify_all()

    def _on_cancel(self) -> None:
        with self._cond:
            self.status = "cancelled"
            self._cond.notify_all()

    def _on_fail(self, exc: BaseException) -> None:
        """Resolve the handle as permanently failed (crash re-homing
        exhausted) — never downgrades an already-finished request."""
        with self._cond:
            if self.status not in ("finished", "cancelled"):
                self._error = exc
                self.status = "failed"
            self._cond.notify_all()

    def set_canceller(self, canceller) -> None:
        """Rebind the cancel route (router submit / drain handoff) —
        under the handle condition, because a worker may already be
        streaming transitions into this handle when the router rebinds
        it (a bare attribute store is exactly the unguarded-shared-state
        hazard graft-race GL010 flags)."""
        with self._cond:
            self._canceller = canceller

    # ---- caller side
    @property
    def done(self) -> bool:
        return self.status in ("finished", "cancelled", "failed")

    def tokens(self) -> List[int]:
        """Every token committed so far (a copy)."""
        with self._cond:
            return list(self._tokens)

    def cancel(self) -> bool:
        """Cancel via whoever owns the request now (engine or router);
        ``False`` if it already finished."""
        return bool(self._canceller and self._canceller(self.uid))

    def next_token(self, timeout: Optional[float] = None) -> Optional[int]:
        """Streaming cursor: the next committed token, or ``None`` once
        the request is finished/cancelled/failed.  ``timeout=0`` is the
        non-blocking poll for callers driving ``step()`` themselves
        (``None`` then also means "nothing new yet"); a positive
        ``timeout`` that expires with the request still live raises
        ``TimeoutError`` — a lost replica surfaces as a loud, typed
        error at the caller instead of an indefinite hang
        (docs/reliability.md).  ``timeout=None`` blocks until a token
        arrives or the request resolves."""
        if self._sanitizer is not None and timeout != 0:
            self._sanitizer.check_wait(
                f"RequestHandle.next_token(uid={self.uid!r})",
                site=caller_site(2))
        with self._cond:
            self._cond.wait_for(
                lambda: self._cursor < len(self._tokens) or self.done,
                timeout)
            if self._cursor < len(self._tokens):
                tok = self._tokens[self._cursor]
                self._cursor += 1
                return tok
            if self.done or not timeout:   # resolved, poll, or blocking
                return None
            raise TimeoutError(
                f"request {self.uid!r} streamed nothing new within "
                f"{timeout}s (status {self.status})")

    def result(self, timeout: Optional[float] = None) -> Optional[np.ndarray]:
        """Block until completion; the padded ``[prompt + completion]``
        array (``serve`` semantics), or ``None`` if cancelled.  Raises
        ``TimeoutError`` if ``timeout`` expires first, and
        :class:`RequestFailedError` when the fleet permanently failed
        the request (crash re-homing exhausted — the tokens streamed
        before the failure stay readable via :meth:`tokens`)."""
        if self._sanitizer is not None and timeout != 0:
            self._sanitizer.check_wait(
                f"RequestHandle.result(uid={self.uid!r})",
                site=caller_site(2))
        with self._cond:
            if not self._cond.wait_for(lambda: self.done, timeout):
                raise TimeoutError(
                    f"request {self.uid!r} still {self.status} after "
                    f"{timeout}s")
            if self.status == "failed":
                raise self._error
            return self._result


@dataclasses.dataclass
class _PendingItem:
    """One queued request plus its resume/streaming context — what the
    pending queue holds and what ``drain()`` hands a router."""
    req: Request
    prior: List[int]               # tokens generated before a preemption
    priority: int = 0
    slo_class: Optional[str] = None
    eos: Optional[int] = None
    handle: Optional[RequestHandle] = None
    _order: tuple = (0, 0)         # (-priority, seq) — queue sort key


class _PendingQueue:
    """Priority-then-FIFO admission queue.

    Items sort by ``(-priority, submit seq)`` — higher priority first,
    FIFO within a class — except preemption resumes (``push_front``),
    which jump ahead of EVERYTHING: the resumed sequence holds admission
    recency and the scheduler's no-starvation gate reasons about the
    literal queue head."""

    def __init__(self):
        self._items: List[_PendingItem] = []
        self._seq = 0
        self._front = -1

    def push(self, item: _PendingItem) -> None:
        item._order = (-int(item.priority), self._seq)
        self._seq += 1
        i = len(self._items)
        while i > 0 and self._items[i - 1]._order > item._order:
            i -= 1
        self._items.insert(i, item)

    def push_front(self, item: _PendingItem) -> None:
        item._order = (-(1 << 30), self._front)
        self._front -= 1
        self._items.insert(0, item)

    def popleft(self) -> _PendingItem:
        return self._items.pop(0)

    def remove(self, uid) -> Optional[_PendingItem]:
        for i, item in enumerate(self._items):
            if item.req.uid == uid:
                return self._items.pop(i)
        return None

    def drain(self) -> List[_PendingItem]:
        items, self._items = self._items, []
        return items

    def __len__(self) -> int:
        return len(self._items)

    def __bool__(self) -> bool:
        return bool(self._items)

    def __iter__(self):
        return iter(self._items)

    def __getitem__(self, i) -> _PendingItem:
        return self._items[i]


@dataclasses.dataclass
class _SlotState:
    req: Request
    admit_seq: int                 # admission recency (preemption victim order)
    prompt_eff: np.ndarray         # prompt (+ pre-preemption tokens on resume)
    prior: List[int]               # tokens generated before a preemption
    out: List[int] = dataclasses.field(default_factory=list)
    base: int = 0                  # tokens already in the paged cache
    phase: str = "prefill"         # "prefill" -> "decode"
    eos: Optional[int] = None      # per-request eos (submit-time)
    priority: int = 0
    slo_class: Optional[str] = None
    handle: Optional[RequestHandle] = None
    #: resident-window serving: first block index of the device-resident
    #: window (blocks in [landmark, window_blk) are demoted + masked);
    #: stays 0 when resident_window_blocks == 0 or nothing has slid yet
    window_blk: int = 0

    @property
    def plen_eff(self) -> int:
        return int(self.prompt_eff.size)

    @property
    def gen_count(self) -> int:
        return len(self.prior) + len(self.out)

    @property
    def pos_cap(self) -> int:
        """One past the highest cache position this request can ever
        commit: original prompt + completion budget.  Speculative windows
        reaching past the cap scatter to the scratch block instead of
        allocating blocks the request can never use (rollback-aware
        accounting — see ops/paged_kv.py)."""
        return self.plen_eff - len(self.prior) + self.req.max_new_tokens


class ServingEngine:
    """Iteration-level (continuous-batching) scheduler over an
    :class:`~deepspeed_tpu.inference.engine.InferenceEngine`'s KV-decode
    path, with a block-paged cache (module docstring has the design).

    Parameters
    ----------
    engine:         an ``init_inference`` engine whose model carries
                    ``decode_hooks`` with ``supports_lengths`` and
                    ``supports_paged`` (gpt2 / llama / opt / mixtral).
    slots:          max concurrently-active sequences.
    max_seq_len:    per-sequence budget (prompt + completion), clamped to
                    the model context length.
    block_size:     tokens per KV block (paging granularity — also the
                    prefix-reuse granularity).
    num_blocks:     physical pool size incl. the scratch block.  Default
                    ``1 + slots * ceil(max_seq_len/block_size)`` (no
                    oversubscription); smaller pools oversubscribe and rely
                    on prefix eviction + preemption.
    chunked_prefill: ``True`` = fixed-window chunked prefill (1 compiled
                    prefill program, prefix reuse available).  ``False`` =
                    bucket-ladder fallback (per-bucket programs, no
                    reuse).  Default ``None`` = auto: bucketed iff
                    ``prompt_buckets`` is passed.
    prefill_chunk:  chunk window length (chunked mode).
    prompt_buckets: ascending prompt-length ladder (bucketed mode).
    prefill_batch:  sequences per prefill call (both modes); short groups
                    pad with scratch-routed rows.
    prefix_caching: enable the block trie (chunked mode only).
    spec_tokens:    speculative draft length K (0 = off; chunked mode
                    only).  Each decode iteration proposes K tokens per
                    slot and verifies them in one K+1-token target pass.
    shard_kv:       shard the paged pool over the mesh's ``tp`` axis
                    (KV-head dim — module docstring).  Default ``None`` =
                    auto: shard iff tp > 1 and the KV head count divides
                    it.  ``True`` additionally raises when the head count
                    does not divide (instead of silently replicating);
                    ``False`` forces the replicated tp=1 layout.
    quantize:       serving-path quantization (module docstring): ``None``
                    (default, bit-identical to pre-quantization behavior),
                    ``"kv8"`` (int8 paged KV pool + per-block scale
                    table), ``"w8a8"`` (requires an engine whose params
                    carry K-grouped int8 records — ``init_serving`` wires
                    the config), or ``"w8a8+kv8"``.  Quantized lanes trade
                    exact greedy parity for a bounded token-divergence /
                    logit-error contract.
    host_blocks:    host-DRAM tier size in KV blocks (module docstring
                    "Tiered KV cache"); ``0`` (default) disables tiering
                    — behavior, programs, and scheduling are then
                    byte-identical to the pre-tiering engine.  Requires
                    chunked-prefill mode with ``prefix_caching`` on (the
                    trie is the content-address space promotions graft
                    back into).  Size it at the session working set you
                    want to survive eviction — e.g. the full trace
                    footprint for a multi-turn chat tier.
    swap_batch:     blocks per demotion/promotion device round trip (the
                    fixed shape of the two swap programs; default 8).
                    Larger batches amortize transfer latency, smaller
                    ones waste less padding on short chains.
    decode_steps:   K decode iterations fused into ONE on-device
                    ``lax.while_loop`` program (default 1 = the classic
                    per-token host loop, bit-identical to earlier PRs).
                    With K > 1 the per-slot eos/budget checks move
                    on-device behind a fixed-shape ``active`` mask, the
                    program emits a ``[slots, K]`` token buffer, and the
                    host catches up once per window at the fence
                    (``_fence_harvest`` — the ONLY device sync of the
                    decode path).  Block-table writes stay inside each
                    slot's pre-reserved span, so the paged invariants
                    hold across the whole fused window.  Token-exact
                    with K=1 greedy decode by construction; the fused
                    program REPLACES the single-token decode program
                    (compile budget unchanged).  Inert in speculative
                    mode — the draft/verify round already amortizes the
                    host loop over K+1 tokens per program.
    engine_mode:    ``"replicas"`` (default) or ``"dp_tp"``.  dp_tp runs
                    ONE engine over a 2-D ``("dp", "tp")`` mesh: the
                    slot/batch axis and the physical-block dim shard
                    over ``dp`` (each dp group owns a contiguous span of
                    slots AND of pool blocks, with its own scratch
                    block), KV heads stay tp-sharded via the existing
                    ``tp_context`` — one compiled decode program serves
                    what otherwise takes dp router-fronted replicas,
                    and the router demotes to front-end admission.
                    v1 restrictions (ctor-validated): chunked prefill;
                    no speculative decoding, host tier, prefix caching
                    or quantization; ``slots`` and ``num_blocks``
                    divisible by the mesh dp degree.
    draft:          draft proposer model — an ``init_inference`` engine or
                    a bare ModelSpec (wrapped with the target's inference
                    config) of a small same-family/same-tokenizer model.
                    ``None`` selects the model-free n-gram prompt-lookup
                    proposer (zero extra compiled programs).
    ngram_max/min:  n-gram match lengths for the lookup proposer (longest
                    match first, most recent occurrence wins).
    debug_checks:   turn the documented contracts into enforced ones
                    (``analysis/``): the recompile sentry RAISES at trace
                    time on any retrace past the engine's compile budget
                    (with an abstract-signature diff), and the paged-state
                    invariant audit (refcounts, free list, scratch, trie,
                    table spans) runs after every scheduler iteration.
                    Off (default): the sentry still counts traces (zero
                    runtime cost — the wrapped body only executes while
                    tracing) and ``stats()['retraces_observed']`` reports
                    drift; the audit is one skipped branch per iteration.
    trace_capacity: per-request trace timeline ring size (module
                    docstring "Telemetry"): scheduler events are recorded
                    into a bounded host-side ring and exported as Chrome
                    ``trace_event`` JSON by :meth:`dump_trace`.  ``0``
                    disables event recording entirely (one predicate per
                    would-be event); the metrics registry backing
                    ``stats()`` is always on.
    slo_targets:    per-``slo_class`` latency targets + attainment
                    objective overrides, merged over
                    ``telemetry/slo.py DEFAULT_SLO_TARGETS`` — every
                    finished request lands in its class's TTFT/TPOT
                    histograms and attainment counters; ``slo_report()``
                    is the per-class view.
    peak_flops:     the MFU denominator (per-chip peak FLOPs × chips)
                    for :meth:`flops_report`; ``None`` leaves the MFU
                    gauge unset unless the report call supplies one.
    """

    def __init__(self, engine, *, slots: int = 8,
                 max_seq_len: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 prefill_batch: int = 4,
                 block_size: int = 32,
                 num_blocks: Optional[int] = None,
                 chunked_prefill: Optional[bool] = None,
                 prefill_chunk: int = 128,
                 prefix_caching: bool = True,
                 decode_steps: int = 1,
                 engine_mode: str = "replicas",
                 sp: int = 1,
                 resident_window_blocks: int = 0,
                 spec_tokens: int = 0,
                 quantize: Optional[str] = None,
                 host_blocks: int = 0,
                 swap_batch: int = 8,
                 role: str = "both",
                 nvme_blocks: int = 0,
                 nvme_high_watermark: float = 0.9,
                 nvme_path: Optional[str] = None,
                 draft=None,
                 ngram_max: int = 3,
                 ngram_min: int = 1,
                 shard_kv: Optional[bool] = None,
                 sampling: bool = True,
                 spec_verifier: str = "rejection",
                 logit_masks: bool = False,
                 debug_checks: bool = False,
                 trace_capacity: int = 16384,
                 slo_targets: Optional[Dict[str, Dict[str, float]]] = None,
                 peak_flops: Optional[float] = None):
        self.spec_tokens = int(spec_tokens)
        if self.spec_tokens < 0:
            raise ValueError(f"spec_tokens must be >= 0, got {spec_tokens}")
        if self.spec_tokens and self.spec_tokens + 1 > VERIFY_T_MAX:
            raise ValueError(
                f"spec_tokens={spec_tokens} needs a {spec_tokens + 1}-token "
                f"verify window but the paged verify kernel takes at most "
                f"{VERIFY_T_MAX} — lower spec_tokens to "
                f"{VERIFY_T_MAX - 1} or less")
        if draft is not None and not self.spec_tokens:
            raise ValueError(
                "a draft model was given but spec_tokens is 0 — pass "
                "spec_tokens=K to enable speculative decoding")
        # ----- on-device sampling stack (PR 20)
        self.sampling = bool(sampling)
        self.spec_verifier = str(spec_verifier)
        if self.spec_verifier not in ("rejection", "greedy"):
            raise ValueError(
                f"spec_verifier must be 'rejection' or 'greedy', got "
                f"{spec_verifier!r}")
        if self.spec_tokens and self.sampling and \
                self.spec_verifier == "greedy":
            raise ValueError(
                "speculative decoding on a sampling engine requires the "
                "rejection verifier (spec_verifier='rejection') — the "
                "greedy prefix-matcher would silently reshape sampled "
                "output distributions; pass sampling=False to keep the "
                "legacy greedy verifier")
        self.logit_masks = bool(logit_masks)
        if self.logit_masks and not self.sampling:
            raise ValueError(
                "logit_masks=True needs the sampling stack — constrained "
                "decoding applies the mask inside the sampler programs; "
                "drop sampling=False")
        self.quantize, self.kv_quant, want_w8a8 = _parse_quantize(quantize)
        qcfg = engine._config.quant
        self.weight_quant = qcfg.type if qcfg.enabled else None
        if want_w8a8 and self.weight_quant != "w8a8":
            raise ValueError(
                "quantize includes 'w8a8' but the wrapped engine carries "
                f"{self.weight_quant or 'full-precision'} weights — build "
                "it with config={'quant': {'enabled': True, 'type': "
                "'w8a8'}} (init_serving(quantize=...) does this for you)")
        hooks = _validate_decode_hooks(engine.module,
                                       speculative=bool(self.spec_tokens),
                                       kv_quant=self.kv_quant,
                                       sampling=self.sampling)
        self.engine = engine
        self._fwd = hooks["forward_cached"]
        self._init_cache = hooks["init_cache"]
        max_ctx = hooks.get("max_seq_len")
        if max_seq_len is None:
            max_seq_len = max_ctx or 512
        if max_ctx is not None and max_seq_len > max_ctx:
            raise ValueError(
                f"max_seq_len {max_seq_len} exceeds the model context "
                f"length {max_ctx}")
        self.max_seq_len = int(max_seq_len)
        self.slots = int(slots)
        if self.slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.block_size = int(block_size)
        # logical per-sequence capacity, rounded up to whole blocks
        self._cache_len = blocks_for(self.max_seq_len, block_size) \
            * block_size
        self._nbper = self._cache_len // block_size      # block-table width

        self.chunked_prefill = (prompt_buckets is None) \
            if chunked_prefill is None else bool(chunked_prefill)
        if self.chunked_prefill:
            self.prompt_buckets: Tuple[int, ...] = ()
            # floor of 2: forward_cached dispatches per-row DECODE on T == 1,
            # so a width-1 prefill window would be misread as a decode step
            # (1-token prompts prefill fine in a width-2 window — the pad
            # column writes to scratch)
            self.prefill_chunk = max(2, min(int(prefill_chunk),
                                            self._cache_len))
        else:
            buckets = tuple(sorted(prompt_buckets)) if prompt_buckets \
                else default_buckets(self.max_seq_len)
            if any(b > self.max_seq_len for b in buckets):
                raise ValueError(
                    f"prompt bucket(s) {buckets} exceed max_seq_len "
                    f"{self.max_seq_len}")
            self.prompt_buckets = buckets
            self.prefill_chunk = 0
        self.prefill_batch = int(prefill_batch)
        if self.prefill_batch < 1:
            raise ValueError(
                f"prefill_batch must be >= 1, got {prefill_batch}")

        # ----- fused multi-step decode window + engine mode
        self._K = int(decode_steps)
        if self._K < 1:
            raise ValueError(
                f"decode_steps must be >= 1, got {decode_steps}")
        self.engine_mode = str(engine_mode)
        if self.engine_mode not in ("replicas", "dp_tp"):
            raise ValueError(
                f"engine_mode must be 'replicas' or 'dp_tp', got "
                f"{engine_mode!r}")
        dp = int(dict(engine.mesh.shape).get(DP_AXIS, 1)) \
            if self.engine_mode == "dp_tp" else 1
        if self.engine_mode == "dp_tp":
            if not self.chunked_prefill:
                raise ValueError(
                    "engine_mode='dp_tp' requires chunked-prefill mode — "
                    "drop prompt_buckets / pass chunked_prefill=True")
            if spec_tokens or int(host_blocks) or quantize:
                raise ValueError(
                    "engine_mode='dp_tp' v1 excludes speculative decoding, "
                    "the host KV tier and quantization — run those "
                    "compositions in 'replicas' mode")
            if prefix_caching:
                raise ValueError(
                    "engine_mode='dp_tp' v1 excludes prefix caching (the "
                    "trie would share blocks across dp groups) — pass "
                    "prefix_caching=False")
            if self.logit_masks:
                raise ValueError(
                    "engine_mode='dp_tp' v1 excludes logit_masks — the "
                    "[slots, vocab] mask operand is not dp-sharded yet; "
                    "run constrained decoding in 'replicas' mode")
            if self.slots % dp:
                raise ValueError(
                    f"engine_mode='dp_tp': slots ({self.slots}) must "
                    f"divide evenly over the mesh dp axis ({dp})")
        self.dp_degree = dp

        # ----- sequence-parallel (Ulysses) prefill over the mesh sp axis
        self.sp_degree = int(sp)
        if self.sp_degree < 1:
            raise ValueError(f"sp must be >= 1, got {sp}")
        if self.sp_degree > 1:
            mesh_sp = int(dict(engine.mesh.shape).get(SP_AXIS, 1))
            if mesh_sp != self.sp_degree:
                raise ValueError(
                    f"sp={sp} but the engine mesh carries an sp axis of "
                    f"size {mesh_sp} — build the engine with "
                    f"config={{'sequence_parallel': {sp}}} "
                    "(init_serving(sp=...) does this for you)")
            if not self.chunked_prefill:
                raise ValueError(
                    "sp > 1 requires chunked-prefill mode — the Ulysses "
                    "all-to-all shards the fixed prefill_chunk window; "
                    "drop prompt_buckets / pass chunked_prefill=True")
            if self.prefill_chunk % self.sp_degree:
                raise ValueError(
                    f"prefill_chunk ({self.prefill_chunk}) must divide "
                    f"evenly over sp={sp} — each sp rank owns a "
                    "prefill_chunk/sp sequence shard")
            if self.dp_degree > 1:
                raise ValueError(
                    "sp > 1 composes with tp, not with engine_mode="
                    "'dp_tp' — run sequence-parallel prefill in "
                    "'replicas' mode")
            if self.spec_tokens:
                raise ValueError(
                    "sp > 1 v1 excludes speculative decoding — the "
                    "draft/verify programs are decode-side (T <= "
                    f"{VERIFY_T_MAX}) where sequence parallelism has "
                    "nothing to shard; drop spec_tokens")

        # ----- resident-window context paging for 100k+-token prompts
        self.resident_window_blocks = int(resident_window_blocks)
        if self.resident_window_blocks < 0:
            raise ValueError(
                f"resident_window_blocks must be >= 0, got "
                f"{resident_window_blocks}")
        if self.resident_window_blocks:
            if not self.chunked_prefill:
                raise ValueError(
                    "resident_window_blocks > 0 requires chunked-prefill "
                    "mode — giant prompts stream through the fixed "
                    "prefill window; drop prompt_buckets / pass "
                    "chunked_prefill=True")
            if not int(host_blocks):
                raise ValueError(
                    "resident_window_blocks > 0 needs the tiered KV cache "
                    "(host_blocks > 0): cold context blocks demote to the "
                    "host arena when the window slides past them")
            if self.spec_tokens:
                raise ValueError(
                    "resident_window_blocks > 0 v1 excludes speculative "
                    "decoding — the verify window's span math assumes a "
                    "dense block table; drop spec_tokens")
            if self._K > 1:
                raise ValueError(
                    "resident_window_blocks > 0 v1 excludes decode_steps "
                    "> 1 — the fused window derives the table span from a "
                    "dense leading run, which window slides punch holes "
                    "in; use decode_steps=1")
            if self.dp_degree > 1:
                raise ValueError(
                    "resident_window_blocks > 0 v1 excludes engine_mode="
                    "'dp_tp' — run resident-window serving in 'replicas' "
                    "mode")
            if self.sp_degree > 1:
                raise ValueError(
                    "resident_window_blocks > 0 v1 excludes sp > 1 — "
                    "sequence-parallel prefill assumes every committed "
                    "block is device-resident; pick one per engine")
            min_win = blocks_for(self.prefill_chunk, self.block_size) + 1
            if self.resident_window_blocks < min_win:
                raise ValueError(
                    f"resident_window_blocks ({resident_window_blocks}) "
                    f"must be >= {min_win} (one prefill_chunk span "
                    f"+ 1 decode block) or the window would slide out "
                    "from under the chunk currently being prefilled")
        #: leading blocks pinned device-resident + attention-visible
        self._landmark_blocks = _LANDMARK_BLOCKS \
            if self.resident_window_blocks else 0

        if num_blocks is None:
            num_blocks = self.dp_degree + self.slots * self._nbper
        if self.dp_degree > 1:
            # one allocation group per dp shard: each group owns a
            # contiguous span of physical blocks (its local block 0 is that
            # group's scratch), so every dp shard's gathers and scatters
            # stay within its own pool chunk
            if num_blocks % self.dp_degree:
                raise ValueError(
                    f"engine_mode='dp_tp': num_blocks ({num_blocks}) must "
                    f"divide evenly over the mesh dp axis "
                    f"({self.dp_degree})")
            if num_blocks // self.dp_degree < 1 + self._nbper:
                raise ValueError(
                    f"num_blocks {num_blocks} over {self.dp_degree} dp "
                    f"groups cannot hold one full sequence per group "
                    f"({self._nbper} blocks + 1 scratch each)")
            self._alloc = GroupedBlockAllocator(num_blocks, self.dp_degree)
            self._scratch_blocks = frozenset(
                g * (num_blocks // self.dp_degree)
                for g in range(self.dp_degree))
        else:
            # resident-window serving exists precisely so the device pool
            # can be SMALLER than one full logical sequence: only the
            # landmark prefix + sliding window (+ the chunk being
            # prefilled) must fit at once
            min_need = 1 + self._nbper
            if self.resident_window_blocks:
                min_need = min(
                    min_need,
                    1 + self._landmark_blocks + self.resident_window_blocks
                    + blocks_for(self.prefill_chunk, self.block_size))
            if num_blocks < min_need:
                raise ValueError(
                    f"num_blocks {num_blocks} cannot hold one full sequence "
                    f"({self._nbper} blocks + 1 scratch)"
                    if not self.resident_window_blocks else
                    f"num_blocks {num_blocks} cannot hold one resident "
                    f"window ({self._landmark_blocks} landmark + "
                    f"{self.resident_window_blocks} window + chunk span "
                    f"+ 1 scratch = {min_need})")
            self._alloc = BlockAllocator(num_blocks)
            self._scratch_blocks = None
        self._prefix = PrefixCache(self.block_size) \
            if (prefix_caching and self.chunked_prefill) else None
        self.host_blocks = int(host_blocks)
        if self.host_blocks < 0:
            raise ValueError(f"host_blocks must be >= 0, got {host_blocks}")
        self.swap_batch = int(swap_batch)
        if self.host_blocks and self.swap_batch < 1:
            raise ValueError(f"swap_batch must be >= 1, got {swap_batch}")
        if self.host_blocks and self.swap_batch > self.host_blocks:
            raise ValueError(
                f"swap_batch={swap_batch} exceeds host_blocks="
                f"{host_blocks} — one demotion batch could never fit the "
                "host arena; lower swap_batch or grow host_blocks")
        if self.host_blocks and self._prefix is None:
            raise ValueError(
                "the tiered KV cache (host_blocks > 0) needs chunked-"
                "prefill mode with prefix_caching=True — promoted chains "
                "re-register in the prefix trie (drop prompt_buckets / "
                "prefix_caching=False, or host_blocks)")

        # ----- disaggregated serving role + NVMe third tier
        self.role = str(role)
        if self.role not in ("prefill", "decode", "both"):
            raise ValueError(
                f"role must be 'prefill', 'decode' or 'both', got {role!r}")
        if self.role != "both" and not self.host_blocks:
            raise ValueError(
                f"role={self.role!r} needs the tiered KV cache "
                "(host_blocks > 0): the prefill→decode handoff travels as "
                "a host-tier chain export/import — pass host_blocks, or "
                "role='both'")
        self.nvme_blocks = int(nvme_blocks)
        if self.nvme_blocks < 0:
            raise ValueError(
                f"nvme_blocks must be >= 0, got {nvme_blocks}")
        if self.nvme_blocks and not self.host_blocks:
            raise ValueError(
                f"nvme_blocks={nvme_blocks} needs the host tier above it "
                "(host_blocks > 0) — NVMe entries spill from and promote "
                "through the host arena, never the device pool directly")
        self.nvme_high_watermark = float(nvme_high_watermark)
        if not (0.0 < self.nvme_high_watermark <= 1.0):
            raise ValueError(
                f"nvme_high_watermark must be in (0, 1], got "
                f"{nvme_high_watermark}")
        if self.nvme_blocks and \
                self.swap_batch > int(self.nvme_high_watermark
                                      * self.host_blocks):
            raise ValueError(
                f"swap_batch={swap_batch} exceeds the host-arena watermark "
                f"budget int({nvme_high_watermark} * {host_blocks}) — one "
                "promotion batch would immediately re-spill its own head; "
                "lower swap_batch or raise nvme_high_watermark/host_blocks")
        self._nvme_path_arg = nvme_path

        # ----- tensor parallelism: one pool, committed on the engine mesh so
        # the very first step sees the same placement as every later one —
        # sharded over the KV-HEAD dim (``P(None, None, "tp")`` on the
        # stacked [L, NB, HKV, bs, hd] buffer) when the mesh carries a tp
        # axis the head count divides, else replicated (module docstring)
        self.tp_degree = int(dict(engine.mesh.shape).get(TP_AXIS, 1))
        if self.kv_quant:
            # int8 pool records {qp, ps} (ops/paged_kv): codes + per-block
            # scale table, built from the float pool's ABSTRACT shapes
            # (eval_shape) — materializing the bf16 pool first would cost
            # a transient bf16+int8 double footprint at exactly the
            # near-full-HBM block counts kv8 exists to serve.  Host
            # bookkeeping below is layout-invariant — scales ride under
            # block ids — but the engine keeps a ledger of which blocks
            # own LIVE scale rows so the debug audit can prove scale
            # allocation stays in lockstep with blocks (scale-lockstep
            # invariant, analysis/invariants.py)
            abstract = jax.eval_shape(
                lambda: self._init_cache(num_blocks, self.block_size,
                                         engine._config.jnp_dtype))
            pool = paged_kv.quantize_pool(abstract)
            self._kv_dtype = "int8"
        else:
            pool = self._init_cache(num_blocks, self.block_size,
                                    engine._config.jnp_dtype)
            self._kv_dtype = jnp.dtype(
                jax.tree_util.tree_leaves(pool)[0].dtype).name
        self._pool_shape = tuple(paged_kv.pool_payload(
            jax.tree_util.tree_leaves(
                pool, is_leaf=paged_kv.is_quantized_pool)[0]).shape)
        self._kv_scale_live: set = set()
        hkv = int(self._pool_shape[2])
        divisible = self.tp_degree > 1 and hkv % self.tp_degree == 0
        if shard_kv and self.tp_degree > 1 and not divisible:
            raise ValueError(
                f"shard_kv=True but the model's KV head count ({hkv}) does "
                f"not divide the mesh tp axis ({self.tp_degree}) — GQA "
                "pools with HKV < tp serve replicated (head groups are "
                "shared across chips); drop shard_kv or lower tp_size")
        self.kv_sharded = divisible if shard_kv is None else \
            (bool(shard_kv) and divisible)
        if self.sp_degree > 1:
            # mirror ops/sp_attention.sp_shards so a config that would
            # silently fall back to single-rank prefill fails loud here
            nh = getattr(engine.module.model_config, "num_heads", None)
            sp_tp = self.tp_degree if self.kv_sharded else 1
            if nh is not None and (
                    int(nh) % hkv or int(nh) % sp_tp
                    or (int(nh) // sp_tp) % self.sp_degree):
                raise ValueError(
                    f"sp={sp}: the {nh} query heads must shard evenly "
                    f"over tp={sp_tp} then sp={sp} (and divide the "
                    f"{hkv} KV heads) for the Ulysses all-to-all — "
                    "lower sp or pick a head-count-compatible mesh")
        rep = NamedSharding(engine.mesh, P())
        if self.dp_degree > 1:
            # dp_tp: the physical-block dim shards over dp (each group owns
            # a contiguous span, matching GroupedBlockAllocator's layout)
            # and heads over tp when divisible — ``P(None, "dp", "tp")`` on
            # the stacked [L, NB, HKV, bs, hd] buffer
            pool_sharding = NamedSharding(
                engine.mesh,
                P(None, DP_AXIS, TP_AXIS) if self.kv_sharded
                else P(None, DP_AXIS))
        else:
            pool_sharding = NamedSharding(
                engine.mesh, P(None, None, TP_AXIS)) \
                if self.kv_sharded else rep
        self._pool_sharding = pool_sharding
        self._cache = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, pool_sharding), pool)
        # host-side block tables; entry 0 = scratch doubles as "unset"
        self._tables = np.zeros((self.slots, self._nbper), np.int32)
        self._held: List[List[int]] = [[] for _ in range(self.slots)]
        self._tokens = np.zeros(self.slots, np.int32)
        self._lengths = np.zeros(self.slots, np.int32)
        #: resident-window serving: per-slot first attention-visible token
        #: past the landmark prefix (== landmark span while nothing has
        #: been demoted; rows of idle slots stay 0 and are never read by a
        #: windowed program because their batch rows are masked inactive)
        self._window_start = np.zeros(self.slots, np.int32)
        #: per-slot sampling state (PR 20): fixed-shape device operands —
        #: knob changes are operand VALUE changes, never recompiles.
        #: Rows of idle slots keep greedy defaults (temp 0), so inactive
        #: batch rows always take the bit-exact argmax lane.
        self._temps = np.zeros(self.slots, np.float32)
        self._topks = np.zeros(self.slots, np.int32)
        self._topps = np.ones(self.slots, np.float32)
        self._seeds = np.zeros(self.slots, np.uint32)
        self._vocab = int(getattr(engine.module.model_config, "vocab_size",
                                  0) or 0)
        #: constrained decoding: per-slot bool logit mask (True = allowed);
        #: only materialized when the engine opts into the mask operand
        self._masks = np.ones((self.slots, self._vocab), bool) \
            if self.logit_masks else None
        if self.logit_masks and not self._vocab:
            raise ValueError(
                "logit_masks=True needs the model config to expose "
                "vocab_size — the [slots, vocab] mask operand is sized "
                "from it")

        # compiled-program caches (true LRU, utils/lru.py — shared policy
        # with InferenceEngine._generate_fns); sized past the ladder so a
        # large custom bucket set can never thrash-recompile per call
        self._prefill_fns = LRUCache(
            capacity=max(16, len(self.prompt_buckets) + 1))
        self._decode_fn = None
        self._verify_fn = None
        self._draft_fn = None
        #: compile probe — one entry per traced program; chunked mode stays
        #: at 1 prefill + 1 decode for an entire trace (speculative: 1
        #: prefill + 1 verify [+ 1 draft rollout] — never more than 3)
        self.compiled_programs: List[Any] = []

        # ----- correctness tooling (analysis/): the recompile sentry wraps
        # every jitted body below so trace counts are enforced against the
        # declared budget — 2 chunked (1 prefill + 1 decode; the n-gram
        # speculative verify replaces decode), 3 with a draft model (fused
        # prefill + rollout + verify), O(#buckets)+2 bucketed (ladder +
        # full-cache-width preemption fallback + decode).  debug_checks
        # additionally raises at trace time and audits the paged host state
        # every scheduler iteration.
        self.debug_checks = bool(debug_checks)
        if self.spec_tokens:
            self.compile_budget = 3 if draft is not None else 2
        elif self.chunked_prefill:
            self.compile_budget = 2
        else:
            self.compile_budget = len(self.prompt_buckets) + 2
        if self.host_blocks:
            # the tiered KV swap pair: kv_demote (block gather) +
            # kv_promote (block scatter), both fixed-shape at swap_batch —
            # H2D/D2H traffic itself never compiles anything further
            self.compile_budget += 2
        # long-context amendments are ZERO by construction and the sentry
        # enforces it: windowed programs REPLACE the plain decode/prefill
        # bodies one-for-one (the window mask is traced into the same
        # sentry entries, window_start rides as a [slots] int32 operand),
        # and sp prefill reshapes the SAME prefill program through
        # shard_map — neither adds a program
        self.sentry = RecompileSentry(name="serving",
                                      strict=self.debug_checks,
                                      total_budget=self.compile_budget)
        # lock sanitizer for the handle Conditions this engine mints
        # (analysis/concurrency.py): a router embedding this replica
        # overrides it with the fleet-shared one so replica-lock ->
        # handle-cond acquisition edges are order-checked; None when
        # debug_checks is off (handles fall back to plain Conditions —
        # zero overhead, same contract as the sentry)
        self._lock_sanitizer = LockSanitizer() if self.debug_checks \
            else None
        if self.debug_checks:
            # process-wide jax.monitoring compile counter (idempotent):
            # corroborates the sentry by also seeing programs built OUTSIDE
            # registered entry points; surfaced as stats()["backend_compiles"]
            install_compile_listener()

        # ----- speculative decoding state
        self._draft = None                 # draft InferenceEngine
        self._dcache = None                # draft paged pool (shares tables)
        self._dcache_sharded = False
        self._proposer = None              # host-side n-gram fallback
        self.ngram_max = int(ngram_max)    # kept for resolved_config()
        self.ngram_min = int(ngram_min)
        if self.spec_tokens:
            if not self.chunked_prefill:
                raise ValueError(
                    "speculative decoding requires chunked-prefill mode — "
                    "drop prompt_buckets / pass chunked_prefill=True")
            if draft is not None:
                from .engine import InferenceEngine

                if not isinstance(draft, InferenceEngine):
                    draft = InferenceEngine(draft, engine._config)
                _validate_decode_hooks(draft.module, role="draft model",
                                       kv_quant=self.kv_quant,
                                       sampling=self.sampling)
                tv = getattr(engine.module.model_config, "vocab_size", None)
                dv = getattr(draft.module.model_config, "vocab_size", None)
                if tv is not None and dv is not None and tv != dv:
                    raise ValueError(
                        f"draft model vocab size {dv} != target vocab size "
                        f"{tv} — speculative decoding needs a shared "
                        "tokenizer")
                self._draft = draft
                mk_dpool = lambda: draft.module.decode_hooks["init_cache"](
                    num_blocks, self.block_size, draft._config.jnp_dtype)
                if self.kv_quant:
                    # the draft pool shares the target's block tables AND
                    # its quantization story — rollout reads/writes move
                    # int8 + scales too (abstract build, same double-
                    # footprint argument as the target pool above)
                    dpool = paged_kv.quantize_pool(jax.eval_shape(mk_dpool))
                else:
                    dpool = mk_dpool()
                dhkv = int(paged_kv.pool_payload(
                    jax.tree_util.tree_leaves(
                        dpool,
                        is_leaf=paged_kv.is_quantized_pool)[0]).shape[2])
                d_div = dhkv % self.tp_degree == 0
                if self.kv_sharded and not d_div:
                    if shard_kv:
                        raise ValueError(
                            f"shard_kv=True but the draft model's KV head "
                            f"count ({dhkv}) does not divide the mesh tp "
                            f"axis ({self.tp_degree}) — pick a draft whose "
                            "heads divide tp, or drop shard_kv")
                    log_dist(
                        f"ServingEngine: draft KV head count {dhkv} does "
                        f"not divide tp={self.tp_degree}; draft pool stays "
                        "replicated (target pool is sharded)", ranks=[0])
                # the draft pool rides the target's sharding story: same
                # head-dim spec when its HKV divides tp, else replicated
                # (the paged ops fall back per-shape — ops/paged_kv.py)
                self._dcache_sharded = self.kv_sharded and d_div
                dsharding = pool_sharding if self._dcache_sharded else rep
                self._dcache = jax.tree_util.tree_map(
                    lambda x: jax.device_put(x, dsharding), dpool)
            else:
                self._proposer = NGramProposer(self.spec_tokens,
                                               max_n=ngram_max,
                                               min_n=ngram_min)

        # ----- tiered KV: the host-DRAM arena below the device pool
        # (module docstring).  Built from the live swap tree's per-block
        # leaf shapes — the target pool, plus the draft pool when one
        # exists (drafts read their own KV through the shared tables, so
        # a promoted block must restore BOTH pools' bytes or speculative
        # acceptance would collapse to zero after every swap).  Quantized
        # records contribute their codes and scale rows as separate
        # leaves, so they demote/promote in lockstep by construction.
        self._host: Optional[HostBlockStore] = None
        self._demote_fn = None
        self._promote_fn = None
        self._staged: Dict[Any, Dict[str, Any]] = {}
        self._prefetch_gate: Dict[Any, tuple] = {}
        # prefill-role replicas park finished-prefill requests here (KV
        # demoted, slot released) until the router pumps take_handoffs()
        self._handoff_ready: List[_PendingItem] = []
        self._staging_shardings = None
        self._nvme: Optional[NvmeBlockStore] = None
        self.nvme_path: Optional[str] = None
        self._nvme_owns_path = False
        self._nvme_spills_seen = 0       # store-counter deltas already
        self._nvme_loads_seen = 0        # mirrored into the registry
        self._nvme_rejects_seen = 0
        if self.host_blocks:
            specs = [(tuple(l.shape[:1]) + tuple(l.shape[2:]), l.dtype)
                     for l in jax.tree_util.tree_leaves(self._swap_pools())]
            if self.nvme_blocks:
                # NVMe third tier below the arena: spill file at nvme_path
                # (auto-minted tempfile when unset — the engine owns and
                # unlinks it at close); entries keep chain_key + checksum
                # and every NVMe exit re-verifies before bytes re-enter
                # the arena (paged.py NvmeBlockStore)
                path = self._nvme_path_arg
                if path is None:
                    fd, path = tempfile.mkstemp(
                        prefix="ds_kv_spill_", suffix=".bin")
                    os.close(fd)
                    self._nvme_owns_path = True
                self.nvme_path = str(path)
                self._nvme = NvmeBlockStore(
                    self.nvme_blocks, specs, self.nvme_path)
                self._host = HostBlockStore(
                    self.host_blocks, specs, nvme=self._nvme,
                    nvme_watermark=self.nvme_high_watermark)
            else:
                self._host = HostBlockStore(self.host_blocks, specs)
            # per-leaf device_put specs are fixed for the engine's life
            self._staging_shardings = self._swap_leaf_shardings()

        # ----- telemetry (telemetry/): scheduler counters and latency
        # distributions live in the metrics registry — stats() is a view
        # over it (Prometheus text / JSON snapshot come for free), and the
        # legacy counter attributes below are read-only properties.  The
        # TTFT/TPOT histograms are fixed-bucket streaming: bounded memory
        # for a serve session of any length (the old raw-sample lists grew
        # forever and re-sorted on every stats() call); _latencies keeps a
        # small deque of recent per-request records as a debug view.
        m = self.metrics = MetricsRegistry()
        self._c_iterations = m.counter(
            "serving_iterations_total", "scheduler iterations run")
        self._c_decode_steps = m.counter(
            "serving_decode_steps_total", "single-token decode steps")
        self._c_fused_iterations = m.counter(
            "serving_fused_iterations_total",
            "device-side decode iterations executed inside fused "
            "multi-step windows (0 when decode_steps == 1)")
        self._c_host_fence_waits = m.counter(
            "serving_host_fence_waits_total",
            "host blocks on the device fence — one per fused decode "
            "window, the ONLY sync of the fused decode path")
        self._c_prefill_calls = m.counter(
            "serving_prefill_calls_total", "prefill program invocations")
        self._c_admitted = m.counter(
            "serving_requests_admitted_total", "requests admitted to slots")
        self._c_preempted = m.counter(
            "serving_requests_preempted_total",
            "sequences preempted under block pressure")
        self._c_prompt_tokens = m.counter(
            "serving_prompt_tokens_total", "prompt tokens admitted")
        self._c_prefix_hit_tokens = m.counter(
            "serving_prefix_hit_tokens_total",
            "prompt tokens served from the prefix cache")
        self._c_spec_rounds = m.counter(
            "serving_spec_rounds_total", "speculative draft-verify rounds")
        self._c_drafted = m.counter(
            "serving_spec_drafted_tokens_total", "draft tokens proposed")
        self._c_accepted = m.counter(
            "serving_spec_accepted_tokens_total", "draft tokens accepted")
        self._c_spec_rejected = m.counter(
            "serving_spec_draft_rejected_total",
            "draft tokens the verifier rejected (rejection sampler or "
            "greedy mismatch)")
        self._c_sampled = {
            mode: m.counter("serving_sampled_requests_total",
                            "submitted requests by sampling mode",
                            mode=mode)
            for mode in ("greedy", "sampled", "constrained")}
        self._h_accept_ratio = m.histogram(
            "serving_spec_accept_ratio",
            buckets=(0.0, 0.25, 0.5, 0.75, 1.0),
            help="per-round fraction of drafted tokens accepted")
        self._c_finished = m.counter(
            "serving_requests_finished_total", "requests run to completion")
        self._c_cancelled = m.counter(
            "serving_requests_cancelled_total",
            "requests cancelled before completion (queued or active)")
        self._c_gen_tokens = m.counter(
            "serving_generated_tokens_total",
            "tokens committed across all requests (prefill first tokens, "
            "decode steps, accepted speculative drafts)")
        self._g_queue_depth = m.gauge(
            "serving_queue_depth", "requests waiting for a slot")
        self._c_invariant_checks = m.counter(
            "serving_invariant_checks_total",
            "paged-state audits run (analysis/invariants.py)")
        # tiered-KV swap traffic (zero-valued, never incremented when the
        # tier is off — the cells exist so dashboards see a stable schema)
        self._c_swap_out = m.counter(
            "serving_kv_swaps_total",
            "KV blocks swapped between the device pool and the host tier",
            direction="out", tier="host")
        self._c_swap_in = m.counter(
            "serving_kv_swaps_total",
            "KV blocks swapped between the device pool and the host tier",
            direction="in", tier="host")
        self._c_swap_bytes = m.counter(
            "serving_swap_bytes_total",
            "bytes moved over the device<->host KV tier (both directions)",
            tier="host")
        # NVMe third-tier traffic (tier="nvme"): host-arena spills past the
        # watermark and verified promotions back — synced by delta from the
        # NvmeBlockStore counters at every swap commit point
        self._c_nvme_out = m.counter(
            "serving_kv_swaps_total",
            "KV blocks swapped between the device pool and the host tier",
            direction="out", tier="nvme")
        self._c_nvme_in = m.counter(
            "serving_kv_swaps_total",
            "KV blocks swapped between the device pool and the host tier",
            direction="in", tier="nvme")
        self._c_nvme_bytes = m.counter(
            "serving_swap_bytes_total",
            "bytes moved over the device<->host KV tier (both directions)",
            tier="nvme")
        self._g_nvme_in_use = m.gauge(
            "serving_nvme_blocks_in_use",
            "KV blocks currently resident in the NVMe spill file")
        self._c_handoffs = m.counter(
            "serving_handoffs_total",
            "prefill->decode handoffs extracted from a prefill-role "
            "replica's scheduler")
        self._c_prefetch_miss = m.counter(
            "serving_prefetch_misses_total",
            "promotions that had to stage synchronously at admission "
            "(no prefetch was in flight for the chain)")
        self._c_resume_recompute = m.counter(
            "serving_resume_recompute_tokens_total",
            "prompt tokens re-prefilled when admitting a preemption resume "
            "(near zero with the host tier: demoted state promotes back)")
        self._c_checksum_fail = m.counter(
            "serving_checksum_failures_total",
            "host-tier KV blocks rejected by the integrity checksum "
            "(corrupt bytes dropped and recomputed, never served)")
        self._h_prefetch_wait = m.histogram(
            "serving_prefetch_wait_seconds",
            help="time admission blocked on an in-flight promotion "
                 "(0-bucket = the H2D transfer fully overlapped decode)")
        self._g_host_blocks_in_use = m.gauge(
            "serving_host_blocks_in_use",
            "host-tier arena slots holding demoted KV blocks")
        # long-context lane (zero-valued when sp == 1 and
        # resident_window_blocks == 0 — stable dashboard schema)
        self._c_window_slides = m.counter(
            "serving_context_window_slides_total",
            "resident-window slides: cold context block runs demoted to "
            "the host tier and masked out of decode attention")
        self._c_sp_a2a_bytes = m.counter(
            "serving_sp_alltoall_bytes_total",
            "cross-rank bytes moved by the Ulysses all-to-all pair "
            "during sequence-parallel prefill (analytic, host-computed)")
        self._h_ttft = m.histogram(
            "serving_ttft_seconds", help="per-request time to first token")
        self._h_tpot = m.histogram(
            "serving_tpot_seconds",
            help="per-request time per output token (decode cadence)")
        self._g_blocks_in_use = m.gauge(
            "serving_blocks_in_use", "physical KV blocks referenced")
        self._g_free_blocks = m.gauge(
            "serving_free_blocks", "physical KV blocks on the free list")
        # SLO attainment accounting (telemetry/slo.py): every finished
        # request lands in its class's TTFT/TPOT histograms + attainment
        # counters on THIS registry; slo_report() is the per-class view
        self._slo = SLOTracker(m, slo_targets)
        self.peak_flops = peak_flops
        self._flops_profiler = None        # built lazily by flops_report()
        #: raw (un-sentry-wrapped) program bodies + shape meta, captured
        #: at build time for the FLOPs profiler — lowering a RAW body for
        #: cost_analysis never ticks the sentry and never compiles
        #: (telemetry/flops.py).  "prefill" maps width -> body (bucketed
        #: mode builds one program per bucket width — each must be costed
        #: at ITS width), with per-width invocation counts alongside.
        self._program_bodies: Dict[str, Any] = {}
        self._program_meta: Dict[str, Any] = {}
        self._prefill_calls_by_width: Dict[int, int] = {}
        #: router-noted flow ids (uid -> Chrome flow id): admission emits
        #: the matching flow-finish so the merged fleet trace draws the
        #: route -> admit arrow (telemetry/trace.py flow events)
        self._flow_ids: Dict[Any, int] = {}
        self.timeline = TraceTimeline(capacity=trace_capacity)
        if self.timeline.enabled:
            # bounded lane table: one span lane per SLOT (a request's span
            # lands on the slot that finished it) — lane count never grows
            # with traffic, unlike per-uid lanes
            for s in range(self.slots):
                self.timeline.thread(f"slot {s}")
        self.sentry.on_trace = self._emit_trace_event
        self._latencies = deque(maxlen=256)  # recent finished requests
        self._trace_times: Dict[Any, Dict[str, Any]] = {}
        self._admit_seq = 0
        self._blocked_gate = None          # (head id, resume len, version)
        # ----- incremental scheduler state (module docstring "Incremental
        # serving API"): the pending queue and active slot map live on the
        # engine so submit()/step()/cancel()/drain() can drive the same
        # scheduler serve() wraps
        self._pending = _PendingQueue()
        self._active: Dict[int, _SlotState] = {}
        self._live_uids: set = set()       # pending + active uids, O(1)
        self._cancel_flags: set = set()    # active-slot cancels, applied at
        self._admission_log = None         # the next iteration boundary
        self._step_log = None
        #: trace-capture hook (autotuning/trace.py TraceRecorder): called
        #: once per successful submit() with the request and its
        #: submit-time knobs, BEFORE any slo_class -> priority mapping
        self._submit_observer = None
        #: fault-injection hook (serving/faults.py): a bound replica view
        #: armed by arm_faults(); None = zero cost (one predicate at each
        #: injection point, nothing else changes)
        self._fault_injector = None
        #: bounded deterministic retry/backoff for the engine-internal
        #: swap transport (demote/promote) under an armed fault plan —
        #: attributes, not ctor knobs, so resolved_config() stays stable
        self._transport_retries = 2
        self._transport_backoff_s = 0.0
        log_dist(
            f"ServingEngine: slots={self.slots}, cache_len="
            f"{self._cache_len}, block_size={self.block_size}, "
            f"num_blocks={num_blocks}, "
            + (f"chunked prefill (chunk={self.prefill_chunk}, prefix_cache="
               f"{self._prefix is not None})" if self.chunked_prefill
               else f"bucketed prefill {self.prompt_buckets}")
            + f", prefill_batch={self.prefill_batch}"
            + (f", speculative K={self.spec_tokens} "
               f"({'draft ' + self._draft.module.name if self._draft else 'n-gram'})"
               if self.spec_tokens else "")
            + (f", fused decode K={self._K}" if self._K > 1 else "")
            + (f", engine_mode=dp_tp (dp={self.dp_degree} groups)"
               if self.engine_mode == "dp_tp" else "")
            + (f", kv sharded over tp={self.tp_degree} "
               f"({hkv // self.tp_degree} heads/chip)" if self.kv_sharded
               else (f", kv replicated (tp={self.tp_degree})"
                     if self.tp_degree > 1 else ""))
            + (f", quantize={self.quantize}" if self.quantize else "")
            + (f", tiered KV (host_blocks={self.host_blocks}, "
               f"{self._host.arena_bytes / 1e6:.1f}MB host arena, "
               f"swap_batch={self.swap_batch})" if self._host else "")
            + (f", nvme tier (nvme_blocks={self.nvme_blocks}, watermark="
               f"{self.nvme_high_watermark}, {self.nvme_path})"
               if self._nvme is not None else "")
            + (f", role={self.role}" if self.role != "both" else "")
            + (f", sp={self.sp_degree} (Ulysses prefill)"
               if self.sp_degree > 1 else "")
            + (f", resident window={self.resident_window_blocks} blocks "
               f"(+{self._landmark_blocks} landmark)"
               if self.resident_window_blocks else ""),
            ranks=[0])

    def close(self) -> None:
        """Release host-side tier resources: join the NVMe aio handle and
        unlink an auto-minted spill file (a caller-provided ``nvme_path``
        is the caller's to keep).  Idempotent; the device pool and
        compiled programs are garbage-collected as usual."""
        nvme, self._nvme = self._nvme, None
        if nvme is not None:
            nvme.close()
            if self._nvme_owns_path and self.nvme_path:
                try:
                    os.unlink(self.nvme_path)
                except OSError:  # graft: noqa(GL013) best-effort cleanup of our own temp spill file
                    pass

    def __del__(self):
        try:
            self.close()
        except Exception:  # graft: noqa(GL013) __del__ during interpreter teardown — nothing left to tell
            pass

    def _tp_ctx(self):
        """Context every compiled-fn invocation runs under: tracing happens
        inside the call, so the paged device ops (``ops/paged_kv.py``,
        ``ops/decode_attention.py``) bake THIS engine's mesh — or none —
        into the program, even when engines of different tp degrees coexist
        in one process."""
        return paged_kv.tp_context(
            self.engine.mesh if self.kv_sharded else None)

    def _sp_ctx(self):
        """Sequence-parallel tracing context (``ops/sp_attention``):
        prefill invocations — and ONLY prefill invocations — enter it, so
        the T > 1 paged-attention dispatch sees the sp mesh and reshapes
        through the Ulysses all-to-all, while decode/verify programs
        (T <= VERIFY_T_MAX windows with nothing to shard) trace with the
        hook dormant."""
        if self.sp_degree > 1:
            return sp_attention.sp_context(self.engine.mesh)
        return contextlib.nullcontext()

    def _decode_ctx(self):
        """:meth:`_tp_ctx` plus the dp grouping for ``engine_mode='dp_tp'``:
        the paged ops additionally shard batch rows and the physical-block
        dim over the mesh ``dp`` axis, localizing each shard's block-table
        reads into its own contiguous pool chunk (``ops/paged_kv.py
        dp_context``)."""
        if self.dp_degree > 1:
            stack = contextlib.ExitStack()
            stack.enter_context(self._tp_ctx())
            stack.enter_context(paged_kv.dp_context(
                self.engine.mesh, self.dp_degree,
                self._alloc.num_blocks // self.dp_degree))
            return stack
        return self._tp_ctx()

    # -------------------------------------------------------------- telemetry
    # Legacy counter attributes are read-only views over the registry cells
    # (internal code increments the cells; tests and callers keep reading
    # srv.iterations / srv.preempted / ... unchanged).
    @property
    def iterations(self) -> int:
        return int(self._c_iterations.value)

    @property
    def decode_steps(self) -> int:
        return int(self._c_decode_steps.value)

    @property
    def prefill_calls(self) -> int:
        return int(self._c_prefill_calls.value)

    @property
    def admitted(self) -> int:
        return int(self._c_admitted.value)

    @property
    def preempted(self) -> int:
        return int(self._c_preempted.value)

    @property
    def prompt_tokens(self) -> int:
        return int(self._c_prompt_tokens.value)

    @property
    def prefix_hit_tokens(self) -> int:
        return int(self._c_prefix_hit_tokens.value)

    @property
    def spec_rounds(self) -> int:
        return int(self._c_spec_rounds.value)

    @property
    def drafted_tokens(self) -> int:
        return int(self._c_drafted.value)

    @property
    def accepted_tokens(self) -> int:
        return int(self._c_accepted.value)

    @property
    def invariant_checks_run(self) -> int:
        return int(self._c_invariant_checks.value)

    def _emit_trace_event(self, entry) -> None:
        """Sentry trace callback (``analysis/sentry.py``): every (re)trace
        of a registered jitted body lands on the timeline — a ``retrace``
        event is contract drift made visible next to the scheduler events
        that triggered it."""
        over = entry.budget is not None and entry.traces > entry.budget
        self.timeline.instant("retrace" if over else "jit_trace",
                              entry=entry.name, traces=entry.traces)

    def dump_trace(self, path: str) -> str:
        """Write the per-request trace timeline as Chrome ``trace_event``
        JSON (open at https://ui.perfetto.dev); returns ``path``.  The
        ring holds the most recent ``trace_capacity`` events —
        ``stats()['trace_events_dropped']`` says how much history fell
        off."""
        return self.timeline.dump(
            path, process_name=f"serving:{self.engine.module.name}")

    # ------------------------------------------------------------ compiled fns
    def note_flow(self, uid, flow_id: int) -> None:
        """Register a Chrome flow id for a routed request: admission will
        emit the matching flow-finish (``f``) event, linking the router's
        ``route`` flow-start to this replica's admission in the merged
        fleet trace (``telemetry/aggregate.merge_chrome_traces``).  The
        caller (the :class:`~deepspeed_tpu.serving.ReplicaRouter`) owns
        flow-id uniqueness across every ring that will be merged."""
        self._flow_ids[uid] = int(flow_id)

    def slo_report(self) -> Dict[str, Dict[str, Any]]:
        """Per-``slo_class`` attainment report (``telemetry/slo.py``):
        requests, TTFT/TPOT attainment against the configured targets,
        merged percentiles, and error-budget burn rates."""
        return self._slo.report()

    def flops_report(self, peak_flops: Optional[float] = None,
                     window_s: Optional[float] = None) -> Dict[str, Any]:
        """FLOPs/MFU snapshot (``telemetry/flops.py``): per-program FLOPs
        from XLA cost analysis (analytic fallback), cumulative
        ``serving_model_flops_total``, the MFU gauge against
        ``peak_flops`` (defaults to the constructor's), and the
        prefill/decode/swap/idle busy-fraction breakdown from the
        timeline.  Profiling lowers raw program bodies only — it never
        compiles and never ticks the recompile sentry."""
        if self._flops_profiler is None:
            from ..telemetry.flops import ServingFlopsProfiler

            self._flops_profiler = ServingFlopsProfiler(
                self, peak_flops=self.peak_flops)
        return self._flops_profiler.report(peak_flops=peak_flops,
                                           window_s=window_s)

    @property
    def compile_count(self) -> int:
        return len(self.compiled_programs)

    def _donate(self):
        # donating the pool avoids a full cache copy per step; XLA:CPU
        # ignores donation with a warning, so only ask for it on TPU
        return (1,) if jax.default_backend() == "tpu" else ()

    def _constrain_pool(self, cache):
        """dp_tp only: pin the cache OUTPUT of every decode/prefill program
        to the committed pool sharding.  Input shardings are part of the
        jit cache key — without this, a prefill that resharded the pool
        would hand the next decode a differently-placed argument and force
        a silent retrace the sentry would (rightly) flag."""
        if self.dp_degree <= 1:
            return cache
        sharding = self._pool_sharding
        return jax.tree_util.tree_map(
            lambda x: jax.lax.with_sharding_constraint(x, sharding), cache)

    def _next_tokens(self, logits, samp):
        """The per-row token rule shared by every program body: argmax for
        a greedy-only engine; otherwise a per-row ``where(temp > 0)``
        select between the on-device sampler (``ops/sampling.py``,
        counter-keyed by the row's seed + emitted count) and the SAME
        masked argmax — so one traced program serves mixed
        greedy+sampled+constrained batches with zero recompiles and the
        temp=0 rows stay bit-identical to the legacy greedy path."""
        if samp is None:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        temps, topks, topps, seeds, counts, masks = samp
        greedy, lp = sampling_ops.filtered_logprobs(
            logits, temps, topks, topps, masks)
        keys = sampling_ops.slot_keys(seeds, counts,
                                      sampling_ops.SALT_TOKEN)
        return jnp.where(temps > 0,
                         sampling_ops.sample_tokens(lp, keys), greedy)

    @staticmethod
    def _pack_samp(tail):
        """Normalize a program's ``*samp`` operand tail: ``()`` (greedy
        engine) -> None, a 5-tuple (no mask operand) -> 6-tuple with
        ``masks=None``."""
        if not tail:
            return None
        t = tuple(tail)
        return t + (None,) if len(t) == 5 else t

    def _samp_args(self, counts):
        """The per-dispatch sampling operand tail (slot-indexed): the
        per-slot knob vectors + this dispatch's emitted-count vector
        (+ the mask matrix when the engine carries the mask operand).
        Empty for sampling=False engines — callers splat it, so greedy
        engines keep the exact legacy call signature."""
        if not self.sampling:
            return ()
        args = (jnp.asarray(self._temps), jnp.asarray(self._topks),
                jnp.asarray(self._topps), jnp.asarray(self._seeds),
                jnp.asarray(np.asarray(counts, np.int32)))
        if self.logit_masks:
            args += (jnp.asarray(self._masks),)
        return args

    def _decode_counts(self):
        """[slots] emitted counts for a decode-phase dispatch (idle rows
        0 — their greedy lane never touches the PRNG)."""
        counts = np.zeros(self.slots, np.int32)
        for slot, st in self._active.items():
            if st.phase == "decode":
                counts[slot] = st.gen_count
        return counts

    def _samp_args_rows(self, group, rows):
        """The ROW-gathered sampling operand tail for a prefill dispatch
        (prefill batches arbitrary slots into ``rows`` rows; pad rows
        keep the greedy/unmasked defaults, so their discarded lane never
        touches the PRNG)."""
        if not self.sampling:
            return ()
        temps = np.zeros(rows, np.float32)
        topks = np.zeros(rows, np.int32)
        topps = np.ones(rows, np.float32)
        seeds = np.zeros(rows, np.uint32)
        counts = np.zeros(rows, np.int32)
        for row, slot in enumerate(group):
            temps[row] = self._temps[slot]
            topks[row] = self._topks[slot]
            topps[row] = self._topps[slot]
            seeds[row] = self._seeds[slot]
            counts[row] = self._active[slot].gen_count
        args = (jnp.asarray(temps), jnp.asarray(topks),
                jnp.asarray(topps), jnp.asarray(seeds),
                jnp.asarray(counts))
        if self.logit_masks:
            masks = np.ones((rows, self._vocab), bool)
            for row, slot in enumerate(group):
                masks[row] = self._masks[slot]
            args += (jnp.asarray(masks),)
        return args

    def _refresh_masks(self) -> None:
        """Rebuild every constrained slot's ``[vocab]`` mask row from its
        host-side builder (``inference/constrain.py`` protocol: bool
        allow-vector over the generated-so-far tokens + remaining
        budget).  Runs once per scheduler iteration BEFORE the dispatches
        — tokens only commit at iteration boundaries, so one refresh
        covers prefill-emit, decode, and verify alike.  Unconstrained
        rows were reset to all-True at admit/release and stay that way."""
        if self._masks is None:
            return
        for slot, st in self._active.items():
            mb = st.req.mask_builder
            if mb is None:
                continue
            row = np.asarray(
                mb.allowed(st.prior + st.out,
                           st.req.max_new_tokens - st.gen_count),
                dtype=bool)
            if row.shape != (self._vocab,):
                raise ValueError(
                    f"request {st.req.uid!r}: mask_builder.allowed() "
                    f"returned shape {row.shape}, expected "
                    f"({self._vocab},) (the model's vocab_size)")
            self._masks[slot, :] = row

    def _get_decode_fn(self):
        if self._decode_fn is None:
            fwd, prepare = self._fwd, self.engine._prepare
            K, constrain = self._K, self._constrain_pool
            next_tokens = self._next_tokens

            def step_core(params, cache, tokens, lengths, block_tables,
                          samp):
                logits, cache = fwd(prepare(params), tokens[:, None], cache,
                                    0, lengths=lengths,
                                    block_tables=block_tables)
                return next_tokens(logits, samp), constrain(cache)

            def fused_core(params, cache, tokens, lengths, block_tables,
                           active, budgets, eos_ids, samp):
                """K decode steps in ONE ``lax.while_loop``: per-slot
                eos/budget checks live on-device behind the fixed-shape
                ``active`` mask; ``out[slot, i]`` is the i-th token the
                window committed for the slot, ``-1`` past its end (eos
                fired or per-slot budget spent).  Frozen rows keep feeding
                their last token at a frozen length — an idempotent
                rewrite of already-written KV, never a new position — so
                the loop stays fixed-shape with no gather/compaction.
                Sampled rows draw step ``i`` with the counter key
                ``counts + i`` — the same keys the K=1 path uses, so
                fused and plain sampled streams are token-identical."""
                p = prepare(params)
                out0 = jnp.full((tokens.shape[0], K), -1, jnp.int32)

                def cond(state):
                    i, _, _, _, act, _ = state
                    return (i < K) & jnp.any(act)

                def body(state):
                    i, toks, lens, cache, act, out = state
                    logits, cache = fwd(p, toks[:, None], cache, 0,
                                        lengths=lens,
                                        block_tables=block_tables)
                    cache = constrain(cache)
                    if samp is None:
                        nxt = next_tokens(logits, None)
                    else:
                        temps, topks, topps, seeds, counts, masks = samp
                        nxt = next_tokens(logits, (temps, topks, topps,
                                                   seeds, counts + i, masks))
                    out = out.at[:, i].set(jnp.where(act, nxt, -1))
                    lens = lens + act.astype(lens.dtype)
                    toks = jnp.where(act, nxt, toks)
                    act = act & (nxt != eos_ids) & (i + 1 < budgets)
                    return (i + 1, toks, lens, cache, act, out)

                _, _, _, cache, _, out = jax.lax.while_loop(
                    cond, body,
                    (jnp.int32(0), tokens, lengths, cache, active, out0))
                return out, cache

            # *samp is the engine's sampling operand tail — () for
            # sampling=False (the exact legacy programs, bit-path
            # identical), (temps, topks, topps, seeds, counts[, masks])
            # otherwise; _samp_args builds it to match per dispatch
            pack = self._pack_samp

            def decode_step(params, cache, tokens, lengths, block_tables,
                            *samp):
                return step_core(params, cache, tokens, lengths,
                                 block_tables, pack(samp))

            def decode_fused(params, cache, tokens, lengths, block_tables,
                             active, budgets, eos_ids, *samp):
                return fused_core(params, cache, tokens, lengths,
                                  block_tables, active, budgets, eos_ids,
                                  pack(samp))

            # the fused program REPLACES the per-token decode program —
            # same sentry entry, same compile budget
            body_fn = decode_step if K == 1 else decode_fused
            if self.resident_window_blocks:
                # the windowed program also REPLACES plain decode (same
                # sentry entry, +0 budget): window_start rides as a
                # [slots] int32 operand and the mask is traced into the
                # body via the ops-module window context (entered HERE,
                # inside the traced python, so only this program bakes it)
                lm_tokens = self._landmark_blocks * self.block_size

                def decode_windowed(params, cache, tokens, lengths,
                                    block_tables, window_start, *samp):
                    # *samp is the engine's sampling operand tail (empty
                    # for sampling=False) — the window wrapper stays
                    # agnostic to it
                    with decode_attention.window_context(
                            window_start, lm_tokens):
                        return decode_step(params, cache, tokens,
                                           lengths, block_tables, *samp)

                body_fn = decode_windowed
            self._program_bodies["decode"] = body_fn
            self._decode_fn = jax.jit(self.sentry.wrap(body_fn, "decode"),
                                      donate_argnums=self._donate())
            self.compiled_programs.append(
                ("decode", self.slots) if K == 1
                else ("decode", self.slots, K))
        return self._decode_fn

    def _get_prefill_fn(self, width: int):
        """One compiled prefill program per window length: chunked mode uses
        a single ``prefill_chunk`` width, bucketed mode one per bucket.
        With a draft model, the draft's prefill is FUSED into the same
        program (both caches advance through the identical window/table
        contract), so speculative prefill still costs one program."""
        fwd, prepare = self._fwd, self.engine._prepare
        draft = self._draft
        constrain = self._constrain_pool

        next_tokens, pack = self._next_tokens, self._pack_samp

        def build():
            def prefill(params, cache, ids, block_tables, base, valid,
                        *samp):
                """ids [J, width] right-padded; base int32 [J] per-row chunk
                start (reused-prefix length for fresh slots); valid int32
                [J] real tokens per row (pads write to scratch block 0).
                *samp is the ROW-gathered sampling tail (empty for greedy
                engines): the first emitted token of a sampled request
                draws with the SAME counter key (seed, emitted count) the
                decode path would use — that is what makes preempt/crash
                resumes, which re-emit through prefill, token-exact."""
                logits, cache = fwd(prepare(params), ids, cache, base,
                                    lengths=valid, block_tables=block_tables)
                return next_tokens(logits, pack(samp)), constrain(cache)

            if self.resident_window_blocks:
                # windowed prefill REPLACES the plain program (+0 budget):
                # later chunks of a giant prompt must not attend into the
                # demoted middle (those table entries now point at
                # scratch), so the same window mask gates the T > 1 path
                lm_tokens = self._landmark_blocks * self.block_size

                def prefill_windowed(params, cache, ids, block_tables,
                                     base, valid, window_start, *samp):
                    with decode_attention.window_context(
                            window_start, lm_tokens):
                        return prefill(params, cache, ids, block_tables,
                                       base, valid, *samp)

                self._program_bodies.setdefault("prefill", {})[width] = \
                    prefill_windowed
                return jax.jit(
                    self.sentry.wrap(prefill_windowed,
                                     f"prefill[w{width}]"),
                    donate_argnums=self._donate())
            if draft is None:
                self._program_bodies.setdefault("prefill", {})[width] = \
                    prefill
                return jax.jit(
                    self.sentry.wrap(prefill, f"prefill[w{width}]"),
                    donate_argnums=self._donate())
            dfwd = draft.module.decode_hooks["forward_cached"]
            dprepare = draft._prepare

            def prefill_fused(params, dparams, cache, dcache, ids,
                              block_tables, base, valid, *samp):
                first, cache = prefill(params, cache, ids, block_tables,
                                       base, valid, *samp)
                _, dcache = dfwd(dprepare(dparams), ids, dcache, base,
                                 lengths=valid, block_tables=block_tables)
                return first, cache, dcache

            self._program_bodies.setdefault("prefill", {})[width] = \
                prefill_fused
            self._program_meta["prefill_fused"] = True
            return jax.jit(
                self.sentry.wrap(prefill_fused, f"prefill[w{width}]"),
                donate_argnums=(2, 3) if self._donate() else ())

        return self._prefill_fns.get_or_build(
            width, build,
            on_build=lambda _: self.compiled_programs.append(
                ("prefill", width, self.prefill_batch)))

    def _get_verify_fn(self):
        """The speculative K+1 verify program: one fixed-shape paged
        forward through the chunked-prefill T>1 path, scoring EVERY
        window position (the ``all_positions`` verify head) — this
        replaces the single-token decode program entirely in speculative
        mode.  On a sampling engine the same forward feeds the
        distribution-exact rejection sampler (delta-proposal form of
        Leviathan/Chen: the proposer — draft model OR n-gram — is treated
        as a point mass at its proposed token ``d``, so accept w.p.
        ``p_target(d)`` and resample the zeroed-``d`` residual on
        reject); ``temperature == 0`` rows degenerate bit-exactly to the
        greedy prefix-match, so one program serves mixed traces."""
        if self._verify_fn is None:
            fwd, prepare = self._fwd, self.engine._prepare
            k, pack = self.spec_tokens, self._pack_samp

            def verify(params, cache, ids, block_tables, base, valid,
                       *samp):
                """ids [slots, K+1] = [pending, d_1..d_K] per row; base
                int32 [slots] committed lengths; valid int32 [slots] real
                window tokens (0 for non-decode rows — all writes land in
                scratch).  Greedy engines return ``(scored, cache)``;
                sampling engines return ``(scored, accept, plain, resid,
                cache)`` where ``accept[s, i]`` is the rejection verdict
                for draft ``d_{i+1}`` and the two tail lanes are the
                draws the host walker picks between BY STOP REASON:
                ``resid[s, i]`` (residual draw) when the walk stopped
                because ``accept[s, i]`` is False, ``plain[s, i]``
                (unconditional target draw) when it stopped at the
                accept cap or the all-accepted bonus position — a cap
                stop never consumed the verdict, so blending on it
                would bias the emission (see docs/inference.md)."""
                logits, cache = fwd(prepare(params), ids, cache, base,
                                    lengths=valid, block_tables=block_tables,
                                    all_positions=True)
                samp_t = pack(samp)
                if samp_t is None:
                    return jnp.argmax(logits, -1).astype(jnp.int32), cache
                temps, topks, topps, seeds, counts, masks = samp_t
                slots, width = ids.shape          # width == K + 1
                flat = logits.reshape((-1, logits.shape[-1]))
                rep = lambda x: jnp.repeat(x, width)  # noqa: E731
                mrep = None if masks is None else \
                    jnp.repeat(masks, width, axis=0)
                greedy, lp = sampling_ops.filtered_logprobs(
                    flat, rep(temps), rep(topks), rep(topps), mrep)
                scored = greedy.reshape(slots, width)
                lp = lp.reshape(slots, width, -1)
                # accept test: position i decides emission counts + i
                pos = lp[:, :-1].reshape((-1, lp.shape[-1]))  # [S*K, V]
                drafts = ids[:, 1:].reshape(-1)
                p_d = sampling_ops.token_probs(pos, drafts) \
                    .reshape(slots, k)
                u = sampling_ops.accept_uniforms(sampling_ops.grid_keys(
                    seeds, counts, sampling_ops.SALT_ACCEPT, k))
                accept = u < p_d
                # tail lanes: the plain draw (accept-cap / bonus stop)
                # and the residual draw (rejection stop) share the
                # RESIDUAL-salt key at their emission index — the host
                # walker consumes exactly ONE of them per round (at the
                # single stop position), and the accept uniforms live on
                # their own salt, so the consumed stream stays i.i.d.
                # They are returned SEPARATELY: only the walker knows the
                # stop reason, and a cap stop (draft-model K-1 cap,
                # constrained cap 0) leaves accept[a] unconsumed — a
                # device-side where(accept, plain, resid) blend there
                # would emit marginal p(x)(1 + q) / q^2 instead of p.
                fkeys = sampling_ops.grid_keys(
                    seeds, counts, sampling_ops.SALT_RESIDUAL, width)
                fkeys = fkeys.reshape((-1,) + fkeys.shape[2:])
                plain = sampling_ops.sample_tokens(
                    lp.reshape((-1, lp.shape[-1])), fkeys) \
                    .reshape(slots, width)
                rkeys = sampling_ops.grid_keys(
                    seeds, counts, sampling_ops.SALT_RESIDUAL, k)
                resid = sampling_ops.sample_tokens(
                    sampling_ops.residual_logits(pos, drafts),
                    rkeys.reshape((-1,) + rkeys.shape[2:])) \
                    .reshape(slots, k)
                # temp == 0 rows: bit-exact greedy (already implied by the
                # one-hot algebra; the select makes it unconditional)
                plain = jnp.where(temps[:, None] > 0, plain, scored)
                resid = jnp.where(temps[:, None] > 0, resid,
                                  scored[:, :k])
                return scored, accept, plain, resid, cache

            self._program_bodies["verify"] = verify
            self._verify_fn = jax.jit(self.sentry.wrap(verify, "verify"),
                                      donate_argnums=self._donate())
            self.compiled_programs.append(
                ("verify", self.slots, self.spec_tokens + 1))
        return self._verify_fn

    def _get_draft_fn(self):
        """The draft rollout program: K single-token steps of the draft
        model inside ONE ``lax.scan`` — the whole proposal costs one
        compiled program per trace, and the draft pool advances through the
        target's own block tables.  Sampling engines draw each step from
        the draft's own filtered distribution (DRAFT-salt counter keys, so
        proposals are deterministic from the committed prefix — what makes
        re-homed replay round-identical); the delta-form rejection
        verifier needs no draft probabilities back, so the output shape is
        unchanged.  Drafts never see logit masks: constrained slots run
        with ``max_accept = 0`` host-side."""
        if self._draft_fn is None:
            draft = self._draft
            dfwd = draft.module.decode_hooks["forward_cached"]
            dprepare = draft._prepare
            k, pack = self.spec_tokens, self._pack_samp

            def propose(dparams, dcache, tokens, lengths, block_tables,
                        *samp):
                dp = dprepare(dparams)
                samp_t = pack(samp)

                def rollout_step(carry, i):
                    tok, lens, cache = carry
                    logits, cache = dfwd(dp, tok[:, None], cache, 0,
                                         lengths=lens,
                                         block_tables=block_tables)
                    if samp_t is None:
                        nxt = jnp.argmax(logits, axis=-1) \
                            .astype(jnp.int32)
                    else:
                        temps, topks, topps, seeds, counts, _ = samp_t
                        greedy, lp = sampling_ops.filtered_logprobs(
                            logits, temps, topks, topps, None)
                        keys = sampling_ops.slot_keys(
                            seeds, counts + i, sampling_ops.SALT_DRAFT)
                        nxt = jnp.where(
                            temps > 0,
                            sampling_ops.sample_tokens(lp, keys), greedy)
                    return (nxt, lens + 1, cache), nxt

                (_, _, dcache), drafts = jax.lax.scan(
                    rollout_step, (tokens, lengths, dcache),
                    jnp.arange(k, dtype=jnp.int32))
                return drafts.T, dcache            # [slots, K]

            self._program_bodies["draft"] = propose
            self._draft_fn = jax.jit(
                self.sentry.wrap(propose, "draft"),
                donate_argnums=(1,) if self._donate() else ())
            self.compiled_programs.append(("draft", self.slots, k))
        return self._draft_fn

    # ------------------------------------------------------------- tiered KV
    def _swap_pools(self):
        """The tree the host tier mirrors: the target pool, plus the draft
        pool when speculative decoding carries one (they share block
        tables, so block residency is joint)."""
        return (self._cache, self._dcache) if self._dcache is not None \
            else self._cache

    def _set_swap_pools(self, pools) -> None:
        if self._dcache is not None:
            self._cache, self._dcache = pools
        else:
            self._cache = pools

    def _swap_leaf_shardings(self):
        """One sharding per flattened swap-tree leaf for the staging
        ``device_put``: target-pool leaves follow ``kv_sharded``, draft
        leaves follow ``_dcache_sharded`` — the two can differ (a GQA
        draft whose head count does not divide tp stays replicated while
        the target pool shards), and staging a replicated leaf with the
        head-sharded spec would raise at ``device_put``."""
        rep = NamedSharding(self.engine.mesh, P())
        hs = NamedSharding(self.engine.mesh, P(None, None, TP_AXIS))
        tgt = jax.tree_util.tree_map(
            lambda _: hs if self.kv_sharded else rep, self._cache)
        if self._dcache is None:
            return jax.tree_util.tree_leaves(tgt)
        drf = jax.tree_util.tree_map(
            lambda _: hs if self._dcache_sharded else rep, self._dcache)
        return jax.tree_util.tree_leaves((tgt, drf))

    def _get_demote_fn(self):
        """The fixed-shape block-gather program (``swap_batch`` ids, pad =
        scratch): its output is what one ``jax.device_get`` pulls per
        demotion batch."""
        if self._demote_fn is None:
            def kv_demote(cache, ids):
                return paged_kv.paged_block_gather(cache, ids)

            self._demote_fn = jax.jit(
                self.sentry.wrap(kv_demote, "kv_demote"),
                donate_argnums=())        # the pool lives on
            self.compiled_programs.append(("kv_demote", self.swap_batch))
        return self._demote_fn

    def _get_promote_fn(self):
        """The fixed-shape block-scatter program committing staged
        (device_put-ahead) host blocks into the pool."""
        if self._promote_fn is None:
            def kv_promote(cache, staged, ids):
                return paged_kv.paged_block_scatter(cache, staged, ids)

            self._promote_fn = jax.jit(
                self.sentry.wrap(kv_promote, "kv_promote"),
                donate_argnums=(0,) if self._donate() else ())
            self.compiled_programs.append(("kv_promote", self.swap_batch))
        return self._promote_fn

    def warm_swap_programs(self) -> None:
        """Compile the fixed-shape ``kv_demote``/``kv_promote`` pair ahead
        of traffic with a no-op round trip through the scratch block
        (gather scratch, scatter it back onto itself — byte-neutral by
        construction).  Without this, the first real demotion or
        promotion pays the compile inside a latency-sensitive admission —
        the router calls it on drain targets before migrated sessions
        land.  No-op when the tier is off; the programs are the same two
        sentry-registered entries either way (budget unchanged)."""
        if self._host is None:
            return
        if self._demote_fn is not None and self._promote_fn is not None:
            return                          # both already compiled
        ids = jnp.zeros(self.swap_batch, jnp.int32)
        with self._tp_ctx():
            staged = self._get_demote_fn()(self._swap_pools(), ids)
            self._set_swap_pools(
                self._get_promote_fn()(self._swap_pools(), staged, ids))

    # ------------------------------------------------------- fault injection
    def arm_faults(self, injector) -> None:
        """Arm (or, with ``None``, disarm) a fault-injection view on this
        engine (``serving/faults.py`` — a :class:`FaultInjector` bound to
        this replica's id).  The armed view is consulted at every
        scheduler iteration (crash/stall/corruption events) and at every
        swap-transport operation (transient/permanent transport faults);
        unarmed, each injection point is a single ``is None`` predicate."""
        self._fault_injector = injector

    def _swap_transport_ok(self, op: str) -> bool:
        """Gate one engine-internal swap-transport operation (demote /
        promote) through the armed fault plan with bounded deterministic
        retry: transient faults back off exponentially
        (``_transport_backoff_s * 2^attempt``) and retry up to
        ``_transport_retries`` times; a permanent fault (or an exhausted
        budget) returns ``False`` — the caller falls back to dropping
        the demotion or recomputing the chain (docs/reliability.md).
        Always ``True`` when no plan is armed."""
        inj = self._fault_injector
        if inj is None:
            return True
        for attempt in range(self._transport_retries + 1):
            try:
                inj.on_transport(op)
                return True
            except TransportError as e:
                # TransportError ONLY: anything else out of the
                # injector is a bug and must propagate, not masquerade
                # as a quiet permanent transport fault
                self.timeline.instant("transport_fault", op=op,
                                      attempt=attempt,
                                      transient=e.transient)
                if not e.transient:
                    break
                if self._transport_backoff_s:
                    time.sleep(self._transport_backoff_s * (2 ** attempt))
        return False

    def _verified_keys(self, keys: List[bytes]) -> List[bytes]:
        """Integrity gate at the points host-tier bytes LEAVE the arena
        (promotion staging, prefetch staging, cross-replica export):
        verify EVERY entry in the probed run against its stored
        checksum, drop every corrupt one, and truncate the usable run
        at the first failure (the chain is only walkable contiguously)
        — so each corrupt block in a probed run is detected exactly
        once, counted, and recomputed from tokens; corrupt KV is never
        staged, exported, or served.  An entry a live staged record
        still pins keeps its slot (the staged device copy predates the
        corruption and is clean); it drops on the next unpinned pass."""
        cut = len(keys)
        for i, key in enumerate(keys):
            if self._host.is_spilled(key):
                # no arena bytes to check yet: a spilled entry verifies at
                # the NVMe exit instead (promote_spilled — per-op aio
                # status + checksum re-hash), before staging can read it
                continue
            if self._host.verify(key):
                continue
            cut = min(cut, i)
            if any(key in rec["keys"] for rec in self._staged.values()):
                # pinned by a live staged record: truncate (never serve
                # it) but COUNT only on the pass that actually drops it
                # — otherwise every re-probe of the same pinned entry
                # would re-count one corruption
                continue
            self._c_checksum_fail.inc()
            self.timeline.instant("checksum_fail", key=key.hex()[:16],
                                  block_index=i)
            self._host.drop_corrupt(key)
        return keys[:cut]

    def scrub_host_tier(self) -> int:
        """Patrol scrub (the background-scrubber primitive real storage
        tiers run): verify EVERY resident host-tier entry against its
        stored checksum and drop the corrupt ones — entries shadowed
        behind an earlier corrupt block in their chain would otherwise
        sit undetected until (if ever) probed.  In-flight entries are
        skipped (their staged device copies predate the corruption and
        are clean; they drop on the next pass).  Counted into
        ``serving_checksum_failures_total``; returns entries dropped.
        O(arena bytes) — run it between traffic, not per iteration."""
        if self._host is None:
            return 0
        dropped = 0
        for key, e in list(self._host._entries.items()):
            if e.in_flight or self._host.verify(key):
                continue
            self._c_checksum_fail.inc()
            self.timeline.instant("checksum_fail", key=key.hex()[:16],
                                  scrub=True)
            self._host.drop_corrupt(key)
            dropped += 1
        if dropped:
            self.timeline.instant("host_scrub", dropped=dropped,
                                  resident=len(self._host))
        return dropped

    def _sync_nvme_metrics(self) -> None:
        """Mirror :class:`NvmeBlockStore` counter deltas into the metrics
        registry at the swap commit points (sanctioned sync helper, lint
        GL007 naming): ``tier="nvme"`` swap/byte counters, the spill-file
        occupancy gauge, ``nvme_spill``/``nvme_load`` timeline instants,
        and NVMe-exit checksum rejects folded into
        ``serving_checksum_failures_total`` (one integrity ledger across
        every tier boundary)."""
        if self._nvme is None:
            return
        h = self._host
        d_out = h.nvme_spills - self._nvme_spills_seen
        d_in = h.nvme_loads - self._nvme_loads_seen
        d_rej = h.nvme_checksum_rejects - self._nvme_rejects_seen
        if d_out:
            self._nvme_spills_seen = h.nvme_spills
            self._c_nvme_out.inc(d_out)
            self._c_nvme_bytes.inc(d_out * h.block_nbytes)
            self.timeline.instant("nvme_spill", blocks=d_out,
                                  bytes=d_out * h.block_nbytes)
        if d_in:
            self._nvme_loads_seen = h.nvme_loads
            self._c_nvme_in.inc(d_in)
            self._c_nvme_bytes.inc(d_in * h.block_nbytes)
            self.timeline.instant("nvme_load", blocks=d_in,
                                  bytes=d_in * h.block_nbytes)
        if d_rej:
            self._nvme_rejects_seen = h.nvme_checksum_rejects
            self._c_checksum_fail.inc(d_rej)
            self.timeline.instant("checksum_fail", op="nvme",
                                  blocks=d_rej)
        self._g_nvme_in_use.set(h.nvme_blocks_in_use)

    def _demote_blocks(self, blocks: List[int], keys: List[bytes]) -> int:
        """Copy the given device blocks into the host arena under their
        chain keys — the sanctioned blocking demotion helper (lint GL007):
        one gather program call + ONE ``jax.device_get`` per ``swap_batch``
        batch, host-arena writes, no per-block syncs.  Returns the blocks
        actually stored (the arena can refuse when it is full of in-flight
        entries — the demotion is then simply dropped; contents stay
        recomputable)."""
        if blocks and not self._swap_transport_ok("demote"):
            return 0                      # dropped: contents recomputable
        m = self.swap_batch
        stored = 0
        swap_t0 = self.timeline.now_us()
        for i in range(0, len(blocks), m):
            chunk_b = blocks[i:i + m]
            chunk_k = keys[i:i + m]
            ids = np.zeros(m, np.int32)
            ids[:len(chunk_b)] = chunk_b
            with self._tp_ctx():
                staged = self._get_demote_fn()(self._swap_pools(),
                                               jnp.asarray(ids))
            host = jax.device_get(staged)          # one D2H per batch
            leaves = jax.tree_util.tree_leaves(host)
            for j, key in enumerate(chunk_k):
                if self._host.put(key, [lf[:, j] for lf in leaves]) \
                        is not None:
                    stored += 1
        if blocks:
            # the demotion round trip as an X span: the FLOPs profiler's
            # busy-fraction breakdown reads "swap" span durations
            self.timeline.complete("swap", swap_t0, direction="out",
                                   blocks=len(blocks))
        if stored:
            self._c_swap_out.inc(stored)
            self._c_swap_bytes.inc(stored * self._host.block_nbytes)
            self.timeline.instant(
                "demote", blocks=stored,
                bytes=stored * self._host.block_nbytes)
        self._sync_nvme_metrics()   # arena stores may have spilled LRU tail
        return stored

    def _demote_evict_batch(self) -> int:
        """Tiered replacement for per-block ``evict_one`` under pool
        pressure: demote up to ``swap_batch`` LRU evictable prefix-cache
        leaves to the host tier in one device round trip, then release
        them — the freed blocks land on the free list with their contents
        preserved below.  Returns the number of blocks freed."""
        entries = self._prefix.evictable_leaves(self._alloc, self.swap_batch)
        if not entries:
            return 0
        blocks, keys, ekeys = [], [], []
        for e in entries:
            chain = self._prefix.chain_tokens(e)
            # chain is exactly (depth+1)*block_size tokens, so the block
            # index falls out of its length — no second parent walk
            key = chain_key(chain, len(chain) // self.block_size - 1,
                            self.block_size)
            ekeys.append(key)
            if not self._host.has(key):
                blocks.append(int(e.block))
                keys.append(key)
        if blocks:
            self._demote_blocks(blocks, keys)
        for e, key in zip(entries, ekeys):
            b = int(e.block)
            self._prefix.evict_entry(e, self._alloc)
            self._kv_scale_live.discard(b)
            # demoted=True iff the tier really holds the bytes now (a
            # saturated arena can refuse the store — then this eviction
            # discarded contents, exactly like the untiered path)
            self.timeline.instant("evict_block", block=b,
                                  demoted=self._host.has(key))
        return len(entries)

    def _demote_slot_blocks(self, slot: int, st: "_SlotState") -> None:
        """Preemption demotion: move the victim's exclusively-owned full
        blocks (committed content only) to the host tier before its slot
        releases — on resume, the same chain keys (generated tokens fold
        into the resume prompt) promote them back, so the recompute that
        used to re-run the whole prefix shrinks to the unfinished tail."""
        committed = max(int(self._lengths[slot]), st.base)
        seq = np.concatenate([st.prompt_eff, np.asarray(st.out, np.int32)])
        full = min(committed, seq.size) // self.block_size
        run = chain_keys(seq, full, self.block_size)
        blocks, keys = [], []
        for i in range(full):
            b = int(self._tables[slot, i])
            if b == 0 or self._alloc.refcount(b) != 1:
                continue       # shared (trie / other slot): stays on device
            if not self._host.has(run[i]):
                blocks.append(b)
                keys.append(run[i])
        if blocks:
            self._demote_blocks(blocks, keys)

    def _slide_windows(self) -> None:
        """Resident-window maintenance (one pass per scheduler iteration):
        for every active slot whose committed span has outgrown
        ``resident_window_blocks``, demote the oldest non-landmark block
        run to the host tier under its chain keys, zero the table entries
        (the windowed programs' attention mask already hides those
        positions — zeroed entries read scratch garbage that never
        reaches the softmax), release the blocks, and advance the slot's
        window start.  The demoted middle stays recoverable through the
        ordinary host/NVMe promotion path (e.g. for a later re-prefill at
        full attention); NOTHING downstream of the table sees a special
        case — the hole is just more scratch entries."""
        if not self.resident_window_blocks:
            return
        W, lm = self.resident_window_blocks, self._landmark_blocks
        for slot in sorted(self._active):
            st = self._active[slot]
            committed = max(int(self._lengths[slot]), st.base)
            nfull = committed // self.block_size
            f = max(st.window_blk, lm)
            cut = nfull - W
            if cut <= f:
                continue
            seq = np.concatenate([st.prompt_eff,
                                  np.asarray(st.out, np.int32)])
            run = chain_keys(seq, min(cut, seq.size // self.block_size),
                             self.block_size)
            demote_b, demote_k = [], []
            for li in range(f, cut):
                b = int(self._tables[slot, li])
                if b == 0 or li >= len(run):
                    continue
                if self._alloc.refcount(b) == 1 \
                        and not self._host.has(run[li]):
                    demote_b.append(b)
                    demote_k.append(run[li])
            if demote_b:
                self._demote_blocks(demote_b, demote_k)
            freed = 0
            for li in range(f, cut):
                b = int(self._tables[slot, li])
                if b == 0:
                    continue
                # drop THIS slot's mapping + reference; a trie-shared
                # block stays alive under the trie's refs and frees when
                # the LRU eviction path gets to it
                self._decref(b)
                self._held[slot].remove(b)
                self._tables[slot, li] = 0
                freed += 1
            st.window_blk = cut
            self._window_start[slot] = cut * self.block_size
            self._c_window_slides.inc()
            self.timeline.instant(
                "window_slide", slot=slot, uid=str(st.req.uid),
                window_start=cut * self.block_size, blocks_freed=freed,
                demoted=len(demote_b))

    def _stage_chunks(self, keys: List[bytes]):
        """Assemble host-resident blocks into ``swap_batch``-shaped staging
        buffers and issue their H2D ``jax.device_put`` (async — dispatch
        returns immediately, the copy overlaps whatever the device is
        running).  Marks every key in-flight; returns
        ``[(keys_chunk, staged_tree), ...]``."""
        m = self.swap_batch
        chunks = []
        shardings = self._staging_shardings
        template = jax.tree_util.tree_structure(self._swap_pools())
        for i in range(0, len(keys), m):
            chunk = keys[i:i + m]
            short = False
            if self._nvme is not None:
                # NVMe promotion staged through this same double-buffered
                # path: load the chunk's spilled entries back into arena
                # slots (verified at the NVMe exit) just before the read —
                # a shortfall (failed load, or the watermark budget is
                # holding earlier still-in-flight chunks) truncates the
                # stageable run here AND drops every later chunk (the
                # chain is only walkable contiguously); the tail stays
                # spilled and promotes on a later pass once the engine
                # pops the staged prefix
                n_ok = self._host.promote_spilled(chunk)
                self._sync_nvme_metrics()
                if n_ok < len(chunk):
                    chunk, short = chunk[:n_ok], True
                    if not chunk:
                        break
            per_leaf = None
            for j, key in enumerate(chunk):
                arrs = self._host.read(key)
                if per_leaf is None:
                    per_leaf = [
                        np.zeros((a.shape[0], m) + a.shape[1:], a.dtype)
                        for a in arrs]
                for buf, a in zip(per_leaf, arrs):
                    buf[:, j] = a
                self._host.mark_in_flight(key)
            staged = jax.tree_util.tree_unflatten(
                template, [jax.device_put(buf, sh)
                           for buf, sh in zip(per_leaf, shardings)])
            chunks.append((chunk, staged))
            if short:
                break
        return chunks

    def _issue_prefetch(self, pending) -> None:
        """End-of-iteration prefetch: probe the pending queue's first two
        requests for host-resident chains and stage their promotions NOW,
        one-plus scheduler iterations before admission can consume them —
        the double-buffered H2D overlap (``runtime/zero/param_stream.py``
        does the same for ZeRO-3 parameters)."""
        n = 0
        for item in pending:
            req, prior = item.req, item.prior
            if n >= 2 or len(self._staged) >= 2:   # double buffer
                break
            n += 1
            if req.uid in self._staged:
                continue
            # empty-probe memo: while neither the trie's refcount state
            # nor the host key set moved, re-probing the same request is
            # the same O(prompt) walk for the same empty answer — skip it
            # every idle iteration (the _blocked_gate trick, again)
            gate = (id(req), len(prior), self._alloc.version,
                    self._host.version)
            if self._prefetch_gate.get(req.uid) == gate:
                continue
            prompt_eff = np.concatenate(
                [req.prompt, np.asarray(prior, np.int32)]) \
                if prior else req.prompt
            plen = int(prompt_eff.size)
            n_dev = self._prefix.probe(prompt_eff, plen - 1)
            keys = self._host.probe_run(prompt_eff, n_dev, plen - 1,
                                        self.block_size)
            if keys:
                # never stage corrupt arena bytes toward the device
                # (integrity gate — the truncated tail recomputes)
                keys = self._verified_keys(keys)
            if not keys:
                self._prefetch_gate[req.uid] = gate
                continue
            # cap the staged DEVICE footprint at two swap batches per
            # request (a true chunk-level double buffer): a long session
            # chain would otherwise pin chain-length worth of staging
            # buffers next to a deliberately small pool for as long as
            # the queue head stays blocked — admission consumes the
            # staged prefix and stages the remainder there
            keys = keys[:2 * self.swap_batch]
            chunks = self._stage_chunks(keys)
            # NVMe shortfalls truncate inside _stage_chunks — the record
            # must name exactly the keys that really staged (and are
            # pinned in-flight), or a later discard would try to unflag
            # entries that never left the spill file
            keys = [k for ck, _ in chunks for k in ck]
            if not keys:
                self._prefetch_gate[req.uid] = gate
                continue
            self._staged[req.uid] = {"keys": keys, "chunks": chunks}
            self.timeline.instant("prefetch_issue", uid=str(req.uid),
                                  blocks=len(keys))

    def _unflag_keys(self, keys) -> None:
        """Roll staged keys back to plain host residency — UNLESS another
        live staged record still references them (two pending requests
        sharing a session prefix both stage the same chain; the in-flight
        pin must outlive either single record)."""
        still = {k for rec in self._staged.values() for k in rec["keys"]}
        for key in keys:
            if key not in still and self._host.has(key) \
                    and not self._host.is_spilled(key):
                # a spilled key has no arena entry to unflag (it re-spilled
                # or never promoted) — nothing to roll back
                self._host.mark_in_flight(key, False)

    def _discard_all_staged(self) -> None:
        recs = list(self._staged.values())
        self._staged.clear()
        for rec in recs:
            self._unflag_keys(rec["keys"])

    def _take_staged(self, uid, keys: List[bytes]):
        """Consume the prefetched staging for ``uid`` iff it covers a
        leading PREFIX of the chain admission resolved (prefetch caps its
        staged footprint, so a long chain's tail stages at admission); a
        mismatch discards it (and counts as a prefetch miss for the sync
        path)."""
        rec = self._staged.pop(uid, None)
        if rec is None:
            return None
        rk = rec["keys"]
        if len(rk) <= len(keys) and rk == keys[:len(rk)]:
            return rec["chunks"]
        self._unflag_keys(rk)
        return None

    def _promote_wait(self, staged) -> float:
        """Join an in-flight staging buffer (sanctioned blocking helper,
        lint GL007) and return how long admission actually stalled — 0 ≈
        the prefetch fully hid the transfer behind decode."""
        t0 = time.perf_counter()
        for leaf in jax.tree_util.tree_leaves(staged):
            leaf.block_until_ready()
        return time.perf_counter() - t0

    def _promote_chain(self, prompt_eff, plen: int, n_dev: int,
                       req) -> List[int]:
        """Promote the host-resident continuation of an admitted prompt's
        chain back into the device pool: consume the prefetched staging
        (or stage synchronously on a miss), allocate device blocks —
        reclaiming via batch demotion, never preemption; the admission
        gate already proved free + evictable covers the need — scatter the
        staged bytes in, and re-register the chain in the prefix trie from
        block ``n_dev`` on.  Returns the promoted physical blocks, claimed
        for the caller (one reference each, like ``PrefixCache.lookup``).
        Partial promotion (pool pressure mid-run) keeps the unpromoted
        tail host-resident."""
        keys = self._host.probe_run(prompt_eff, n_dev, plen - 1,
                                    self.block_size)
        # integrity gate: a corrupt entry truncates the promotable run
        # (dropped + counted; the tail recomputes), and a transport
        # fault that survives the bounded retry abandons the promotion
        # entirely — both fall back to the ordinary prefill recompute
        if keys:
            keys = self._verified_keys(keys)
        if keys and not self._swap_transport_ok("promote"):
            keys = []
        if not keys:
            # nothing host-resident to promote — but a prefetch staged for
            # this request may still exist (a sharing request promoted the
            # chain first, the trie drifted, or the run was just dropped
            # by the integrity/transport gate): it dies WITH the
            # admission, or its record would pin in-flight entries and
            # occupy the double buffer for the rest of the trace
            rec = self._staged.pop(req.uid, None)
            if rec is not None:
                self._unflag_keys(rec["keys"])
            return []
        chunks = self._take_staged(req.uid, keys)
        miss = chunks is None
        if miss:
            self._c_prefetch_miss.inc()
            self.timeline.instant("prefetch_miss", uid=str(req.uid),
                                  blocks=len(keys))
            chunks = self._stage_chunks(keys)
        else:
            staged_n = sum(len(ck) for ck, _ in chunks)
            if staged_n < len(keys):
                # the prefetch staged only the capped prefix — the tail
                # stages now; its device_put overlaps the prefix chunks'
                # scatter work
                chunks = chunks + self._stage_chunks(keys[staged_n:])
        promoted: List[int] = []
        wait_s = 0.0
        swap_t0 = self.timeline.now_us()
        for ci, (chunk_keys, staged) in enumerate(chunks):
            ids = np.zeros(self.swap_batch, np.int32)
            got: List[int] = []
            for key in chunk_keys:
                b = self._alloc.alloc()
                if b is None:
                    if not self._demote_evict_batch():
                        break
                    b = self._alloc.alloc()
                    if b is None:
                        break
                if self.kv_quant:
                    self._kv_scale_live.add(b)
                got.append(b)
            if got:
                ids[:len(got)] = got
                wait_s += self._promote_wait(staged)
                with self._tp_ctx():
                    self._set_swap_pools(self._get_promote_fn()(
                        self._swap_pools(), staged, jnp.asarray(ids)))
                for key in chunk_keys[:len(got)]:
                    self._host.pop(key)     # residency moved to device
                promoted.extend(got)
            if len(got) < len(chunk_keys):
                # pool dry mid-run: everything unscattered — this chunk's
                # tail and every later chunk — rolls back to plain host
                # residency (NEVER left dangling in-flight; keys a sharing
                # request still has staged keep their pin)
                self._unflag_keys(chunk_keys[len(got):])
                for later_keys, _ in chunks[ci + 1:]:
                    self._unflag_keys(later_keys)
                break
        if promoted:
            self.timeline.complete("swap", swap_t0, direction="in",
                                   blocks=len(promoted))
            # a sharing pending request may have the just-popped keys
            # staged too: drop those records NOW — their staging is stale
            # (the sharer's own admission would probe the chain on device
            # and discard anyway), and a dangling record would both hold
            # the double buffer and flag a false residency violation if
            # the chain is later re-demoted un-flagged
            popped = set(k for ck, _ in chunks for k in ck
                         if not self._host.has(k))
            stale = [uid for uid, rec in self._staged.items()
                     if popped.intersection(rec["keys"])]
            for uid in stale:
                rec = self._staged.pop(uid)
                self._unflag_keys(rec["keys"])
            # graft the chain onto the trie (the cache takes its own hold,
            # exactly like a freshly prefilled prompt's registration)
            self._prefix.register(prompt_eff, promoted, self._alloc,
                                  start=n_dev)
            self._c_swap_in.inc(len(promoted))
            self._c_swap_bytes.inc(len(promoted) * self._host.block_nbytes)
            self._h_prefetch_wait.observe(wait_s)
            self.timeline.instant(
                "promote", uid=str(req.uid), blocks=len(promoted),
                bytes=len(promoted) * self._host.block_nbytes,
                prefetch="miss" if miss else "hit",
                wait_s=round(wait_s, 6))
        return promoted

    # ----------------------------------------------------------- block plumbing
    def _decref(self, b: int) -> None:
        """Release one reference; when the block actually frees, retire
        its scale-ledger entry in the same step (kv8 — the device scale
        row is stale from here until the next owner's first write)."""
        self._alloc.decref(b)
        if self._alloc.refcount(b) == 0:
            self._kv_scale_live.discard(b)

    def _release_slot(self, slot: int) -> None:
        for b in self._held[slot]:
            self._decref(b)
        self._held[slot] = []
        self._tables[slot] = 0
        self._tokens[slot] = 0
        self._lengths[slot] = 0
        self._window_start[slot] = 0
        # idle rows revert to the greedy/unmasked defaults — their lanes
        # still run in every dispatch (outputs discarded), so they must
        # stay deterministic and NaN-free
        self._temps[slot] = 0.0
        self._topks[slot] = 0
        self._topps[slot] = 1.0
        self._seeds[slot] = 0
        if self._masks is not None:
            self._masks[slot, :] = True

    def _preempt(self, slot: int) -> None:
        """Evict a sequence under block pressure: free its blocks and
        re-queue it at the FRONT with generated tokens folded into the
        prompt (greedy => recompute is token-exact).  With the host tier
        the victim's committed full blocks demote first, so the resume's
        "recompute" promotes them back instead of re-running prefill."""
        st = self._active.pop(slot)
        nblocks = len(self._held[slot])
        if self._host is not None:
            self._demote_slot_blocks(slot, st)
        self._release_slot(slot)
        self._pending.push_front(_PendingItem(
            req=st.req, prior=st.prior + st.out, priority=st.priority,
            slo_class=st.slo_class, eos=st.eos, handle=st.handle))
        self._c_preempted.inc()
        self.timeline.instant("preempt", uid=str(st.req.uid), slot=slot,
                              blocks_freed=nblocks)

    def _slot_group(self, slot: int) -> int:
        """dp group owning ``slot`` (always 0 outside dp_tp mode): slot
        spans are contiguous, matching the shard_map ``P("dp")`` row
        chunking of the fused decode program."""
        return slot // (self.slots // self.dp_degree) \
            if self.dp_degree > 1 else 0

    def _alloc_block(self, requester: int) -> Optional[int]:
        """One fresh block, reclaiming in order: free list -> LRU prefix-
        cache eviction -> preempting the latest-admitted sequence.  Returns
        ``None`` iff the requester itself was preempted.  In dp_tp mode
        both the free list and the preemption victim pool are scoped to
        the requester's dp group — blocks never cross pool shards."""
        grp = self._slot_group(requester)
        while True:
            b = self._alloc.alloc(grp) if self.dp_degree > 1 \
                else self._alloc.alloc()
            if b is not None:
                if self.kv_quant:
                    self._kv_scale_live.add(b)
                return b
            if self._prefix is not None:
                if self._host is not None:
                    # tiered: demote a batch of LRU leaves to host DRAM,
                    # then free them — contents survive below
                    if self._demote_evict_batch():
                        continue
                else:
                    evicted = self._prefix.evict_one(self._alloc)
                    if evicted:
                        self._kv_scale_live.discard(evicted)
                        self.timeline.instant("evict_block",
                                              block=int(evicted))
                        continue
            cands = self._active if self.dp_degree == 1 else \
                {s: st for s, st in self._active.items()
                 if self._slot_group(s) == grp}
            victim = max(cands, key=lambda s: cands[s].admit_seq)
            if victim == requester and len(cands) == 1:
                # cannot happen when num_blocks >= nbper+1 per group
                # (ctor check)
                raise RuntimeError(
                    "paged KV pool too small for a single sequence")
            self._preempt(victim)
            if victim == requester:
                return None

    def _ensure_blocks(self, slot: int, upto: int) -> bool:
        """Make the slot's table cover positions ``[0, upto)``; may preempt
        other slots (or the slot itself — returns False).  Resident-window
        serving: the demoted region ``[landmark, window_blk)`` is a
        DELIBERATE scratch hole — its entries stay 0 (attention masks them
        out) and must never be re-allocated here."""
        st = self._active.get(slot)
        skip_hi = getattr(st, "window_blk", 0) \
            if self.resident_window_blocks and st is not None else 0
        for li in range(blocks_for(upto, self.block_size)):
            if self._landmark_blocks <= li < skip_hi:
                continue
            if slot not in self._active:
                return False
            if self._tables[slot, li] == 0:
                b = self._alloc_block(requester=slot)
                if b is None:
                    return False
                self._tables[slot, li] = b
                self._held[slot].append(b)
        return slot in self._active

    # --------------------------------------------------------------- schedule
    def _bucket_for(self, prompt_len: int) -> int:
        for b in self.prompt_buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt length {prompt_len} exceeds the largest bucket "
            f"{self.prompt_buckets[-1]}")

    def _prefill_width(self, prompt_len: int) -> int:
        """Prefill window for a prompt in bucketed mode: its ladder rung —
        or the full cache width for a preemption resume whose prompt (with
        generated tokens folded in) outgrew a custom ladder, instead of
        failing mid-trace (the prefill program is width-generic, so this
        costs at most one extra compile).  Floor of 2 for the same T == 1
        decode-dispatch reason as ``prefill_chunk``."""
        for b in self.prompt_buckets:
            if prompt_len <= b:
                return max(2, b)
        return self._cache_len

    def _admit(self):
        """Head-of-queue-gated admission into free slots (priority order,
        module docstring), gated on block availability (free + prefix-
        evictable) so an admitted sequence can always prefill its prompt;
        the queue head blocks admission when it doesn't fit — no
        starvation within a priority class."""
        pending, active = self._pending, self._active
        free = [s for s in range(self.slots) if s not in active]
        reserved = 0                       # blocks promised to this call's
        reserved_g: Dict[int, int] = {}    # ... per dp group, in dp_tp mode
        while pending and free:            # earlier joiners, not yet alloc'd
            item = pending[0]
            req, prior = item.req, item.prior
            if self.dp_degree > 1:
                # placement: the free slot whose dp group has the most
                # unpromised blocks — admission gates on THAT group's span
                slot_pick = max(
                    free,
                    key=lambda s: (self._alloc.group_free(
                        self._slot_group(s))
                        - reserved_g.get(self._slot_group(s), 0), -s))
                grp = self._slot_group(slot_pick)
            else:
                slot_pick, grp = free[0], None
            # blocked-head memo: while nothing refcount-related moved, the
            # gate's probe/evictable answer cannot change — skip the
            # O(prompt + trie) host walk every idle iteration
            gate_key = (id(req), len(prior), self._alloc.version)
            if gate_key == self._blocked_gate:
                break
            prompt_eff = np.concatenate(
                [req.prompt, np.asarray(prior, np.int32)]) \
                if prior else req.prompt
            plen = int(prompt_eff.size)
            # gate on a non-mutating probe first: while the queue head is
            # blocked, iterations must not churn refcounts / LRU recency
            total_need = blocks_for(plen + 1, self.block_size)
            if self.resident_window_blocks:
                # resident-window serving admits on the RESIDENT footprint
                # only — landmark + window + the chunk being prefilled —
                # because everything older demotes as the window slides;
                # gating on the full logical span would block every giant
                # prompt the window exists to serve
                total_need = min(
                    total_need,
                    self._landmark_blocks + self.resident_window_blocks
                    + blocks_for(self.prefill_chunk, self.block_size))
            n_hit = self._prefix.probe(prompt_eff, plen - 1) \
                if self._prefix is not None else 0

            def _avail():
                if grp is not None:
                    return self._alloc.group_free(grp) - \
                        reserved_g.get(grp, 0)
                return self._alloc.free_blocks - reserved + \
                    (self._prefix.evictable(self._alloc)
                     if self._prefix is not None else 0)

            if total_need - n_hit > _avail():
                self._blocked_gate = gate_key
                break                      # strict FIFO: head blocks the rest
            hits: List[int] = []
            if self._prefix is not None:
                # cap below the full prompt: >= 1 tail token must prefill
                hits = self._prefix.lookup(prompt_eff, plen - 1, self._alloc)
            # re-check post-claim: hit blocks that were evictable no longer
            # count toward avail, so the probe gate can be optimistic by
            # up to n_hit blocks
            need = total_need - len(hits)
            if need > _avail():
                for b in hits:             # unclaim and wait for pressure
                    self._decref(b)        # to drain
                self._blocked_gate = (id(req), len(prior),
                                      self._alloc.version)
                break
            if self._host is not None and not self.resident_window_blocks:
                # tiered KV: the chain's continuation may live in host
                # DRAM (earlier eviction or this request's own preempted
                # state) — promote it back and extend the claimed prefix;
                # the gate above just proved the device blocks this costs
                # are coverable, so promotion never preempts anyone.
                # Resident-window mode skips this: demoted middle blocks
                # belong OUT of the device pool (the window mask hides
                # them) — promoting a 100k-token chain would flood the
                # deliberately small pool at admission
                hits.extend(self._promote_chain(prompt_eff, plen,
                                                len(hits), req))
                need = total_need - len(hits)
            reserved += max(need, 0)
            if grp is not None:
                reserved_g[grp] = reserved_g.get(grp, 0) + max(need, 0)
            pending.popleft()
            slot = slot_pick
            free.remove(slot)
            # latency probes: admit stamped once per request per trace (a
            # preemption resume keeps the original admission time, so its
            # TTFT/TPOT and its timeline span cover the whole wait)
            self._trace_times.setdefault(
                req.uid, {"admit": time.perf_counter(), "first": None,
                          "admit_us": self.timeline.now_us()})
            self._tables[slot, :len(hits)] = hits
            self._held[slot] = list(hits)
            st = _SlotState(req=req, admit_seq=self._admit_seq,
                            prompt_eff=prompt_eff, prior=list(prior),
                            base=len(hits) * self.block_size,
                            eos=item.eos, priority=item.priority,
                            slo_class=item.slo_class, handle=item.handle)
            self._admit_seq += 1
            active[slot] = st
            if self.sampling:
                self._temps[slot] = np.float32(req.temperature)
                self._topks[slot] = np.int32(req.top_k)
                self._topps[slot] = np.float32(req.top_p)
                self._seeds[slot] = np.uint32(req.seed)
                if self._masks is not None:
                    self._masks[slot, :] = True
            if self.resident_window_blocks:
                # fresh slot: full attention until the first slide
                self._window_start[slot] = 0
            if st.handle is not None:
                st.handle._on_active()
            if self._admission_log is not None:
                self._admission_log.append((req.uid, slot))
            self._c_admitted.inc()
            self._c_prompt_tokens.inc(plen)
            self._c_prefix_hit_tokens.inc(st.base)
            if prior:
                # tokens a preemption resume actually re-prefills — with
                # the host tier this stays near zero (promoted chains
                # cover all but the unfinished tail)
                self._c_resume_recompute.inc(plen - st.base)
            # prefix_hit_tokens == 0 is the cache-miss record
            self.timeline.instant("admit", uid=str(req.uid), slot=slot,
                                  prompt_tokens=plen,
                                  prefix_hit_tokens=st.base,
                                  resumed=bool(prior))
            fid = self._flow_ids.pop(req.uid, None)
            if fid is not None:
                # close the router's route flow on this admission — the
                # merged fleet trace draws the router -> replica arrow
                self.timeline.flow_end("route", fid, uid=str(req.uid),
                                       slot=slot)

    # --------------------------------------------------- incremental serving
    def _validate_request(self, r: Request) -> None:
        total = len(r.prompt) + r.max_new_tokens
        if total > self.max_seq_len:
            raise ValueError(
                f"request {r.uid!r}: prompt ({len(r.prompt)}) + "
                f"max_new_tokens ({r.max_new_tokens}) = {total} exceeds "
                f"max_seq_len {self.max_seq_len}")
        if r.sampled and not self.sampling:
            raise ValueError(
                f"request {r.uid!r} asks for temperature="
                f"{r.temperature} but this engine was built with "
                "sampling=False (greedy-only programs) — rebuild with "
                "sampling=True to serve sampled traffic")
        if r.mask_builder is not None and not self.logit_masks:
            raise ValueError(
                f"request {r.uid!r} carries a mask_builder but this "
                "engine was built without the constrained-decoding lane "
                "— pass logit_masks=True (the [slots, vocab] mask "
                "operand is only threaded through the programs then)")
        if not self.chunked_prefill:
            self._bucket_for(len(r.prompt))  # raises if no bucket fits

    def _session_boundary_reset(self) -> None:
        """First submit into an idle engine: object ids and prefetch gates
        from the previous trace are stale (the per-call reset ``serve``
        used to do)."""
        if self._pending or self._active:
            return
        self._blocked_gate = None
        if self._host is not None:
            self._discard_all_staged()
            self._prefetch_gate.clear()

    def submit(self, request: Request, *, priority: int = 0,
               slo_class: Optional[str] = None,
               eos_token_id: Optional[int] = None) -> RequestHandle:
        """Enqueue one request into the live scheduler and return its
        :class:`RequestHandle` (per-token streaming, ``result()``,
        ``cancel()``).  Admission happens on subsequent ``step()`` calls
        (``serve()`` and the replica router drive them).  ``priority``
        orders the pending queue (higher admits first; FIFO within a
        class); ``slo_class`` maps to a default priority through
        :data:`SLO_PRIORITY` when ``priority`` is 0."""
        self._validate_request(request)
        if request.uid in self._live_uids:
            raise ValueError(
                f"request uid {request.uid!r} is already in flight")
        self._session_boundary_reset()
        if self._submit_observer is not None:
            # record the CALLER's knobs (pre-SLO-mapping priority) so a
            # replay resubmits through the same mapping
            self._submit_observer(request, priority=priority,
                                  slo_class=slo_class,
                                  eos_token_id=eos_token_id)
        if priority == 0 and slo_class is not None:
            priority = SLO_PRIORITY.get(str(slo_class), 0)
        handle = RequestHandle(request, priority=priority,
                               slo_class=slo_class, canceller=self.cancel,
                               lock_sanitizer=self._lock_sanitizer)
        self._pending.push(_PendingItem(
            req=request, prior=[], priority=priority, slo_class=slo_class,
            eos=eos_token_id, handle=handle))
        self._c_sampled["constrained" if request.mask_builder is not None
                        else "sampled" if request.sampled
                        else "greedy"].inc()
        self._live_uids.add(request.uid)
        self._g_queue_depth.set(len(self._pending))
        self.timeline.instant("submit", uid=str(request.uid),
                              priority=int(priority),
                              slo=str(slo_class) if slo_class else "")
        return handle

    def _submit_item(self, item: _PendingItem,
                     canceller=None) -> None:
        """Router handoff entry: enqueue a fully-formed pending item (an
        in-flight request drained off another replica), keeping its
        handle, prior tokens, priority, and eos — token streaming
        continues on the same handle.  ``canceller`` is the cancel
        route to rebind (the router passes its own ``cancel`` so the
        handle never — even transiently — routes around the fleet
        locks straight into this engine); defaults to this engine's."""
        self._validate_request(item.req)
        if item.req.uid in self._live_uids:
            raise ValueError(
                f"request uid {item.req.uid!r} is already in flight")
        self._session_boundary_reset()
        if item.handle is not None:
            # under the handle condition (set_canceller) — the stream
            # may still be read concurrently during a drain handoff
            item.handle.set_canceller(canceller or self.cancel)
        self._pending.push(item)
        self._live_uids.add(item.req.uid)
        self._g_queue_depth.set(len(self._pending))
        self.timeline.instant("submit", uid=str(item.req.uid),
                              priority=int(item.priority),
                              resumed=bool(item.prior))

    def _cancel_pending(self, uid) -> bool:
        item = self._pending.remove(uid)
        if item is None:
            return False
        self._live_uids.discard(uid)
        if self._host is not None:
            rec = self._staged.pop(uid, None)
            if rec is not None:
                self._unflag_keys(rec["keys"])
        self._prefetch_gate.pop(uid, None)
        self._blocked_gate = None          # the head may have been this item
        self._trace_times.pop(uid, None)
        fid = self._flow_ids.pop(uid, None)
        if fid is not None:
            # never admitted — close the router's route flow here so the
            # merged trace carries no dangling flow start
            self.timeline.flow_end("route", fid, uid=str(uid),
                                   cancelled=True)
        self._c_cancelled.inc()
        self._g_queue_depth.set(len(self._pending))
        self.timeline.instant("cancelled", uid=str(uid), queued=True)
        if item.handle is not None:
            item.handle._on_cancel()
        return True

    def cancel(self, uid) -> bool:
        """Cancel a live request.  Queued: dropped immediately (its staged
        prefetch, if any, rolls back).  Active: the slot and its blocks
        release at the NEXT scheduler-iteration boundary — the only point
        the paged-state invariants are guaranteed to hold — with a
        ``cancelled`` timeline event; already-streamed tokens stand.
        Returns ``False`` when the uid is unknown or already finished."""
        if self._cancel_pending(uid):
            return True
        if any(st.req.uid == uid for st in self._active.values()):
            self._cancel_flags.add(uid)
            return True
        return False

    def _process_cancellations(self) -> None:
        """Apply deferred active-slot cancels at the iteration boundary:
        pop the slot, decref its blocks (prefix-shared blocks survive in
        the trie), and emit the audited ``cancelled`` event."""
        if not self._cancel_flags:
            return
        flags, self._cancel_flags = self._cancel_flags, set()
        for uid in flags:
            slot = next((s for s, st in self._active.items()
                         if st.req.uid == uid), None)
            if slot is None:
                # finished before the boundary, or preempted back to the
                # queue — the pending path handles the latter
                self._cancel_pending(uid)
                continue
            st = self._active.pop(slot)
            nblocks = len(self._held[slot])
            self._release_slot(slot)
            self._live_uids.discard(uid)
            self._trace_times.pop(uid, None)
            self._c_cancelled.inc()
            self.timeline.instant("cancelled", uid=str(uid), slot=slot,
                                  blocks_freed=nblocks)
            if st.handle is not None:
                st.handle._on_cancel()

    def step(self) -> bool:
        """ONE scheduler iteration over the live queue/slots: process
        cancellations, admit, advance prefills, run the decode (or
        draft–verify) round, stage prefetches, audit.  Returns whether
        work remains — drive it in a loop (``serve``), from a replica
        worker thread (``deepspeed_tpu/serving/``), or by hand."""
        if self._fault_injector is not None:
            # chaos harness (serving/faults.py): may raise SimulatedCrash
            # (the router/worker converts it into fail-and-re-home),
            # stall this replica, or flip bits in the host arena — all on
            # the armed plan's deterministic schedule
            self._fault_injector.on_step(self)
        self._process_cancellations()
        if not self._pending and not self._active:
            if self._host is not None:
                self._discard_all_staged()  # no queue left to consume them
            self._g_queue_depth.set(0)
            return False
        params = self.engine.params
        self._c_iterations.inc()
        admitted0, preempted0 = self.admitted, self.preempted
        self._admit()
        self._refresh_masks()
        self._run_prefill(params)
        if self.role == "prefill":
            # disaggregated mode: prefill-complete slots leave the decode
            # rotation NOW — the decode dispatch below only ever advances
            # prefilling/empty slots on this replica
            self._extract_handoffs()
        # one decode step over every slot (per-sequence positions);
        # prefilling/empty slots point at the scratch block.  In
        # speculative mode the single-token step is replaced by a
        # draft–verify round committing up to K+1 tokens per slot.
        # a slot that finished prefill above decodes in this SAME
        # iteration — its first generated token must gate the second, so
        # constrained rows rebuild between the two dispatches
        self._refresh_masks()
        if self.spec_tokens:
            self._run_spec_decode(params)
        elif self._K > 1:
            self._run_fused_decode(params)
        else:
            self._run_plain_decode(params)
        # resident-window maintenance AFTER both phases committed this
        # iteration's tokens: mid-prefill giant prompts slide too (the
        # next chunk's program then masks the demoted middle)
        self._slide_windows()
        if self._host is not None:
            # stage next iteration's promotions NOW: the H2D copies
            # run while the next decode step computes (module
            # docstring "Tiered KV cache" — the param_stream overlap)
            self._issue_prefetch(self._pending)
        self._g_queue_depth.set(len(self._pending))
        if self._step_log is not None:
            self._step_log.append({
                "iteration": self.iterations,
                "admitted": self.admitted - admitted0,
                "evicted": self.preempted - preempted0,
                "blocks_in_use": self._alloc.blocks_in_use,
            })
        if self.debug_checks:
            # O(blocks) host-state audit between scheduler rounds —
            # the scheduler's state is only guaranteed consistent at
            # iteration boundaries (analysis/invariants.py; the audit
            # drops its own event on the timeline)
            audit_serving_engine(self, self._active)
            self._c_invariant_checks.inc()
        return bool(self._pending or self._active)

    def drain(self) -> List[_PendingItem]:
        """Quiesce this engine for a replica handoff (router drain
        protocol): preempt every active slot — with the host tier, each
        victim's committed full blocks demote first and its generated
        tokens fold into the resume prompt — then demote the remaining
        prefix-cache content to the host tier, and hand back the whole
        pending queue for re-submission elsewhere
        (``ReplicaRouter._submit_item`` on another replica).  After a
        drain the device pool is fully free; the host tier is the
        replica's exportable session store (``host_chain_export``)."""
        self._process_cancellations()
        for slot in sorted(self._active,
                           key=lambda s: -self._active[s].admit_seq):
            self._preempt(slot)
        if self._host is not None and self._prefix is not None:
            while self._demote_evict_batch():
                pass
            self._discard_all_staged()
            self._prefetch_gate.clear()
        # parked prefill-complete handoffs leave with the queue (their
        # per-item trace cleanup already ran at extraction)
        items = self.take_handoffs() + self._pending.drain()
        self._blocked_gate = None
        for item in items:
            # the latency span can only finish on the engine that admits
            # the resume; this engine's stamp would dangle forever.  A
            # still-noted flow id (queued, never admitted here) closes
            # NOW — the router starts a fresh flow to the new replica
            self._trace_times.pop(item.req.uid, None)
            fid = self._flow_ids.pop(item.req.uid, None)
            if fid is not None:
                self.timeline.flow_end("route", fid,
                                       uid=str(item.req.uid),
                                       handoff=True)
            self._live_uids.discard(item.req.uid)
        self._g_queue_depth.set(0)
        self.timeline.instant("drain", handoff=len(items),
                              host_blocks_in_use=(
                                  self._host.blocks_in_use
                                  if self._host is not None else 0))
        return items

    def salvage(self) -> List[_PendingItem]:
        """Crash salvage (:meth:`ReplicaRouter.fail` — the hard twin of
        :meth:`drain`): extract every live request's resume context using
        HOST-SIDE bookkeeping only.  No device program runs and nothing
        demotes — the engine is presumed crashed, so its device pool is
        not to be trusted and its host tier is not exportable (survivors'
        tiers are the KV-salvage source; the router pulls from them).
        Active slots fold their already-streamed tokens into the resume
        prompt (the preemption trick, so greedy resume on a survivor is
        token-exact) and release their blocks in the host ownership
        records, leaving the allocator/trie consistent for a later
        restart + readmit.  Items return actives-first in admission
        order, then the pending queue — the same hand-off order
        :meth:`drain` produces.  Deferred cancel flags are honored: a
        cancelled request resolves here instead of re-homing."""
        cancels, self._cancel_flags = self._cancel_flags, set()
        items: List[_PendingItem] = []
        for slot in sorted(self._active,
                           key=lambda s: self._active[s].admit_seq):
            st = self._active[slot]
            items.append(_PendingItem(
                req=st.req, prior=st.prior + st.out, priority=st.priority,
                slo_class=st.slo_class, eos=st.eos, handle=st.handle))
        self._active.clear()
        for slot in range(self.slots):
            self._release_slot(slot)
        items.extend(self.take_handoffs())
        items.extend(self._pending.drain())
        if self._host is not None:
            self._discard_all_staged()
            self._prefetch_gate.clear()
        self._blocked_gate = None
        out: List[_PendingItem] = []
        for item in items:
            uid = item.req.uid
            self._live_uids.discard(uid)
            self._trace_times.pop(uid, None)
            fid = self._flow_ids.pop(uid, None)
            if fid is not None:
                self.timeline.flow_end("route", fid, uid=str(uid),
                                       salvaged=True)
            if uid in cancels:
                self._c_cancelled.inc()
                self.timeline.instant("cancelled", uid=str(uid),
                                      salvaged=True)
                if item.handle is not None:
                    item.handle._on_cancel()
                continue
            out.append(item)
        self._g_queue_depth.set(0)
        self.timeline.instant("salvage", items=len(out))
        return out

    # ------------------------------------------------ prefill-role handoffs
    def _extract_handoffs(self) -> None:
        """Prefill-role scheduling (``role="prefill"``): pull every slot
        whose prefill just completed OUT of the decode rotation — its
        first token is already streamed (TTFT is this replica's job), its
        committed chain demotes to the host tier, and the request parks
        in ``_handoff_ready`` for the router to re-route to a decode
        worker as an ordinary integrity-checked KV pull.  Runs between
        the prefill and decode dispatches of :meth:`step`, so a prefill
        worker's decode program only ever sees empty/prefilling slots and
        a long-prompt burst never time-shares a decode replica's TPOT.
        The generated-so-far tokens fold into the resume prompt exactly
        like a preemption, so the decode worker's greedy continuation is
        token-exact."""
        done = [s for s, st in self._active.items()
                if st.phase == "decode"]
        for slot in sorted(done, key=lambda s: self._active[s].admit_seq):
            st = self._active.pop(slot)
            nblocks = len(self._held[slot])
            if self._host is not None:
                self._demote_slot_blocks(slot, st)
            self._release_slot(slot)
            self._handoff_ready.append(_PendingItem(
                req=st.req, prior=st.prior + st.out, priority=st.priority,
                slo_class=st.slo_class, eos=st.eos, handle=st.handle))
            uid = st.req.uid
            # the TTFT span closed with the first token; the remaining
            # latency accrues on the decode worker that admits the resume
            # — this replica's stamp and route flow close now, exactly
            # like drain()'s per-item cleanup
            self._trace_times.pop(uid, None)
            fid = self._flow_ids.pop(uid, None)
            if fid is not None:
                self.timeline.flow_end("route", fid, uid=str(uid),
                                       handoff=True)
            self._live_uids.discard(uid)
            self._c_handoffs.inc()
            self.timeline.instant("handoff", uid=str(uid), slot=slot,
                                  blocks=nblocks,
                                  tokens=len(st.prior) + len(st.out))

    def take_handoffs(self) -> List[_PendingItem]:
        """Hand the parked prefill-complete requests to the caller (the
        router's per-step pump) and clear the parking list.  Empty on
        ``role="both"``/``"decode"`` replicas — the list only ever fills
        from :meth:`_extract_handoffs`."""
        items, self._handoff_ready = self._handoff_ready, []
        return items

    # ---------------------------------------------------- router probes/pull
    def affinity_probe(self, tokens) -> Dict[str, int]:
        """Routing probe (read-mostly, O(prompt)): leading full-block
        depth of ``tokens`` resident on this replica — device trie plus
        host-tier continuation — and the load signals the router balances
        on.  Capped at ``len(tokens) - 1`` exactly like admission's own
        lookup, so the reported depth is what an admitted request would
        actually reuse."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        plen = int(tokens.size)
        n_dev = self._prefix.probe(tokens, plen - 1) \
            if self._prefix is not None else 0
        n_host = len(self._host.probe_run(tokens, n_dev, plen - 1,
                                          self.block_size)) \
            if self._host is not None else 0
        return {"device_blocks": int(n_dev), "host_blocks": int(n_host),
                "blocks_in_use": int(self._alloc.blocks_in_use),
                "queue_depth": len(self._pending),
                "active": len(self._active)}

    def demote_chain(self, tokens, max_tokens: Optional[int] = None,
                     start_block: int = 0) -> int:
        """Snapshot the device-trie-resident chain of ``tokens`` into the
        host tier (cross-replica export): the trie keeps its entries and
        blocks — this copies bytes DOWN so ``host_chain_export`` can read
        them (the dedup-by-chain-key rule makes the device/host copy pair
        safe: a later eviction sees the key resident and just frees the
        device block).  ``start_block`` skips blocks the importer already
        holds — a pull for the chain's suffix must not D2H-copy (and
        LRU-churn the arena with) the prefix nobody will read.  Returns
        blocks newly stored."""
        if self._host is None or self._prefix is None:
            return 0
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        mt = int(tokens.size) if max_tokens is None else int(max_tokens)
        blocks = self._prefix.chain_blocks(tokens, mt)
        if len(blocks) <= int(start_block):
            return 0
        keys = chain_keys(tokens, len(blocks), self.block_size)
        pairs = [(b, k) for b, k in
                 list(zip(blocks, keys))[int(start_block):]
                 if not self._host.has(k)]
        if not pairs:
            return 0
        return self._demote_blocks([b for b, _ in pairs],
                                   [k for _, k in pairs])

    def host_chain_export(self, tokens, start_block: int = 0,
                          max_tokens: Optional[int] = None):
        """``(keys, per-block per-leaf byte COPIES, checksums)`` of the
        host-resident run of ``tokens`` from ``start_block`` on — the
        cross-replica KV-pull wire format (``HostBlockStore
        .export_chain`` + ``export_checksums``): the same
        content-addressed chain keys name the blocks on every replica,
        quantized ``{qp, ps}`` records travel as ordinary leaves so int8
        codes and scale rows move together bit-identically, and the
        per-block integrity checksums ride beside the bytes so the
        importer verifies the transfer end-to-end.  Corrupt entries are
        dropped before export (never exported); an armed fault plan may
        raise :class:`~deepspeed_tpu.inference.paged.TransportError`
        here — the router's pull retries with backoff."""
        if self._host is None:
            return [], [], []
        if self._fault_injector is not None:
            self._fault_injector.on_transport("export")
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        mt = int(tokens.size) if max_tokens is None else int(max_tokens)
        keys = self._host.probe_run(tokens, start_block, mt,
                                    self.block_size)
        if keys:
            keys = self._verified_keys(keys)
        if keys and self._nvme is not None:
            # spilled entries must climb back into the arena before their
            # bytes can be copied out (verified at the NVMe exit); the
            # watermark budget may truncate a very long run — the importer
            # gets the leading prefix, its tail recomputes or re-pulls
            n_ok = self._host.promote_spilled(keys)
            self._sync_nvme_metrics()
            keys = keys[:n_ok]
        return keys, self._host.export_chain(keys), \
            self._host.export_checksums(keys)

    def host_chain_import(self, keys, blocks, checksums=None) -> int:
        """Store a pulled chain into this replica's host tier (admission
        then promotes it on-device through the ordinary fixed-shape
        scatter path).  With ``checksums`` every arriving block re-hashes
        against the exporter's record and a mismatch stops the import —
        ticked into ``serving_checksum_failures_total``, the chain tail
        recomputes locally.  Returns blocks stored."""
        if self._host is None or not keys:
            return 0
        if self._fault_injector is not None:
            self._fault_injector.on_transport("import")
        before = self._host.checksum_rejects
        n = self._host.import_chain(keys, blocks, checksums=checksums)
        rejects = self._host.checksum_rejects - before
        if rejects:
            self._c_checksum_fail.inc(rejects)
            self.timeline.instant("checksum_fail", op="import",
                                  blocks=rejects)
        self._sync_nvme_metrics()   # imports may push the LRU tail to NVMe
        return n

    # ----------------------------------------------------------- batch serve
    def serve(self, requests: Sequence[Request],
              eos_token_id: Optional[int] = None,
              admission_log: Optional[list] = None,
              step_log: Optional[list] = None,
              debug_checks: Optional[bool] = None,
              profile_dir: Optional[str] = None,
              profile_iters: Optional[int] = None) -> Dict[Any, np.ndarray]:
        """Run a request trace to completion; returns ``uid -> [prompt +
        completion]`` int32 arrays, padded to ``prompt + max_new_tokens``
        with eos back-fill (HF semantics, same as ``generate``).  A thin
        wrapper over the incremental API — ``submit`` everything, loop
        ``step()``, gather handle results — with identical scheduling;
        an empty request list returns ``{}`` without tracing anything.

        ``admission_log``, when given, collects ``(uid, slot)`` in admission
        order — the scheduler-determinism tests read it.  ``step_log``
        collects one dict per iteration (admitted / evicted / blocks_in_use
        per step) for observability.  ``debug_checks`` overrides the
        engine-level flag from here on (ctor docstring): per-iteration
        paged-state audits + strict recompile-sentry enforcement.

        ``profile_dir`` opens a ``jax.profiler`` trace window over this
        call's first ``profile_iters`` scheduler iterations (``None`` =
        the whole call) — the device-level deep dive behind the host-side
        timeline (``dump_trace``).  The window start/stop are themselves
        timeline events, so the two traces line up."""
        if debug_checks is not None:
            self.debug_checks = bool(debug_checks)
            self.sentry.strict = self.debug_checks
            if self.debug_checks:
                install_compile_listener()
        requests = list(requests)
        if not requests:
            return {}
        if self.role != "both":
            raise RuntimeError(
                f"serve() on a role={self.role!r} replica — a "
                "disaggregated worker only runs its half of the pipeline "
                "(handoffs would never complete here); drive it behind a "
                "ReplicaRouter, or build with role='both'")
        if self._pending or self._active:
            raise RuntimeError(
                "serve() on a busy engine — requests are already in "
                "flight; drive submit()/step() instead")
        for r in requests:
            self._validate_request(r)
        uids = [r.uid for r in requests]
        if len(set(uids)) != len(uids):
            raise ValueError("duplicate request uids")
        handles = [self.submit(r, eos_token_id=eos_token_id)
                   for r in requests]
        self._admission_log = admission_log
        self._step_log = step_log
        window = None
        if profile_dir is not None:
            window = ProfilerWindow(profile_dir)
            if window.start():
                self.timeline.instant("profiler_start",
                                      profile_dir=str(profile_dir))
        iter0 = self.iterations
        try:
            while self.step():
                if window is not None and window.active and \
                        profile_iters is not None and \
                        self.iterations - iter0 >= profile_iters:
                    window.stop()
                    self.timeline.instant("profiler_stop")
        finally:
            self._admission_log = None
            self._step_log = None
            if window is not None and window.active:
                window.stop()
                self.timeline.instant("profiler_stop")
        return {h.uid: h.result(timeout=0) for h in handles}

    # ----------------------------------------------------------------- decode
    def _mark_first(self, st: _SlotState) -> None:
        tm = self._trace_times.get(st.req.uid)
        if tm is not None and tm["first"] is None:
            tm["first"] = time.perf_counter()

    def _emit_tokens(self, st: _SlotState, toks) -> None:
        """Per-token streaming + the committed-token counter: every token
        the scheduler commits flows through here exactly once."""
        self._c_gen_tokens.inc(len(toks))
        if st.handle is not None:
            st.handle._on_tokens(toks)

    def _finish_slot(self, slot: int) -> None:
        """Complete a request: build the padded ``[prompt + completion]``
        result (eos back-fill, HF semantics), record latencies and the
        per-request span, release the slot, resolve the handle."""
        st = self._active.pop(slot)
        req = st.req
        gen = np.asarray(st.prior + st.out, np.int32)
        eos_hit = st.eos is not None and gen.size and gen[-1] == st.eos
        out = np.zeros(req.max_new_tokens, np.int32)
        out[:gen.size] = gen
        if eos_hit:
            out[gen.size:] = st.eos        # back-fill (HF semantics)
        result = np.concatenate([req.prompt, out])
        tm = self._trace_times.pop(req.uid, None)
        if tm is not None and tm["first"] is not None:
            done = time.perf_counter()
            ttft = tm["first"] - tm["admit"]
            tpot = ((done - tm["first"]) / (gen.size - 1)) \
                if gen.size > 1 else 0.0
            self._c_finished.inc()
            self._h_ttft.observe(ttft)
            self._h_tpot.observe(tpot)
            # SLO accounting: unclassified traffic lands in "standard"
            # so fleet attainment is never flattered by omission
            self._slo.observe(st.slo_class, ttft, tpot)
            self._latencies.append({
                "uid": req.uid,
                "new_tokens": int(gen.size),
                "ttft_s": ttft,
                "tpot_s": tpot,
            })
            # per-request span on the finishing slot's lane: admission
            # (original — a preemption resume keeps it) to completion
            self.timeline.complete(
                f"req {req.uid}", tm["admit_us"], tid=slot + 1,
                uid=str(req.uid), new_tokens=int(gen.size),
                eos=bool(eos_hit), ttft_s=ttft)
        self._release_slot(slot)
        self._live_uids.discard(req.uid)
        if st.handle is not None:
            st.handle._on_finish(result)

    def _run_plain_decode(self, params):
        """One single-token decode step over every decode-phase slot."""
        active = self._active
        dec = sorted(
            (s for s, st in active.items() if st.phase == "decode"),
            key=lambda s: active[s].admit_seq)
        for slot in dec:
            if slot in active:
                self._ensure_blocks(slot, int(self._lengths[slot]) + 1)
        dec = sorted(s for s, st in active.items()
                     if st.phase == "decode")
        if not dec:
            return
        bt = np.zeros_like(self._tables)
        bt[dec] = self._tables[dec]
        with self.timeline.span("decode", slots=len(dec)):
            args = (params, self._cache, jnp.asarray(self._tokens),
                    jnp.asarray(self._lengths), jnp.asarray(bt))
            if self.resident_window_blocks:
                args += (jnp.asarray(self._window_start),)
            args += self._samp_args(self._decode_counts())
            with self._decode_ctx():
                nxt, self._cache = self._get_decode_fn()(*args)
            nxt = np.asarray(nxt)
        self._c_decode_steps.inc()
        for slot in dec:
            st = active[slot]
            self._lengths[slot] += 1   # the fed token is now cached
            tok = int(nxt[slot])
            st.out.append(tok)
            self._emit_tokens(st, (tok,))
            self._mark_first(st)
            if (st.eos is not None and tok == st.eos) \
                    or st.gen_count >= st.req.max_new_tokens:
                self._finish_slot(slot)
            else:
                self._tokens[slot] = tok

    def _fence_harvest(self, *arrays):
        """The fused decode path's ONE host<->device synchronization point
        (the fence): dispatch above it is fully asynchronous, the scheduler
        blocks here exactly once per fused window, and every host-side
        scalar read below it comes out of the numpy buffers this returns.
        graft-lint GL012 sanctions per-token host harvesting only inside
        this helper — anywhere else in a scheduler loop body it flags."""
        self._c_host_fence_waits.inc()
        arrays = jax.block_until_ready(arrays)
        return tuple(np.asarray(a) for a in arrays)

    def _run_fused_decode(self, params):
        """``decode_steps`` decode iterations in ONE on-device program
        (the tentpole fused window): per-slot eos/budget checks run on
        device, and the host bookkeeping — lengths, token emission, SLO
        stamps, slot finishes — catches up in one batch at the fence by
        replaying the committed ``out[slot, i]`` tokens through the exact
        K=1 commit sequence.  Block tables are pre-reserved for the whole
        window before dispatch (``_ensure_blocks`` up to each slot's
        remaining-token budget), so every in-window KV write lands inside
        the slot's held span and the paged invariants hold at the next
        iteration boundary exactly as in single-step mode."""
        K = self._K
        active = self._active
        dec = sorted(
            (s for s, st in active.items() if st.phase == "decode"),
            key=lambda s: active[s].admit_seq)
        want: Dict[int, int] = {}
        for slot in dec:
            if slot in active and active[slot].phase == "decode":
                st = active[slot]
                ln = int(self._lengths[slot])
                w = max(1, min(K, st.req.max_new_tokens - st.gen_count))
                if self._masks is not None \
                        and st.req.mask_builder is not None:
                    # constrained slots advance ONE token per dispatch:
                    # the mask row is a host-built function of every
                    # token emitted so far, and the host can only refresh
                    # it between dispatches
                    w = 1
                want[slot] = w
                self._ensure_blocks(slot, min(ln + w, self._cache_len))
        dec = sorted(s for s, st in active.items()
                     if st.phase == "decode")
        if not dec:
            return
        budgets = np.zeros(self.slots, np.int32)
        eos_ids = np.full(self.slots, -1, np.int32)
        actv = np.zeros(self.slots, bool)
        for slot in dec:
            st = active[slot]
            ln = int(self._lengths[slot])
            # the device budget is additionally clamped to the held span —
            # a window can never write past the blocks it reserved
            span = int(np.count_nonzero(self._tables[slot])) \
                * self.block_size
            b = min(want.get(slot, K), max(span - ln, 0))
            if b < 1:
                continue
            budgets[slot] = b
            actv[slot] = True
            if st.eos is not None:
                eos_ids[slot] = int(st.eos)
        dec = [s for s in dec if actv[s]]
        if not dec:
            return
        bt = np.zeros_like(self._tables)
        bt[dec] = self._tables[dec]
        with self.timeline.span("decode", slots=len(dec), fused=K):
            with self._decode_ctx():
                out, self._cache = self._get_decode_fn()(
                    params, self._cache, jnp.asarray(self._tokens),
                    jnp.asarray(self._lengths), jnp.asarray(bt),
                    jnp.asarray(actv), jnp.asarray(budgets),
                    jnp.asarray(eos_ids),
                    *self._samp_args(self._decode_counts()))
            out, = self._fence_harvest(out)
        # ----- the fence catch-up: replay each slot's committed window
        # tokens through the exact K=1 commit sequence (emission order,
        # finish conditions, TTFT stamps — token- and event-identical)
        trips = 0
        for slot in dec:
            st = active[slot]
            emitted = 0
            for i in range(K):
                tok = int(out[slot, i])
                if tok < 0:
                    break
                emitted += 1
                self._lengths[slot] += 1
                st.out.append(tok)
                self._emit_tokens(st, (tok,))
                self._mark_first(st)
                if (st.eos is not None and tok == st.eos) \
                        or st.gen_count >= st.req.max_new_tokens:
                    self._finish_slot(slot)
                    break
                self._tokens[slot] = tok
            trips = max(trips, emitted)
        # decode_steps counts executed device ITERATIONS (the while_loop
        # trip count = the deepest slot's window), keeping per-iteration
        # FLOPs billing identical to single-step mode
        self._c_decode_steps.inc(trips)
        self._c_fused_iterations.inc(trips)

    def _run_spec_decode(self, params):
        """One speculative draft–verify round over every decode-phase slot.

        Propose K tokens per row (the draft model's one-program K-step
        rollout, or the host-side n-gram lookup), scatter+score the
        K+1-token window ``[pending, d_1..d_K]`` in ONE verify pass at each
        row's own base position, then commit the longest target-matching
        draft prefix plus the target's correction token
        (``spec.greedy_accept`` — token-exact with plain greedy decode).
        Rollback of rejected tokens is just *not advancing* the host
        lengths past the committed value: stale tail KV stays position-
        masked and is overwritten in place by the next round (blocks stay
        allocated, refcounts untouched).  Block demand is capped at each
        request's remaining completion budget (``pos_cap``) — window
        positions past the cap scatter to scratch instead of allocating.
        """
        k = self.spec_tokens
        active = self._active
        dec = sorted(
            (s for s, st in active.items() if st.phase == "decode"),
            key=lambda s: active[s].admit_seq)
        for slot in dec:
            if slot in active and active[slot].phase == "decode":
                st = active[slot]
                ln = int(self._lengths[slot])
                cap = max(st.pos_cap, ln + 1)
                self._ensure_blocks(slot,
                                    min(ln + k + 1, cap, self._cache_len))
        dec = sorted(s for s, st in active.items()
                     if st.phase == "decode")
        if not dec:
            return
        bt = np.zeros_like(self._tables)
        bt[dec] = self._tables[dec]
        samp = self._samp_args(self._decode_counts())
        with self.timeline.span(
                "spec_propose", slots=len(dec),
                mode="draft" if self._draft is not None else "ngram"):
            if self._draft is not None:
                with self._tp_ctx():
                    drafts, self._dcache = self._get_draft_fn()(
                        self._draft.params, self._dcache,
                        jnp.asarray(self._tokens),
                        jnp.asarray(self._lengths), jnp.asarray(bt),
                        *samp)
                drafts = np.asarray(drafts)
            else:
                drafts = np.zeros((self.slots, k), np.int32)
                for slot in dec:
                    st = active[slot]
                    drafts[slot] = self._proposer.propose(
                        np.concatenate([st.prompt_eff,
                                        np.asarray(st.out, np.int32)]))
        ids = np.zeros((self.slots, k + 1), np.int32)
        valid = np.zeros(self.slots, np.int32)
        ids[dec, 0] = self._tokens[dec]
        ids[dec, 1:] = drafts[dec]
        valid[dec] = k + 1
        with self.timeline.span("spec_verify", slots=len(dec), window=k + 1):
            with self._tp_ctx():
                out = self._get_verify_fn()(
                    params, self._cache, jnp.asarray(ids), jnp.asarray(bt),
                    jnp.asarray(self._lengths), jnp.asarray(valid), *samp)
            if self.sampling:
                scored, accept, plain, resid, self._cache = out
                accept = np.asarray(accept)
                plain = np.asarray(plain)
                resid = np.asarray(resid)
            else:
                scored, self._cache = out
            scored = np.asarray(scored)
        self._c_spec_rounds.inc()
        # a draft-model proposer caps acceptance at K-1: the K-th draft's
        # KV was never written to the draft pool, so accepting it would
        # desync the draft's next feed position (n-gram has no such state)
        max_accept = k - 1 if self._draft is not None else k
        accept_lens = []
        for slot in dec:
            st = active[slot]
            cap = max_accept
            if self._masks is not None and st.req.mask_builder is not None:
                # constrained slots accept 0 drafts per round: the mask
                # row is host-built per emitted token, so only the first
                # window position's (masked) distribution is valid.  The
                # cap-0 stop emits plain[0] — an unconditional draw from
                # the masked target distribution, exact by construction
                # (the unconsumed accept verdict never enters)
                cap = 0
            budget = st.req.max_new_tokens - st.gen_count
            if self.sampling:
                emitted, accepted, finished = rejection_accept(
                    ids[slot].tolist(), accept[slot].tolist(),
                    plain[slot].tolist(), resid[slot].tolist(), cap,
                    st.eos, budget)
                verdict = lambda i: bool(accept[slot][i])  # noqa: E731
            else:
                emitted, accepted, finished = greedy_accept(
                    ids[slot].tolist(), scored[slot].tolist(), cap,
                    st.eos, budget)
                verdict = lambda i: int(ids[slot][i + 1]) == \
                    int(scored[slot][i])                   # noqa: E731
            # acceptance telemetry counts PRE-truncation verdicts over
            # the drafts whose verdicts are real: cap-ineligible drafts
            # (the draft-model K-th, the whole window on constrained
            # slots) and positions past the completion budget (scratch-
            # routed, garbage logits) are excluded rather than counted
            # as rejections — eos/budget-truncated rounds would
            # otherwise read artificially rejection-heavy
            eligible = min(k, cap, budget)
            raw = 0
            while raw < eligible and verdict(raw):
                raw += 1
            self._c_drafted.inc(eligible)
            self._c_accepted.inc(raw)
            self._c_spec_rejected.inc(eligible - raw)
            if eligible:
                self._h_accept_ratio.observe(raw / eligible)
            accept_lens.append(accepted)
            st.out.extend(emitted)
            self._emit_tokens(st, emitted)
            self._mark_first(st)
            if finished:
                self._finish_slot(slot)
            else:
                # commit = pending + accepted drafts now durable in-cache;
                # the correction token becomes the new pending feed
                self._lengths[slot] += accepted + 1
                self._tokens[slot] = emitted[-1]
        self.timeline.instant("spec_accept", accept_lens=accept_lens,
                              drafted=k * len(dec))

    # ---------------------------------------------------------------- prefill
    def _run_prefill(self, params):
        """Advance prefilling slots: one fixed-width chunk per slot per
        iteration (chunked mode), or the whole prompt in its bucket's
        program (bucketed fallback).  Both modes run ``prefill_batch`` rows
        per call; pad rows write to scratch."""
        active = self._active
        pre = [s for s, st in sorted(active.items(),
                                     key=lambda kv: kv[1].admit_seq)
               if st.phase == "prefill"]
        if not pre:
            return
        if self.chunked_prefill:
            groups = []
            ready = []
            for slot in pre:
                if slot not in active:
                    continue               # preempted by an earlier alloc
                st = active[slot]
                v = min(self.prefill_chunk, st.plen_eff - st.base)
                if self._ensure_blocks(slot, st.base + v):
                    ready.append(slot)
            for i in range(0, len(ready), self.prefill_batch):
                group = [s for s in ready[i:i + self.prefill_batch]
                         if s in active]
                if group:
                    groups.append((self.prefill_chunk, group))
        else:
            by_bucket: Dict[int, list] = {}
            for slot in pre:
                if slot not in active:
                    continue
                st = active[slot]
                if self._ensure_blocks(slot, st.plen_eff):
                    by_bucket.setdefault(self._prefill_width(st.plen_eff),
                                         []).append(slot)
            groups = []
            for bucket in sorted(by_bucket):
                grp = by_bucket[bucket]
                for i in range(0, len(grp), self.prefill_batch):
                    group = [s for s in grp[i:i + self.prefill_batch]
                             if s in active]
                    if group:
                        groups.append((bucket, group))

        for width, group in groups:
            group = [s for s in group if s in active]
            if not group:
                continue
            self._run_prefill_group(width, group, params)

    def _run_prefill_group(self, width, group, params):
        """One prefill call: each row advances its slot by ``min(width,
        remaining prompt)`` tokens from its own base.  Rows whose window
        reaches the last prompt token yield that slot's first generated
        token (logits are gathered per row at ``valid - 1``)."""
        active = self._active
        j = self.prefill_batch
        ids = np.zeros((j, width), np.int32)
        bt = np.zeros((j, self._nbper), np.int32)
        base = np.zeros(j, np.int32)
        valid = np.zeros(j, np.int32)
        rows = []
        for row, slot in enumerate(group):
            st = active[slot]
            v = min(width, st.plen_eff - st.base)
            ids[row, :v] = st.prompt_eff[st.base:st.base + v]
            bt[row] = self._tables[slot]
            base[row] = st.base
            valid[row] = v
            rows.append((slot, v))
        samp = self._samp_args_rows(group, j)
        with self.timeline.span("prefill", width=width, rows=len(group),
                                slots=list(map(int, group))):
            if self._draft is not None:
                with self._tp_ctx():
                    first, self._cache, self._dcache = \
                        self._get_prefill_fn(width)(
                            params, self._draft.params, self._cache,
                            self._dcache, jnp.asarray(ids), jnp.asarray(bt),
                            jnp.asarray(base), jnp.asarray(valid), *samp)
            else:
                args = (params, self._cache, jnp.asarray(ids),
                        jnp.asarray(bt), jnp.asarray(base),
                        jnp.asarray(valid))
                if self.resident_window_blocks:
                    # per-ROW window starts (prefill batches rows from
                    # arbitrary slots); pad rows stay 0 = fully visible
                    ws = np.zeros(j, np.int32)
                    for row, slot in enumerate(group):
                        ws[row] = self._window_start[slot]
                    args += (jnp.asarray(ws),)
                args += samp
                with self._tp_ctx(), self._sp_ctx():
                    first, self._cache = self._get_prefill_fn(width)(*args)
            first = np.asarray(first)
        if self.sp_degree > 1:
            nbytes = sp_attention.alltoall_bytes(
                int(self._pool_shape[0]), len(group), width,
                getattr(self.engine.module.model_config, "num_heads",
                        int(self._pool_shape[2])),
                int(self._pool_shape[4]),
                jnp.dtype(self.engine._config.jnp_dtype).itemsize,
                self.sp_degree)
            self._c_sp_a2a_bytes.inc(nbytes)
            self.timeline.instant("sp_prefill", width=width,
                                  rows=len(group), bytes=nbytes,
                                  sp=self.sp_degree)
        self._c_prefill_calls.inc()
        self._prefill_calls_by_width[width] = \
            self._prefill_calls_by_width.get(width, 0) + 1
        for row, (slot, v) in enumerate(rows):
            st = active[slot]
            st.base += v
            if st.base < st.plen_eff:
                continue                   # more chunks to go
            st.phase = "decode"
            if self._prefix is not None:
                # cache the prompt's FULL blocks (the trailing partial block
                # will also hold generated tokens — never shared)
                nfull = st.plen_eff // self.block_size
                if self.resident_window_blocks:
                    # window slides during prefill punch a scratch hole in
                    # the table — only the leading still-resident run is a
                    # valid trie chain (the demoted middle lives host-side
                    # under its chain keys, not in the trie)
                    run = 0
                    trow = self._tables[slot]
                    while run < nfull and int(trow[run]) != SCRATCH_BLOCK:
                        run += 1
                    nfull = run
                if nfull:
                    self._prefix.register(st.prompt_eff,
                                          self._tables[slot, :nfull],
                                          self._alloc)
            if self.spec_tokens and self.sampling and st.prior \
                    and st.req.sampled:
                # spec-sampled RESUME: the original stream's token at
                # emission index len(prior) came out of a verify round
                # (accept/residual salts), not the prefill TOKEN salt —
                # so don't emit here.  Back up one position instead: feed
                # the last resumed token as the pending window head with
                # lengths = plen_eff - 1 (the verify scatter rewrites
                # that position's KV with identical values), and the next
                # round starts at count len(prior) — the exact boundary
                # the original round structure had, so replay is
                # round-identical and token-exact
                self._tokens[slot] = int(st.prompt_eff[-1])
                self._lengths[slot] = st.plen_eff - 1
                continue
            tok = int(first[row])
            st.out.append(tok)
            self._emit_tokens(st, (tok,))
            self._mark_first(st)
            self._tokens[slot] = tok
            self._lengths[slot] = st.plen_eff
            if (st.eos is not None and tok == st.eos) \
                    or st.gen_count >= st.req.max_new_tokens:
                self._finish_slot(slot)

    # ------------------------------------------------------------------ stats
    def resolved_config(self) -> Dict[str, Any]:
        """The engine's resolved serving knobs as a **round-trippable**,
        JSON-able ``init_serving`` kwargs dict: ``init_serving(model,
        **srv.resolved_config())`` rebuilds a behaviorally identical
        engine (auto knobs — ``chunked_prefill``, ``shard_kv``,
        ``num_blocks``, SLO targets — come back resolved, so the rebuilt
        engine does not depend on the defaults in force when this one was
        built).  This is what autotuner trials, ``best_config.json``, and
        the bench JSONs persist so a winning config reproduces from
        artifacts alone.

        Not captured: the wrapped ``init_inference`` engine itself (model,
        params, dtype, quant group sizes) and a ``draft`` model object —
        a draft-model speculative engine round-trips to the n-gram
        proposer at the same ``spec_tokens``.
        """
        return {
            "slots": self.slots,
            "max_seq_len": self.max_seq_len,
            "block_size": self.block_size,
            "num_blocks": int(self._alloc.num_blocks),
            "chunked_prefill": bool(self.chunked_prefill),
            "prefill_chunk": int(self.prefill_chunk),
            "decode_steps": self._K,
            "engine_mode": self.engine_mode,
            "sp": self.sp_degree,
            "resident_window_blocks": self.resident_window_blocks,
            "prompt_buckets": list(self.prompt_buckets) or None,
            "prefill_batch": self.prefill_batch,
            "prefix_caching": self._prefix is not None,
            "spec_tokens": self.spec_tokens,
            "ngram_max": self.ngram_max,
            "ngram_min": self.ngram_min,
            "sampling": self.sampling,
            "spec_verifier": self.spec_verifier,
            "logit_masks": self.logit_masks,
            "quantize": self.quantize,
            "host_blocks": self.host_blocks,
            "swap_batch": self.swap_batch,
            "role": self.role,
            "nvme_blocks": self.nvme_blocks,
            "nvme_high_watermark": self.nvme_high_watermark,
            # the user-passed path (None = auto tempfile): a rebuilt
            # engine mints its OWN spill file rather than contending for
            # this engine's — behaviorally identical, never shared
            "nvme_path": self._nvme_path_arg,
            "shard_kv": bool(self.kv_sharded),
            "topology": self.tp_degree,
            "debug_checks": self.debug_checks,
            "trace_capacity": int(self.timeline.capacity),
            "slo_targets": {cls: dict(t)
                            for cls, t in self._slo.targets.items()},
            "peak_flops": self.peak_flops,
        }

    def _kv_footprint(self) -> Dict[str, Any]:
        """KV memory accounting: pool shape, total logical bytes (quant-
        adjusted — int8 codes + the scale table when ``kv8``), and each
        chip's share — ``total / tp`` when the pool is head-sharded, the
        whole pool when replicated (the pool replicates across every other
        mesh axis, so tp is the only divisor; the scale table carries the
        head dim too, so it shards with the codes)."""
        def _bytes(tree):
            return int(sum(x.size * x.dtype.itemsize
                           for x in jax.tree_util.tree_leaves(tree)))

        total = _bytes(self._cache)
        scale_bytes = int(sum(
            leaf["ps"].size * leaf["ps"].dtype.itemsize
            for leaf in jax.tree_util.tree_leaves(
                self._cache, is_leaf=paged_kv.is_quantized_pool)
            if paged_kv.is_quantized_pool(leaf)))
        out = {
            "tp_degree": self.tp_degree,
            "kv_sharded": self.kv_sharded,
            "quantize": self.quantize,
            "kv_dtype": self._kv_dtype,
            "weight_quant": self.weight_quant,
            "kv_scale_bytes": scale_bytes,
            "kv_pool_shape": list(self._pool_shape),
            "kv_pool_bytes": total,
            # dp_tp: the block dim shards over dp too, so each chip holds
            # total / (dp * tp) — the same per-chip bytes as one tp-only
            # replica serving 1/dp of the load
            "kv_pool_bytes_per_chip": total //
            (self.dp_degree * (self.tp_degree if self.kv_sharded else 1)),
        }
        if self._dcache is not None:
            dtotal = _bytes(self._dcache)
            out["draft_pool_bytes"] = dtotal
            out["draft_pool_bytes_per_chip"] = dtotal // \
                (self.tp_degree if self._dcache_sharded else 1)
        return out

    def _latency_stats(self) -> Dict[str, Any]:
        """TTFT/TPOT percentiles over every finished request (cumulative
        across serve calls, like the other counters) — read from the
        registry's streaming histograms: bounded memory, no re-sort, and
        still ``None`` before the first finished request."""
        out: Dict[str, Any] = {
            "requests_finished": int(self._c_finished.value)}
        for key, hist in (("ttft", self._h_ttft), ("tpot", self._h_tpot)):
            for q in (50, 95):
                out[f"{key}_p{q}_s"] = hist.quantile(q / 100.0)
        return out

    def stats(self) -> Dict[str, Any]:
        """Serving-loop observability: compile probe, prefix-cache hit
        rate, block occupancy, admission/eviction counters, per-request
        latency percentiles, the KV memory footprint (pool shape, total
        bytes, bytes per chip under tp sharding), and — in speculative
        mode — draft/accept counters and the acceptance rate.

        Every counter/latency value is a view over ``self.metrics``
        (``telemetry/``) — ``metrics.prometheus_text()`` and
        ``metrics.snapshot()`` expose the same data for scrapes and
        bench artifacts; the key set here is stable across PRs."""
        self._g_blocks_in_use.set(self._alloc.blocks_in_use)
        self._g_free_blocks.set(self._alloc.free_blocks)
        if self._host is not None:
            self._g_host_blocks_in_use.set(self._host.blocks_in_use)
        if self._nvme is not None:
            self._g_nvme_in_use.set(self._host.nvme_blocks_in_use)
        st = {
            "mode": "chunked" if self.chunked_prefill else "bucketed",
            "compile_count": self.compile_count,
            "compile_budget": self.compile_budget,
            "debug_checks": self.debug_checks,
            "invariant_checks_run": self.invariant_checks_run,
            "retraces_observed": self.sentry.retraces_observed,
            # process-wide, cumulative since the listener was installed
            # (None until a debug_checks engine installs it)
            "backend_compiles": backend_compiles(),
            "iterations": self.iterations,
            "decode_steps": self.decode_steps,
            "engine_mode": self.engine_mode,
            "fused_iterations": int(self._c_fused_iterations.value),
            "host_fence_waits": int(self._c_host_fence_waits.value),
            "prefill_calls": self.prefill_calls,
            "admitted": self.admitted,
            "evicted": self.preempted,
            "cancelled": int(self._c_cancelled.value),
            "queue_depth": len(self._pending),
            "generated_tokens": int(self._c_gen_tokens.value),
            "prompt_tokens": self.prompt_tokens,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "prefix_cache_hit_rate": (
                self.prefix_hit_tokens / self.prompt_tokens
                if self.prompt_tokens else 0.0),
            "prefix_cache_entries": len(self._prefix)
            if self._prefix is not None else 0,
            "prefix_cache_evictions": self._prefix.evictions
            if self._prefix is not None else 0,
            "blocks_in_use": self._alloc.blocks_in_use,
            "free_blocks": self._alloc.free_blocks,
            "num_blocks": self._alloc.num_blocks,
            "block_size": self.block_size,
            "speculative": (
                None if not self.spec_tokens else
                f"draft:{self._draft.module.name}" if self._draft
                else "ngram"),
            "spec_tokens": self.spec_tokens,
            "spec_rounds": self.spec_rounds,
            "drafted_tokens": self.drafted_tokens,
            "accepted_tokens": self.accepted_tokens,
            "acceptance_rate": (self.accepted_tokens / self.drafted_tokens
                                if self.drafted_tokens else 0.0),
            # sampling stack (sampling=False: flags off, zeros — schema
            # stays stable)
            "sampling": self.sampling,
            "spec_verifier": self.spec_verifier,
            "logit_masks": self.logit_masks,
            "sampled_requests": int(
                self._c_sampled["sampled"].value
                + self._c_sampled["constrained"].value),
            "spec_draft_rejected": int(self._c_spec_rejected.value),
            # long-context lane (sp=1 / window off: 1-and-zeros — schema
            # stays stable)
            "sp": self.sp_degree,
            "resident_window_blocks": self.resident_window_blocks,
            "context_window_slides": int(self._c_window_slides.value),
            "sp_alltoall_bytes": int(self._c_sp_a2a_bytes.value),
            # tiered KV (host_blocks=0: zeros — schema stays stable)
            "host_blocks": self.host_blocks,
            "host_blocks_in_use": self._host.blocks_in_use
            if self._host is not None else 0,
            "host_pool_bytes": self._host.arena_bytes
            if self._host is not None else 0,
            "swap_in": int(self._c_swap_in.value),
            "swap_out": int(self._c_swap_out.value),
            "swap_bytes": int(self._c_swap_bytes.value),
            "prefetch_misses": int(self._c_prefetch_miss.value),
            "prefetch_wait_p50_s": self._h_prefetch_wait.quantile(0.50),
            "prefetch_wait_p95_s": self._h_prefetch_wait.quantile(0.95),
            "resume_recompute_tokens": int(self._c_resume_recompute.value),
            # disaggregated serving + NVMe third tier (role="both" /
            # nvme_blocks=0: "both" and zeros — schema stays stable)
            "role": self.role,
            "handoffs": int(self._c_handoffs.value),
            "nvme_blocks": self.nvme_blocks,
            "nvme_blocks_in_use": self._host.nvme_blocks_in_use
            if self._host is not None else 0,
            "nvme_spills": self._host.nvme_spills
            if self._host is not None else 0,
            "nvme_loads": self._host.nvme_loads
            if self._host is not None else 0,
            # timeline ring health (telemetry/trace.py): dropped > 0 means
            # the ring wrapped — raise trace_capacity for longer history
            "trace_capacity": self.timeline.capacity,
            "trace_events": len(self.timeline),
            "trace_events_dropped": self.timeline.dropped,
            # round-trippable init_serving kwargs (autotuner trials and
            # bench JSONs reproduce the engine from artifacts alone)
            "config": self.resolved_config(),
        }
        st.update(self._kv_footprint())
        st.update(self._latency_stats())
        return st
