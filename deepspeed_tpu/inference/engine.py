"""Inference engine.

Analog of reference ``deepspeed/inference/engine.py:35`` (``InferenceEngine``).
Wraps a :class:`ModelSpec`, shards its params over a ``tp`` mesh axis (the
auto-TP analog: our models carry Megatron-style PartitionSpecs in ``tp_rules``,
so "injection" is a sharding annotation instead of a module swap), casts to the
inference dtype, and compiles the forward.  ``jit`` replaces CUDA-graph
capture/replay (reference :479/:498).

Decode runs over a static KV cache when the model carries ``decode_hooks``
(prefill + single-token steps through the Pallas decode-attention kernel,
``ops/decode_attention.py`` — the reference ``softmax_context`` analog); models
without hooks fall back to full-recompute generation.  Both loops early-exit via
``lax.while_loop`` once every sequence has emitted ``eos_token_id``.  Compiled
generate programs are cached per shape with true LRU eviction.  For mixed-length
request traffic, the continuous-batching scheduler in ``inference/serving.py``
replaces these one-shot static batches with a slot-based KV pool and
iteration-level scheduling.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import comm as dist
from ..analysis.sentry import RecompileSentry
from ..parallel.topology import MeshTopology
from ..runtime.model import ModelSpec
from ..utils.logging import log_dist
from ..utils.lru import LRUCache
from .config import DeepSpeedInferenceConfig


def _auto_seed(obj, seed):
    """Fresh draws per call (HF generate uses a stateful RNG); pass an
    explicit seed for reproducibility.  Shared by the resident and
    streamed (zero_inference) generate paths."""
    if seed is not None:
        return seed
    obj._sample_calls = getattr(obj, "_sample_calls", -1) + 1
    return obj._sample_calls


def _fill_after_eos(out, prompt_len, eos_token_id):
    """Back-fill everything after the first eos with eos (HF padding
    semantics).  Shared by the resident and streamed generate paths.

    Vectorized: a cumulative "eos seen" mask over the generated region,
    shifted right one column, marks every position strictly after each
    row's first eos (the eos itself stays; rows without eos are untouched;
    eos inside the prompt is ignored)."""
    if eos_token_id is not None and out.shape[1] > prompt_len:
        gen = out[:, prompt_len:]          # view — writes land in ``out``
        seen = np.cumsum(gen == eos_token_id, axis=1) > 0
        after = np.concatenate(
            [np.zeros((out.shape[0], 1), bool), seen[:, :-1]], axis=1)
        gen[after] = eos_token_id
    return out


def _cast_floating_skip_records(tree, dtype):
    """Cast float leaves to the serving dtype, leaving quantization
    records intact: PRE-QUANTIZED param trees (a quantized checkpoint, or
    a host tree quantized offline at 30B scale) must keep int8 payloads
    and f32 scales — the w8a8 kernel consumes f32 scales, and casting
    them to bf16 would silently degrade every matmul."""
    from ..ops import quantization as quant

    def cast(x):
        if quant.is_record(x):
            return x
        if jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(cast, tree, is_leaf=quant.is_record)


class InferenceEngine:

    def __init__(self, model: ModelSpec, config: DeepSpeedInferenceConfig,
                 params=None):
        assert isinstance(model, ModelSpec), (
            "init_inference expects a deepspeed_tpu ModelSpec")
        assert model.apply_fn is not None, "ModelSpec.apply_fn required for inference"
        self.module = model
        self._config = config
        # Engines own trace-time model-config state (same contract as the
        # training engine's remat/liveness wiring): serving always scans one
        # layer per step — clear a ZeRO-3 G left by a training engine that
        # shared this model object.
        mc = getattr(model, "model_config", None)
        if mc is not None and hasattr(mc, "scan_group_size"):
            mc.scan_group_size = 1

        tp = config.tensor_parallel.tp_size if config.tensor_parallel.enabled else 1
        sp = int(getattr(config, "sequence_parallel", 1) or 1)
        dist.init_distributed()
        n = len(jax.devices())
        assert n % max(tp, 1) == 0, f"tp_size {tp} does not divide {n} devices"
        if n % (max(tp, 1) * max(sp, 1)) != 0:
            raise ValueError(
                f"sequence_parallel={sp} x tp_size={tp} does not divide "
                f"{n} devices")
        self.topology = MeshTopology(tp=tp, sp=sp,
                                     dp=n // (max(tp, 1) * max(sp, 1)))
        dist.configure(topology=self.topology)
        self.mesh = self.topology.mesh

        if params is None:
            # init_fn: immune to a user-held OnDevice('meta') context
            params = model.init_fn(jax.random.PRNGKey(0))
        params = _cast_floating_skip_records(params, config.jnp_dtype)
        tp_specs = model.tp_rules(jax.eval_shape(lambda: params)) \
            if model.tp_rules else None
        rep = NamedSharding(self.mesh, P())
        if tp_specs is not None:
            shardings = jax.tree_util.tree_map(
                lambda spec: NamedSharding(self.mesh, spec), tp_specs,
                is_leaf=lambda x: isinstance(x, P))
        else:
            shardings = jax.tree_util.tree_map(lambda _: rep, params)

        # INT8 weight-only storage (reference GroupQuantizer /
        # ZeRO-Inference): weights live in HBM as int8 + per-group scales.
        # quant-aware models (ModelSpec.quant_aware) dequantize lazily at
        # point of use — per-LAYER peak memory; for others the engine
        # dequantizes the whole tree at jit entry (int8 halves RESTING
        # weight memory but the forward's peak holds a full-precision copy)
        self._quantized = config.quant.enabled
        if self._quantized:
            from ..ops import quantization as quant
            from ..ops import quantized_matmul as qmm

            # the weight-only fused kernel needs an unsharded weight path
            # (pallas_calls are opaque to the GSPMD partitioner); the w8a8
            # kernel instead runs TP-sharded through a custom_partitioning
            # wrapper — column shards run the s8 kernel locally, row shards
            # psum a local partial (ops/quantized_matmul._w8a8_tp_call)
            qmm.configure(kernel_ok=(tp <= 1), w8a8_tp=(tp > 1))
            if tp > 1 and config.quant.type == "w8a8":
                log_dist(
                    "quant: w8a8 under tensor parallelism — decode matmuls "
                    "run sharded via custom_partitioning (s8 kernel per "
                    "shard; row-parallel adds one psum); weights whose "
                    "quant groups don't divide tp gather instead (warned "
                    "per shape at compile)", ranks=[0])

            # Quantize on the HOST: jnp ops on uncommitted (numpy) inputs
            # follow default_device, so stacked multi-billion-param leaves
            # never materialize an f32 copy in HBM (OPT-6.7B's stacked fc_w
            # alone is 8.6GB f32 — quantizing on-device OOMed a 16GB chip).
            # Only the int8 payload + scales reach the device, via the
            # sharded device_put below.
            #
            # Quant-aware models quantize the TRANSFORMER BLOCKS only (the
            # reference GroupQuantizer likewise targets layer weights, not
            # embeddings): a quantized embedding would be re-dequantized by
            # every decode step inside the token loop — measured ~6ms/token
            # on OPT-1.3B — for a memory saving that is <5% of the model.
            params = jax.device_get(params)
            hooks = getattr(model, "pipeline_hooks", None) or {}
            bkey = (getattr(model, "blocks_key", None)
                    or hooks.get("blocks_key")) if model.quant_aware else None
            w8a8 = config.quant.type == "w8a8"
            if w8a8 and bkey is None:
                raise ValueError(
                    f"quant.type 'w8a8' needs a quant-aware model with "
                    f"stacked blocks; {model.name} is not — use the "
                    f"default weight-only type")
            # blocks subtrees are STACKED [L, ...]: min_ndim=3 keeps
            # per-layer 1D params (e.g. [L, 3d] qkv_b) dense — at L >= 64
            # they'd otherwise pass the weight-matrix shape tests
            if w8a8:
                kg = max(128, int(config.quant.group_size))
                # group sizes refine so row-parallel K shards never split a
                # quant group — otherwise _w8a8_partition must gather the
                # weight (e.g. OPT-2.7B K=2560 at tp=8: g=128 -> 20 groups,
                # 20 % 8 != 0 -> gathered; g=80 -> 32 groups, sharded).
                # With the degree DERIVED from tp, only K-sharded leaves
                # refine (spec-aware: finer groups cost scale storage +
                # kernel trip count, pointless on column shards); an
                # EXPLICIT quant.shard_multiple refines uniformly so the
                # records stay bit-identical across tp degrees.
                sm = config.quant.shard_multiple or tp
                spec_aware = config.quant.shard_multiple is None and tp > 1

                def _quantize(tree, min_ndim, specs=None):
                    return quant.quantize_pytree_k_grouped(
                        tree, k_group=kg, min_ndim=min_ndim,
                        shard_multiple=sm,
                        spec_tree=specs if spec_aware else None)
            else:
                def _quantize(tree, min_ndim, specs=None):
                    return quant.quantize_pytree(
                        tree, num_bits=config.quant.num_bits,
                        group_size=config.quant.group_size,
                        min_ndim=min_ndim)
            prequantized = any(
                quant.is_record(leaf) for leaf in jax.tree_util.tree_leaves(
                    params, is_leaf=quant.is_record))
            if prequantized:
                # pre-quantized tree (quantized checkpoint / offline host
                # quantization): records pass through untouched — the kind
                # must match the configured quant type
                kinds = {
                    "w8a8" if quant.is_k_quantized(leaf) else "weight"
                    for leaf in jax.tree_util.tree_leaves(
                        params, is_leaf=quant.is_record)
                    if quant.is_record(leaf)}
                if kinds != {config.quant.type}:
                    raise ValueError(
                        f"pre-quantized params carry {sorted(kinds)} records "
                        f"but quant.type is {config.quant.type!r}")
                log_dist("quant: params arrived pre-quantized — skipping "
                         "host-side quantization", ranks=[0])
            with jax.default_device(jax.local_devices(backend="cpu")[0]):
                if prequantized:
                    pass
                elif bkey is not None:
                    path = (bkey,) if isinstance(bkey, str) else tuple(bkey)
                    node, snode = params, shardings
                    for k in path[:-1]:
                        node, snode = node[k], snode[k]
                    node[path[-1]] = _quantize(node[path[-1]], min_ndim=3,
                                               specs=snode[path[-1]])
                else:
                    params = _quantize(params, min_ndim=2, specs=shardings)
            params = jax.device_get(params)

            def _rec_shardings(x, s):
                if not quant.is_record(x):
                    return s
                out = {}
                for k in x:
                    if k in ("q", "qk"):
                        out[k] = s
                    elif k == "kscale" and getattr(s, "spec", None) is not None:
                        # kscale [..., K/G, 1, N] follows the weight's
                        # [..., K, N] spec (K-dim sharding lands on the K/G
                        # dim) so TP decode never re-slices a replicated
                        # scale tree; dims the axis doesn't divide (e.g.
                        # K/G=1 at hidden==k_group) stay replicated
                        spec = tuple(s.spec)
                        spec = spec + (None,) * (x[k].ndim - 1 - len(spec))
                        kspec = spec[:-2] + (spec[-2], None, spec[-1])
                        kspec = tuple(
                            ax if x[k].shape[i] %
                            qmm.axis_size(self.mesh, ax) == 0 else None
                            for i, ax in enumerate(kspec))
                        out[k] = NamedSharding(self.mesh, P(*kspec))
                    else:
                        out[k] = rep
                return out

            shardings = jax.tree_util.tree_map(
                _rec_shardings, params, shardings, is_leaf=quant.is_record)
            if model.quant_aware:
                self._prepare = lambda p: p
            else:
                log_dist(
                    f"quant: model {model.name} is not quant_aware — "
                    "dequantizing the full tree at jit entry (peak memory "
                    "includes a full-precision copy)", ranks=[0])
                self._prepare = lambda p: quant.dequantize_pytree(
                    p, config.jnp_dtype)
        else:
            self._prepare = lambda p: p
        self._streamed = None
        if config.zero_inference.enabled:
            params, shardings = self._init_zero_inference(params, shardings)
        self.params = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, s), params, shardings)

        prepare = self._prepare
        # recompile sentry (analysis/sentry.py): forward legitimately
        # specializes per batch shape (budget=None, count only); each
        # shape-keyed generate program below declares budget 1 — a retrace
        # of an already-built program is always contract drift
        self.sentry = RecompileSentry(name=f"inference:{model.name}")
        # telemetry registry (telemetry/): profile_model_time observes
        # per-forward wall clock into it, and wrappers (ServingEngine has
        # its own) can hang engine-level metrics here
        from ..telemetry import MetricsRegistry

        self.metrics = MetricsRegistry()
        self._forward_fn = jax.jit(self.sentry.wrap(
            lambda p, batch: model.apply_fn(prepare(p), batch, None),
            "forward", budget=None))
        # bounded per-shape jit cache; hot shapes survive eviction pressure
        # (utils/lru.py — same policy as ServingEngine's prefill-fn cache)
        self._generate_fns = LRUCache(capacity=32)
        if self._streamed is not None:
            self._streamed.resident = self.params
        log_dist(f"InferenceEngine: mesh={self.topology}, dtype={config.dtype}",
                 ranks=[0])

    def _init_zero_inference(self, params, shardings):
        """ZeRO-Inference mode (inference/zero_inference.py): pull the
        stacked blocks OUT of the device tree — they stay host-resident
        (int8 records when quantized) and stream per layer during
        generate.  Returns the resident (params, shardings) to place."""
        from .zero_inference import StreamedGenerator

        model, config = self.module, self._config
        zi = config.zero_inference
        if model.stream_hooks is None or model.decode_hooks is None:
            raise ValueError(
                f"zero_inference needs a model with stream_hooks + "
                f"decode_hooks; {model.name} has neither — serve it "
                "resident or add the per-layer hooks")
        if self.topology.tensor_parallel_size > 1:
            raise ValueError(
                "zero_inference streams layers through ONE device's HBM; "
                "combine with tp later or drop tensor_parallel")
        hooks = getattr(model, "pipeline_hooks", None) or {}
        bkey = hooks.get("blocks_key")
        if bkey is None:
            raise ValueError(
                f"zero_inference needs pipeline_hooks.blocks_key on "
                f"{model.name} to locate the stacked blocks")
        path = (bkey,) if isinstance(bkey, str) else tuple(bkey)
        params = jax.device_get(params)
        node, snode = params, shardings
        for k in path[:-1]:
            node, snode = node[k], snode[k]
        host_blocks = node.pop(path[-1])
        snode.pop(path[-1])
        num_layers = jax.tree_util.tree_leaves(host_blocks)[0].shape[0]
        self._streamed = StreamedGenerator(
            resident_params=None,  # set after device_put of the residents
            host_blocks=host_blocks, num_layers=num_layers,
            stream_hooks=model.stream_hooks,
            init_cache=model.decode_hooks["init_cache"],
            cache_dtype=config.jnp_dtype,
            pin_layers=zi.pin_layers, prefetch=zi.prefetch,
            sync_every=zi.sync_every,
            picker_factory=_make_token_picker)
        return params, shardings

    # ------------------------------------------------------------------ forward
    def forward(self, batch):
        """Logits for a batch (reference ``inference/engine.py:541``)."""
        if self._streamed is not None:
            raise NotImplementedError(
                "zero_inference serves via generate(); whole-batch logits "
                "would stream every layer for one forward — run a resident "
                "engine (or generate with max_new_tokens=1) instead")
        batch = self._put_batch(batch)
        return self._forward_fn(self.params, batch)

    __call__ = forward

    def _put_batch(self, batch):
        dp_total = self.topology.data_parallel_size

        def put(x):
            x = jnp.asarray(x)
            spec = P(("dp", "ep")) if (x.ndim > 0 and x.shape[0] % dp_total == 0) \
                else P()  # small batches replicate rather than fail
            return jax.device_put(x, NamedSharding(self.mesh, spec))

        return jax.tree_util.tree_map(put, batch)

    # ----------------------------------------------------------------- generate
    def generate(self, input_ids, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0,
                 seed: Optional[int] = None):
        """Decode with static shapes (reference ``_generate`` :571; beam
        search is likewise rejected there).  ``do_sample=True`` enables
        temperature / top-k / top-p sampling in-graph; default is greedy."""
        input_ids = np.asarray(input_ids)
        b, prompt_len = input_ids.shape
        total = prompt_len + max_new_tokens
        hooks = self.module.decode_hooks
        max_ctx = (hooks or {}).get("max_seq_len")
        if max_ctx is not None and total > max_ctx:
            raise ValueError(
                f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
                f"= {total} exceeds the model context length {max_ctx}")
        if self._streamed is not None:
            return self._streamed.generate(
                input_ids, max_new_tokens=max_new_tokens,
                eos_token_id=eos_token_id, do_sample=do_sample,
                temperature=temperature, top_k=top_k, top_p=top_p,
                seed=seed)
        sample_cfg = (do_sample, float(temperature), int(top_k),
                      float(top_p)) if do_sample else None
        # eos is part of the compiled program (early-exit while_loop)
        key = (b, prompt_len, max_new_tokens, sample_cfg, eos_token_id)

        def build():
            if self.module.decode_hooks is not None:
                return self._build_kv_cache_gen(
                    b, prompt_len, total, sample_cfg, eos_token_id)
            return self._build_recompute_gen(
                b, prompt_len, total, sample_cfg, eos_token_id)

        gen_fn = self._generate_fns.get_or_build(key, build)
        rng = jax.random.PRNGKey(_auto_seed(self, seed))
        out = gen_fn(self.params, jnp.asarray(input_ids), rng)
        out = np.array(out)  # writable host copy (np.asarray view is read-only)
        return _fill_after_eos(out, prompt_len, eos_token_id)

    @staticmethod
    def _gen_name(kind, b, prompt_len, total, sample_cfg, eos_token_id):
        """Sentry entry name for a shape-keyed generate program: each key
        compiles once (budget 1) — same identity as ``_generate_fns``'
        cache key, so an LRU-evicted rebuild is visible as a recount."""
        return (f"generate/{kind}[b={b},plen={prompt_len},total={total},"
                f"sample={sample_cfg},eos={eos_token_id}]")

    def _build_recompute_gen(self, b, prompt_len, total, sample_cfg=None,
                             eos_token_id=None):
        """Full-recompute fallback for models without decode hooks.  With an
        ``eos_token_id`` the token loop is a ``lax.while_loop`` that stops
        stepping once every sequence has emitted eos (positions past a
        row's eos stay 0 in-graph; ``_fill_after_eos`` back-fills them)."""
        apply_fn = self.module.apply_fn
        pick = _make_token_picker(sample_cfg)
        prepare = self._prepare

        def gen(params, ids, rng):
            params = prepare(params)
            buf = jnp.zeros((b, total), jnp.int32)
            buf = buf.at[:, :prompt_len].set(ids)

            def step(i, buf):
                logits = apply_fn(params, {"input_ids": buf}, None)
                next_tok = pick(logits[:, i - 1, :],
                                jax.random.fold_in(rng, i))
                return buf.at[:, i].set(next_tok), next_tok

            if eos_token_id is None:
                return jax.lax.fori_loop(
                    prompt_len, total,
                    lambda i, buf: step(i, buf)[0], buf)

            def cond(carry):
                _, i, done = carry
                return (i < total) & ~jnp.all(done)

            def body(carry):
                buf, i, done = carry
                buf, next_tok = step(i, buf)
                return buf, i + 1, done | (next_tok == eos_token_id)

            buf, _, _ = jax.lax.while_loop(
                cond, body,
                (buf, jnp.int32(prompt_len), jnp.zeros((b,), bool)))
            return buf

        return jax.jit(self.sentry.wrap(gen, self._gen_name(
            "recompute", b, prompt_len, total, sample_cfg, eos_token_id)))

    def _build_kv_cache_gen(self, b, prompt_len, total, sample_cfg=None,
                            eos_token_id=None):
        """Prefill + single-token decode loop over a static KV cache
        (reference ``softmax_context`` path; workspace sized like
        ``inference_context.h`` by the token budget).  With an
        ``eos_token_id`` the loop is a ``lax.while_loop`` that stops once
        every sequence has emitted eos — mixed-length batches stop at the
        LAST-finishing row instead of always burning the full budget."""
        hooks = self.module.decode_hooks
        init_cache, forward_cached = hooks["init_cache"], hooks["forward_cached"]
        # round the workspace up so the Pallas kernel's block_k divides it
        cache_len = -(-total // 128) * 128
        cache_dtype = self._config.jnp_dtype
        pick = _make_token_picker(sample_cfg)
        prepare = self._prepare

        def gen(params, ids, rng):
            params = prepare(params)
            cache = init_cache(b, cache_len, cache_dtype)
            buf = jnp.zeros((b, total), jnp.int32)
            buf = buf.at[:, :prompt_len].set(ids)
            logits, cache = forward_cached(params, ids, cache, 0)   # prefill
            first = pick(logits, jax.random.fold_in(rng, prompt_len))
            buf = buf.at[:, prompt_len].set(first)

            def step(pos, buf, cache):
                tok = jax.lax.dynamic_slice(buf, (0, pos), (b, 1))
                logits, cache = forward_cached(params, tok, cache, pos)
                nxt = pick(logits, jax.random.fold_in(rng, pos + 1))
                buf = jax.lax.dynamic_update_slice(buf, nxt[:, None],
                                                   (0, pos + 1))
                return buf, cache, nxt

            if eos_token_id is None:
                def body(pos, carry):
                    buf, cache = carry
                    buf, cache, _ = step(pos, buf, cache)
                    return buf, cache

                buf, _ = jax.lax.fori_loop(prompt_len, total - 1, body,
                                           (buf, cache))
                return buf

            def cond(carry):
                _, _, pos, done = carry
                return (pos < total - 1) & ~jnp.all(done)

            def body(carry):
                buf, cache, pos, done = carry
                buf, cache, nxt = step(pos, buf, cache)
                return buf, cache, pos + 1, done | (nxt == eos_token_id)

            buf, _, _, _ = jax.lax.while_loop(
                cond, body, (buf, cache, jnp.int32(prompt_len),
                             first == eos_token_id))
            return buf

        return jax.jit(self.sentry.wrap(gen, self._gen_name(
            "kv", b, prompt_len, total, sample_cfg, eos_token_id)))

    def profile_model_time(self, use_cuda_events: bool = True):
        """Enable per-forward wall-clock capture (reference
        ``inference/engine.py:163``); retrieve with :meth:`model_times`.
        Idempotent — repeated calls do not stack timers.  Every sample
        also lands in the engine registry's ``inference_forward_seconds``
        histogram (``self.metrics``) — the bounded-memory distribution
        survives the :meth:`model_times` drain."""
        if getattr(self, "_profiling", False):
            return
        self._profiling = True
        self._model_times = []
        hist = self.metrics.histogram(
            "inference_forward_seconds",
            help="profiled forward-pass wall clock (profile_model_time)")
        orig = self._forward_fn

        def timed(p, batch):
            import time
            t0 = time.perf_counter()
            out = orig(p, batch)
            # fetch a value: block_until_ready no-ops on tunneled backends
            jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[0])
            dt = time.perf_counter() - t0
            self._model_times.append(dt)
            hist.observe(dt)
            return out

        self._forward_fn = timed

    def model_times(self):
        times = list(getattr(self, "_model_times", []))
        self._model_times = []
        return times


def _make_token_picker(sample_cfg):
    """Greedy argmax, or temperature/top-k/top-p sampling (in-graph).

    The reference's sampling lives in HF ``generate``; here it is part of the
    jitted decode loop.  logits: [B, V] -> int32 [B].
    """
    if sample_cfg is None:
        return lambda logits, rng: jnp.argmax(logits, axis=-1).astype(jnp.int32)
    _, temperature, top_k, top_p = sample_cfg

    def pick(logits, rng):
        logits = logits.astype(jnp.float32) / max(temperature, 1e-6)
        v = logits.shape[-1]
        if top_k and top_k < v:
            kth = jnp.sort(logits, axis=-1)[:, v - top_k][:, None]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        if top_p < 1.0:
            sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
            probs = jax.nn.softmax(sorted_logits, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            # keep the smallest prefix with cumulative prob >= top_p
            cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
            cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
            logits = jnp.where(logits < cutoff, -jnp.inf, logits)
        return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)

    return pick
