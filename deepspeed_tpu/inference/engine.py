"""Inference engine.

Analog of reference ``deepspeed/inference/engine.py:35`` (``InferenceEngine``).
Wraps a :class:`ModelSpec`, shards its params over a ``tp`` mesh axis (the
auto-TP analog: our models carry Megatron-style PartitionSpecs in ``tp_rules``,
so "injection" is a sharding annotation instead of a module swap), casts to the
inference dtype, and compiles the forward.  ``jit`` replaces CUDA-graph
capture/replay (reference :479/:498).

Round-1 decode is full-recompute greedy generation with fixed shapes (one jitted
``fori_loop`` over the token budget).  The KV-cache decode-attention Pallas path
(reference ``softmax_context`` kernels) lands in ``ops/decode_attention.py`` and
will replace the inner step.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import comm as dist
from ..parallel.topology import MeshTopology
from ..runtime.engine import _cast_floating
from ..runtime.model import ModelSpec
from ..utils.logging import log_dist
from .config import DeepSpeedInferenceConfig


class InferenceEngine:

    def __init__(self, model: ModelSpec, config: DeepSpeedInferenceConfig,
                 params=None):
        assert isinstance(model, ModelSpec), (
            "init_inference expects a deepspeed_tpu ModelSpec")
        assert model.apply_fn is not None, "ModelSpec.apply_fn required for inference"
        self.module = model
        self._config = config

        tp = config.tensor_parallel.tp_size if config.tensor_parallel.enabled else 1
        dist.init_distributed()
        n = len(jax.devices())
        assert n % max(tp, 1) == 0, f"tp_size {tp} does not divide {n} devices"
        self.topology = MeshTopology(tp=tp, dp=n // max(tp, 1))
        dist.configure(topology=self.topology)
        self.mesh = self.topology.mesh

        if params is None:
            params = model.init(jax.random.PRNGKey(0))
        params = _cast_floating(params, config.jnp_dtype)
        tp_specs = model.tp_rules(jax.eval_shape(lambda: params)) \
            if model.tp_rules else None
        rep = NamedSharding(self.mesh, P())
        if tp_specs is not None:
            shardings = jax.tree_util.tree_map(
                lambda spec: NamedSharding(self.mesh, spec), tp_specs,
                is_leaf=lambda x: isinstance(x, P))
        else:
            shardings = jax.tree_util.tree_map(lambda _: rep, params)
        self.params = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, s), params, shardings)

        self._forward_fn = jax.jit(
            lambda p, batch: model.apply_fn(p, batch, None))
        self._generate_fns: Dict[Any, Any] = {}
        log_dist(f"InferenceEngine: mesh={self.topology}, dtype={config.dtype}",
                 ranks=[0])

    # ------------------------------------------------------------------ forward
    def forward(self, batch):
        """Logits for a batch (reference ``inference/engine.py:541``)."""
        batch = self._put_batch(batch)
        return self._forward_fn(self.params, batch)

    __call__ = forward

    def _put_batch(self, batch):
        dp_total = self.topology.data_parallel_size

        def put(x):
            x = jnp.asarray(x)
            spec = P(("dp", "ep")) if (x.ndim > 0 and x.shape[0] % dp_total == 0) \
                else P()  # small batches replicate rather than fail
            return jax.device_put(x, NamedSharding(self.mesh, spec))

        return jax.tree_util.tree_map(put, batch)

    # ----------------------------------------------------------------- generate
    def generate(self, input_ids, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None):
        """Greedy decode with static shapes (reference ``_generate`` :571;
        beam search is likewise rejected there)."""
        input_ids = np.asarray(input_ids)
        b, prompt_len = input_ids.shape
        total = prompt_len + max_new_tokens
        hooks = self.module.decode_hooks
        max_ctx = (hooks or {}).get("max_seq_len")
        if max_ctx is not None and total > max_ctx:
            raise ValueError(
                f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
                f"= {total} exceeds the model context length {max_ctx}")
        key = (b, prompt_len, max_new_tokens)
        if key not in self._generate_fns:
            if self.module.decode_hooks is not None:
                self._generate_fns[key] = self._build_kv_cache_gen(
                    b, prompt_len, total)
            else:
                self._generate_fns[key] = self._build_recompute_gen(
                    b, prompt_len, total)
        out = self._generate_fns[key](self.params, jnp.asarray(input_ids))
        out = np.array(out)  # writable host copy (np.asarray view is read-only)
        if eos_token_id is not None:
            for row in range(b):
                hits = np.where(out[row, prompt_len:] == eos_token_id)[0]
                if hits.size:
                    out[row, prompt_len + hits[0] + 1:] = eos_token_id
        return out

    def _build_recompute_gen(self, b, prompt_len, total):
        """Full-recompute fallback for models without decode hooks."""
        apply_fn = self.module.apply_fn

        def gen(params, ids):
            buf = jnp.zeros((b, total), jnp.int32)
            buf = buf.at[:, :prompt_len].set(ids)

            def body(i, buf):
                logits = apply_fn(params, {"input_ids": buf}, None)
                next_tok = jnp.argmax(logits[:, i - 1, :], axis=-1)
                return buf.at[:, i].set(next_tok.astype(jnp.int32))

            return jax.lax.fori_loop(prompt_len, total, body, buf)

        return jax.jit(gen)

    def _build_kv_cache_gen(self, b, prompt_len, total):
        """Prefill + single-token decode loop over a static KV cache
        (reference ``softmax_context`` path; workspace sized like
        ``inference_context.h`` by the token budget)."""
        hooks = self.module.decode_hooks
        init_cache, forward_cached = hooks["init_cache"], hooks["forward_cached"]
        # round the workspace up so the Pallas kernel's block_k divides it
        cache_len = -(-total // 128) * 128
        cache_dtype = self._config.jnp_dtype

        def gen(params, ids):
            cache = init_cache(b, cache_len, cache_dtype)
            buf = jnp.zeros((b, total), jnp.int32)
            buf = buf.at[:, :prompt_len].set(ids)
            logits, cache = forward_cached(params, ids, cache, 0)   # prefill
            buf = buf.at[:, prompt_len].set(
                jnp.argmax(logits, axis=-1).astype(jnp.int32))

            def body(pos, carry):
                buf, cache = carry
                tok = jax.lax.dynamic_slice(buf, (0, pos), (b, 1))
                logits, cache2 = forward_cached(params, tok, cache, pos)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                buf = jax.lax.dynamic_update_slice(buf, nxt[:, None],
                                                   (0, pos + 1))
                return buf, cache2

            buf, _ = jax.lax.fori_loop(prompt_len, total - 1, body,
                                       (buf, cache))
            return buf

        return jax.jit(gen)

    def profile_model_time(self, use_cuda_events: bool = True):
        pass  # jax.profiler traces replace per-module CUDA-event hooks
