"""Device-side ops for the block-paged KV cache (vLLM PagedAttention
layout, JAX/TPU edition).

Layout contract (per layer slice of the stacked pool):

 - pool leaf: ``[NB, HKV, block_size, hd]`` — the batch dim of the
   contiguous layout becomes the physical-block dim and the length dim
   becomes the in-block offset, so the models' ``init_cache(num_blocks,
   block_size, dtype)`` hook builds a pool unchanged.
 - block table: ``int32 [B, NBPER]`` — each row maps a sequence's logical
   block index (``position // block_size``) to a physical block.  Entry 0
   is the reserved scratch block (``inference/paged.py``), which doubles as
   the "unset" marker: reads of unset blocks are masked by position, writes
   of invalid tokens are routed there explicitly.

Speculative-decoding windows lean on two properties of this contract:

 - **Scratch routing is the write-side safety net**: a T = K+1 verify
   window may reach positions past a row's allocated table entries (the
   tail of a draft that cannot fit the request's remaining budget) — those
   writes land in scratch block 0 and are never read back unmasked, so the
   verify program keeps one fixed shape for every row regardless of how
   much budget each row has left.
 - **Rollback is free**: rejected draft tokens leave stale KV at positions
   ``committed_len .. committed_len + K``.  Nothing is copied or zeroed —
   the scheduler just keeps its host-side length at the committed value;
   position-based causal masking hides the stale tail from every read, and
   the next committed write at a position deterministically overwrites it
   (``pos // block_size`` / ``pos % block_size`` addressing — same block,
   same offset).  Refcounts never move on rollback.

**Tensor parallelism** (Megatron-style ``tp`` mesh axis): the serving
engine commits the pool sharded over the KV-HEAD dim
(``NamedSharding(mesh, P(None, None, "tp"))`` on the stacked ``[L, NB,
HKV, bs, hd]`` buffer) and installs its mesh here via :func:`configure`.
Every paged op then runs inside ``shard_map`` — each chip scatters/
gathers/attends over only its own ``HKV/tp`` head shard of the pool, with
ZERO per-step KV collectives (the head dim is fully data-parallel across
chips; the one all-reduce of tensor-parallel attention happens after the
output projection, outside these ops, exactly like the matmul path).
Block ids, tables, and positions are head-invariant, so they replicate
into every shard unchanged.  Pools whose head count does not divide the
axis (GQA with HKV < tp) simply skip the wrapping — ``head_shards``
returns 1 and the op runs replicated, bit-identical to tp=1.

Everything here is pure XLA (scatter / gather), shared by prefill and the
CPU/correctness decode path; the TPU kernels that walk the block table
in-kernel live in ``ops/decode_attention.py``
(``paged_decode_attention_pallas`` / ``paged_verify_attention_pallas``)
and shard through the same context.
"""

from __future__ import annotations

import contextlib

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

# ------------------------------------------------------------- tp context
#: Mesh the paged ops shard over (``None`` = replicated pools, plain XLA
#: ops, the tp=1 behavior).  Installed by ``ServingEngine`` when it commits
#: a head-sharded pool; module-level — like ``ops/quantized_matmul
#: .configure`` — so the model families' ``forward_cached`` stay
#: mesh-agnostic.
_TP_MESH = None
_TP_AXIS = "tp"


def configure(mesh=None, axis: str = "tp") -> None:
    """Install (mesh + axis name) or clear (``None``) the tensor-parallel
    context for the paged device ops.  With a mesh installed, every paged
    op whose head dims divide the axis runs inside ``shard_map`` on its own
    KV-head shard."""
    global _TP_MESH, _TP_AXIS
    _TP_MESH = mesh
    _TP_AXIS = axis


@contextlib.contextmanager
def tp_context(mesh, axis: str = "tp"):
    """Scoped :func:`configure`: install the tp context for the duration of
    a block and restore whatever was there before.  The serving engine
    wraps every device invocation in this — tracing happens inside the
    call, so each engine's programs bake in ITS mesh (or none) even when
    engines of different tp degrees coexist in one process."""
    prev = (_TP_MESH, _TP_AXIS)
    configure(mesh, axis)
    try:
        yield
    finally:
        configure(*prev)


def tp_mesh():
    return _TP_MESH


def tp_axis() -> str:
    return _TP_AXIS


def head_shards(*head_counts: int) -> int:
    """Shard count the configured tp context puts on the given head dims:
    the mesh's tp-axis size when EVERY count divides it, else 1 — the
    replicated fallback for GQA pools with fewer KV heads than chips (head
    groups are shared) and for odd head counts."""
    if _TP_MESH is None:
        return 1
    n = int(dict(_TP_MESH.shape).get(_TP_AXIS, 1))
    if n <= 1:
        return 1
    return n if all(int(h) % n == 0 for h in head_counts) else 1


def head_shard_map(fn, in_specs, out_specs):
    """``shard_map`` over the configured mesh.  Callers place
    :func:`tp_axis` on HEAD dims only, so the body is embarrassingly
    parallel across chips — no collective ever appears inside
    (``check_rep=False``: outputs are sharded, not replicated)."""
    from jax.experimental.shard_map import shard_map

    return shard_map(fn, mesh=_TP_MESH, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


def blocks_for(num_tokens: int, block_size: int) -> int:
    """Blocks needed to cover ``num_tokens`` positions (ceil division) —
    the one accounting formula the allocator, scheduler, and speculative
    budget caps must all agree on."""
    return -(-int(num_tokens) // int(block_size))


def _paged_cache_update(ck, cv, k, v, pos, block_tables, valid=None):
    """Single-shard scatter body of :func:`paged_cache_update` — also the
    whole op when the pool is replicated (tp=1 / GQA fallback)."""
    b, hkv, t, hd = k.shape
    bs = ck.shape[2]
    nbper = block_tables.shape[1]
    pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(-1), (b,))
    p = pos[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]      # [B, T]
    ok = (jnp.arange(t, dtype=jnp.int32)[None, :] <
          jnp.asarray(valid, jnp.int32)[:, None]) if valid is not None \
        else jnp.ones((b, t), bool)
    li = p // bs
    ok = ok & (li >= 0) & (li < nbper)
    phys = jnp.take_along_axis(block_tables.astype(jnp.int32),
                               jnp.clip(li, 0, nbper - 1), axis=1)
    phys = jnp.where(ok, jnp.maximum(phys, 0), 0)                   # [B, T]
    off = jnp.where(ok, p % bs, 0)                                  # [B, T]
    # advanced indices at dims 0 and 2 around the ':' slice put the [B, T]
    # index shape in front: value layout is [B, T, HKV, hd].  Duplicate
    # targets only ever occur on the scratch block (any write order is fine
    # — scratch is never read unmasked).
    ck = ck.at[phys, :, off].set(k.transpose(0, 2, 1, 3).astype(ck.dtype))
    cv = cv.at[phys, :, off].set(v.transpose(0, 2, 1, 3).astype(cv.dtype))
    return ck, cv


def paged_cache_update(ck, cv, k, v, pos, block_tables, valid=None):
    """Scatter a window of new keys/values into the paged pool.

    ck/cv:         [NB, HKV, block_size, hd] pool (one layer)
    k/v:           [B, HKV, T, hd] — T new tokens per row
    pos:           int32 scalar or [B] — global position of ``k[:, :, 0]``
                   per row (T == 1 decode: each row's own position; T > 1
                   chunked prefill: each row's chunk base)
    block_tables:  int32 [B, NBPER]
    valid:         optional int32 [B] — tokens of the T-window that are
                   real (default all T).  Invalid tokens, and positions
                   past the table's reach, write to scratch block 0.

    Under a configured tp context (module docstring) the scatter runs in
    ``shard_map``: each chip writes its own head shard of the pool (the
    k/v window arrives already head-sharded from the column-parallel kv
    projections); positions/tables replicate.
    """
    b = k.shape[0]
    pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(-1), (b,))
    n = head_shards(ck.shape[1], k.shape[1])
    if n <= 1:
        return _paged_cache_update(ck, cv, k, v, pos, block_tables, valid)
    hs = P(None, _TP_AXIS)
    valid = jnp.full((b,), k.shape[2], jnp.int32) if valid is None \
        else jnp.asarray(valid, jnp.int32)
    return head_shard_map(
        _paged_cache_update, (hs, hs, hs, hs, P(), P(), P()), (hs, hs))(
            ck, cv, k, v, pos, jnp.asarray(block_tables, jnp.int32), valid)


def _paged_gather(pool_leaf, block_tables):
    """Single-shard gather body of :func:`paged_gather` — called directly
    by the in-``shard_map`` attention bodies (``ops/decode_attention.py``)
    so sharded callers never re-enter the wrapper."""
    nb, hkv, bs, hd = pool_leaf.shape
    b, nbper = block_tables.shape
    g = pool_leaf[jnp.maximum(block_tables, 0)]     # [B, NBPER, HKV, bs, hd]
    return g.transpose(0, 2, 1, 3, 4).reshape(b, hkv, nbper * bs, hd)


def paged_gather(pool_leaf, block_tables):
    """Materialize each row's logical cache view from the pool:
    ``[NB, HKV, bs, hd]`` through ``int32 [B, NBPER]`` tables ->
    ``[B, HKV, NBPER*bs, hd]``.  Unset (scratch) entries gather garbage
    that sits past every row's valid length — callers mask by position.
    Under a configured tp context each chip gathers only its own head
    shard (output sharded ``[B, HKV/tp, S, hd]`` per chip)."""
    n = head_shards(pool_leaf.shape[1])
    if n <= 1:
        return _paged_gather(pool_leaf, block_tables)
    hs = P(None, _TP_AXIS)
    return head_shard_map(_paged_gather, (hs, P()), hs)(
        pool_leaf, jnp.asarray(block_tables, jnp.int32))
