"""Device-side ops for the block-paged KV cache (vLLM PagedAttention
layout, JAX/TPU edition).

Layout contract (per layer slice of the stacked pool):

 - pool leaf: ``[NB, HKV, block_size, hd]`` — the batch dim of the
   contiguous layout becomes the physical-block dim and the length dim
   becomes the in-block offset, so the models' ``init_cache(num_blocks,
   block_size, dtype)`` hook builds a pool unchanged.
 - block table: ``int32 [B, NBPER]`` — each row maps a sequence's logical
   block index (``position // block_size``) to a physical block.  Entry 0
   is the reserved scratch block (``inference/paged.py``), which doubles as
   the "unset" marker: reads of unset blocks are masked by position, writes
   of invalid tokens are routed there explicitly.

Speculative-decoding windows lean on two properties of this contract:

 - **Scratch routing is the write-side safety net**: a T = K+1 verify
   window may reach positions past a row's allocated table entries (the
   tail of a draft that cannot fit the request's remaining budget) — those
   writes land in scratch block 0 and are never read back unmasked, so the
   verify program keeps one fixed shape for every row regardless of how
   much budget each row has left.
 - **Rollback is free**: rejected draft tokens leave stale KV at positions
   ``committed_len .. committed_len + K``.  Nothing is copied or zeroed —
   the scheduler just keeps its host-side length at the committed value;
   position-based causal masking hides the stale tail from every read, and
   the next committed write at a position deterministically overwrites it
   (``pos // block_size`` / ``pos % block_size`` addressing — same block,
   same offset).  Refcounts never move on rollback.

**Tensor parallelism** (Megatron-style ``tp`` mesh axis): the serving
engine commits the pool sharded over the KV-HEAD dim
(``NamedSharding(mesh, P(None, None, "tp"))`` on the stacked ``[L, NB,
HKV, bs, hd]`` buffer) and installs its mesh here via :func:`configure`.
Every paged op then runs inside ``shard_map`` — each chip scatters/
gathers/attends over only its own ``HKV/tp`` head shard of the pool, with
ZERO per-step KV collectives (the head dim is fully data-parallel across
chips; the one all-reduce of tensor-parallel attention happens after the
output projection, outside these ops, exactly like the matmul path).
Block ids, tables, and positions are head-invariant, so they replicate
into every shard unchanged.  Pools whose head count does not divide the
axis (GQA with HKV < tp) simply skip the wrapping — ``head_shards``
returns 1 and the op runs replicated, bit-identical to tp=1.

**Quantized pool records (int8 KV, PR 7)**: a pool leaf may be a dict
``{"qp": int8 [NB, HKV, bs, hd], "ps": bf16 [NB, HKV, bs]}`` instead of a
float array — int8 codes plus a per-block scale table whose rows live and
die with the blocks (one ``[HKV, bs]`` scale row per block per K/V per
layer; within the row each (head, slot) token vector carries its own
scale).  The granularity is chosen by two constraints:

 - *append-only writes*: a token is quantized once, at scatter time, from
   its own ``hd`` values (``ops/quantization.quantize_kv``).  A scalar
   per-block scale would force a read-modify-write requantization of the
   whole block whenever a later token raised the block absmax; per-token
   scales make the write side exactly the int8 payload + one scale.
 - *head-locality under tp*: scales sit under the pool's own head dim, so
   the sharded scatter computes them from the chip's local head shard —
   no cross-chip absmax, zero per-step collectives, and codes/scales are
   bit-identical to the replicated layout (the tp parity argument of
   PR 5 carries over unchanged).

Scatter quantizes on write, gather (and the paged Pallas kernels in
``ops/decode_attention.py``) dequantizes on read, so HBM only ever moves
int8 codes + scales.  Scale rows of freed blocks hold stale values by
design — reads are position-masked until the next owner rewrites them —
and the serving engine's host-side ledger + ``analysis/invariants.py``
``scale-lockstep`` audit enforce that no live read can reach one.
Rollback of rejected speculative tokens stays free: re-quantizing the
same deterministic values yields the same codes and scales.

Everything here is pure XLA (scatter / gather), shared by prefill and the
CPU/correctness decode path; the TPU kernels that walk the block table
in-kernel live in ``ops/decode_attention.py``
(``paged_decode_attention_pallas`` / ``paged_verify_attention_pallas``)
and shard through the same context.
"""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

# ------------------------------------------------------------- tp context
#: Mesh the paged ops shard over (``None`` = replicated pools, plain XLA
#: ops, the tp=1 behavior).  Installed by ``ServingEngine`` when it commits
#: a head-sharded pool; module-level — like ``ops/quantized_matmul
#: .configure`` — so the model families' ``forward_cached`` stay
#: mesh-agnostic.
_TP_MESH = None
_TP_AXIS = "tp"


def configure(mesh=None, axis: str = "tp") -> None:
    """Install (mesh + axis name) or clear (``None``) the tensor-parallel
    context for the paged device ops.  With a mesh installed, every paged
    op whose head dims divide the axis runs inside ``shard_map`` on its own
    KV-head shard."""
    global _TP_MESH, _TP_AXIS
    _TP_MESH = mesh
    _TP_AXIS = axis


@contextlib.contextmanager
def tp_context(mesh, axis: str = "tp"):
    """Scoped :func:`configure`: install the tp context for the duration of
    a block and restore whatever was there before.  The serving engine
    wraps every device invocation in this — tracing happens inside the
    call, so each engine's programs bake in ITS mesh (or none) even when
    engines of different tp degrees coexist in one process."""
    prev = (_TP_MESH, _TP_AXIS)
    configure(mesh, axis)
    try:
        yield
    finally:
        configure(*prev)


def tp_mesh():
    return _TP_MESH


def tp_axis() -> str:
    return _TP_AXIS


def head_shards(*head_counts: int) -> int:
    """Shard count the configured tp context puts on the given head dims:
    the mesh's tp-axis size when EVERY count divides it, else 1 — the
    replicated fallback for GQA pools with fewer KV heads than chips (head
    groups are shared) and for odd head counts."""
    if _TP_MESH is None:
        return 1
    n = int(dict(_TP_MESH.shape).get(_TP_AXIS, 1))
    if n <= 1:
        return 1
    return n if all(int(h) % n == 0 for h in head_counts) else 1


def head_shard_map(fn, in_specs, out_specs):
    """``shard_map`` over the configured mesh.  Callers place
    :func:`tp_axis` on HEAD dims only, so the body is embarrassingly
    parallel across chips — no collective ever appears inside
    (``check_rep=False``: outputs are sharded, not replicated)."""
    from jax.experimental.shard_map import shard_map

    return shard_map(fn, mesh=_TP_MESH, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


# ------------------------------------------------------------- dp context
#: Data-parallel grouping for ``engine_mode="dp_tp"`` serving
#: (``inference/serving.py``): the batch/slot dim AND the physical-block
#: dim additionally shard over the mesh ``dp`` axis — each dp shard owns a
#: contiguous span of rows and of pool blocks (group-scoped allocation,
#: ``inference/paged.GroupedBlockAllocator``), so every shard's gathers
#: and scatters are self-contained after localizing the global block ids
#: into its own chunk (subtract the group base, clamp to the local
#: scratch).  ``_DP_GROUPS == 1`` (the default) is the untouched tp-only
#: behavior.
_DP_AXIS = "dp"
_DP_MESH = None
_DP_GROUPS = 1
_DP_GSIZE = 0


def configure_dp(mesh=None, groups: int = 1, group_size: int = 0,
                 axis: str = "dp") -> None:
    """Install (mesh + group count + per-group block span) or clear
    (``mesh=None``) the data-parallel context for the paged device ops."""
    global _DP_MESH, _DP_GROUPS, _DP_GSIZE, _DP_AXIS
    _DP_MESH = mesh
    _DP_GROUPS = int(groups) if mesh is not None else 1
    _DP_GSIZE = int(group_size) if mesh is not None else 0
    _DP_AXIS = axis


@contextlib.contextmanager
def dp_context(mesh, groups: int, group_size: int, axis: str = "dp"):
    """Scoped :func:`configure_dp` — the serving engine wraps dp_tp-mode
    program invocations in this (nested inside :func:`tp_context`), so
    only programs traced for THAT engine bake in the dp sharding."""
    prev = (_DP_MESH, _DP_GROUPS, _DP_GSIZE, _DP_AXIS)
    configure_dp(mesh, groups, group_size, axis)
    try:
        yield
    finally:
        configure_dp(*prev)


def dp_groups() -> int:
    return _DP_GROUPS


def dp_axis() -> str:
    return _DP_AXIS


def dp_state():
    """(mesh, groups, group_size) of the installed dp context."""
    return _DP_MESH, _DP_GROUPS, _DP_GSIZE


def localize_block_tables(block_tables, group_size):
    """Map GLOBAL physical block ids into the calling dp shard's local
    chunk: subtract the shard's group base and clamp into
    ``[0, group_size)``.  Group-scoped allocation guarantees a row's real
    entries live in its own group's span, so the subtraction is exact for
    them; the global "unset" sentinel 0 (and any position past the span)
    clamps to local block 0 — the shard's own scratch — preserving the
    scratch-routing write contract shard-locally.  Must be called inside
    ``shard_map`` over the dp axis."""
    g = jax.lax.axis_index(_DP_AXIS)
    return jnp.clip(block_tables.astype(jnp.int32) - g * group_size,
                    0, group_size - 1)


def blocks_for(num_tokens: int, block_size: int) -> int:
    """Blocks needed to cover ``num_tokens`` positions (ceil division) —
    the one accounting formula the allocator, scheduler, and speculative
    budget caps must all agree on."""
    return -(-int(num_tokens) // int(block_size))


# -------------------------------------------------- quantized pool records
#: scale-table dtype (module docstring: range-safe, 2^-9 rounding below
#: the int8 error); exported so tests and stats agree on the layout
SCALE_DTYPE = jnp.bfloat16

_PQ_KEYS = frozenset({"qp", "ps"})


def is_quantized_pool(leaf) -> bool:
    """True for an int8 pool record ``{"qp": codes, "ps": scales}``
    (module docstring has the layout contract)."""
    return isinstance(leaf, dict) and set(leaf) == _PQ_KEYS


def pool_payload(leaf):
    """The code/payload array of a pool leaf: ``qp`` for quantized
    records, the leaf itself otherwise — shape/dtype probes go here."""
    return leaf["qp"] if is_quantized_pool(leaf) else leaf


def quantize_pool(pool, scale_dtype=None):
    """Convert a freshly built float pool (``init_cache`` output, any
    nesting) into int8 records: zero codes plus a zero scale table shaped
    ``payload.shape[:-1]`` (one scale per token vector, rows indexed by
    block — the per-block scale table).  Zero scales dequantize unwritten
    slots to exactly 0.0, matching the float pool's zero init."""
    scale_dtype = scale_dtype or SCALE_DTYPE

    def one(leaf):
        return {"qp": jnp.zeros(leaf.shape, jnp.int8),
                "ps": jnp.zeros(leaf.shape[:-1], scale_dtype)}

    return jax.tree_util.tree_map(one, pool)


def _scatter_one(pool, win, phys, off):
    """Scatter a [B, HKV, T, ...] window into one pool leaf at the [B, T]
    (physical block, in-block offset) targets — quantizing on write when
    the leaf is an int8 record.  Advanced indices at dims 0 and 2 around
    the ':' slice put the [B, T] index shape in front: value layout is
    [B, T, HKV, ...].  Duplicate targets only ever occur on the scratch
    block (any write order is fine — scratch is never read unmasked)."""
    if not is_quantized_pool(pool):
        return pool.at[phys, :, off].set(
            win.transpose(0, 2, 1, 3).astype(pool.dtype))
    from . import quantization as quant

    codes, scale = quant.quantize_kv(win, pool["ps"].dtype)
    return {"qp": pool["qp"].at[phys, :, off].set(
                codes.transpose(0, 2, 1, 3)),
            "ps": pool["ps"].at[phys, :, off].set(
                scale.transpose(0, 2, 1))}


def _paged_cache_update(ck, cv, k, v, pos, block_tables, valid=None):
    """Single-shard scatter body of :func:`paged_cache_update` — also the
    whole op when the pool is replicated (tp=1 / GQA fallback)."""
    b, hkv, t, hd = k.shape
    bs = pool_payload(ck).shape[2]
    nbper = block_tables.shape[1]
    pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(-1), (b,))
    p = pos[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]      # [B, T]
    ok = (jnp.arange(t, dtype=jnp.int32)[None, :] <
          jnp.asarray(valid, jnp.int32)[:, None]) if valid is not None \
        else jnp.ones((b, t), bool)
    li = p // bs
    ok = ok & (li >= 0) & (li < nbper)
    phys = jnp.take_along_axis(block_tables.astype(jnp.int32),
                               jnp.clip(li, 0, nbper - 1), axis=1)
    phys = jnp.where(ok, jnp.maximum(phys, 0), 0)                   # [B, T]
    off = jnp.where(ok, p % bs, 0)                                  # [B, T]
    ck = _scatter_one(ck, k, phys, off)
    cv = _scatter_one(cv, v, phys, off)
    return ck, cv


def paged_cache_update(ck, cv, k, v, pos, block_tables, valid=None):
    """Scatter a window of new keys/values into the paged pool.

    ck/cv:         [NB, HKV, block_size, hd] pool (one layer)
    k/v:           [B, HKV, T, hd] — T new tokens per row
    pos:           int32 scalar or [B] — global position of ``k[:, :, 0]``
                   per row (T == 1 decode: each row's own position; T > 1
                   chunked prefill: each row's chunk base)
    block_tables:  int32 [B, NBPER]
    valid:         optional int32 [B] — tokens of the T-window that are
                   real (default all T).  Invalid tokens, and positions
                   past the table's reach, write to scratch block 0.

    Under a configured tp context (module docstring) the scatter runs in
    ``shard_map``: each chip writes its own head shard of the pool (the
    k/v window arrives already head-sharded from the column-parallel kv
    projections); positions/tables replicate.
    """
    b = k.shape[0]
    pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(-1), (b,))
    n = head_shards(pool_payload(ck).shape[1], k.shape[1])
    if _DP_GROUPS > 1:
        # dp_tp serving: rows + physical blocks shard over dp (heads over
        # tp when divisible); each shard scatters into its own pool chunk
        # through localized tables — no cross-shard traffic
        from jax.experimental.shard_map import shard_map

        hp = P(_DP_AXIS, _TP_AXIS) if n > 1 else P(_DP_AXIS)
        dpsp = P(_DP_AXIS)
        gsize = _DP_GSIZE
        valid = jnp.full((b,), k.shape[2], jnp.int32) if valid is None \
            else jnp.asarray(valid, jnp.int32)

        def body(ck, cv, k, v, pos, bt, valid):
            bt = localize_block_tables(bt, gsize)
            return _paged_cache_update(ck, cv, k, v, pos, bt, valid)

        return shard_map(
            body, mesh=_DP_MESH,
            in_specs=(hp, hp, hp, hp, dpsp, dpsp, dpsp),
            out_specs=(hp, hp), check_rep=False)(
                ck, cv, k, v, pos,
                jnp.asarray(block_tables, jnp.int32), valid)
    if n <= 1:
        return _paged_cache_update(ck, cv, k, v, pos, block_tables, valid)
    # P(None, tp) is a valid spec for every record leaf too: qp
    # [NB, HKV, bs, hd] and ps [NB, HKV, bs] both carry the head dim at
    # index 1, and shard_map broadcasts a PartitionSpec leaf over the
    # record's pytree prefix
    hs = P(None, _TP_AXIS)
    valid = jnp.full((b,), k.shape[2], jnp.int32) if valid is None \
        else jnp.asarray(valid, jnp.int32)
    return head_shard_map(
        _paged_cache_update, (hs, hs, hs, hs, P(), P(), P()), (hs, hs))(
            ck, cv, k, v, pos, jnp.asarray(block_tables, jnp.int32), valid)


def _paged_gather(pool_leaf, block_tables, out_dtype=None):
    """Single-shard gather body of :func:`paged_gather` — called directly
    by the in-``shard_map`` attention bodies (``ops/decode_attention.py``)
    so sharded callers never re-enter the wrapper.  Quantized records
    gather codes + scales and dequantize (f32 expand, one cast to
    ``out_dtype`` — pass the query/compute dtype so bf16 models keep a
    bf16 residual stream, exactly like a float pool of that dtype); HBM
    moves int8 + scales, the expansion happens on-chip.  ``out_dtype``
    never touches a float pool (bit-identical reads)."""
    if is_quantized_pool(pool_leaf):
        from . import quantization as quant

        codes = _paged_gather(pool_leaf["qp"], block_tables)  # [B,HKV,S,hd]
        b, nbper = block_tables.shape
        hkv, bs = pool_leaf["ps"].shape[1], pool_leaf["ps"].shape[2]
        s = pool_leaf["ps"][jnp.maximum(block_tables, 0)]  # [B,NBPER,HKV,bs]
        s = s.transpose(0, 2, 1, 3).reshape(b, hkv, nbper * bs)
        return quant.dequantize_kv(codes, s, out_dtype or jnp.float32)
    nb, hkv, bs, hd = pool_leaf.shape
    b, nbper = block_tables.shape
    g = pool_leaf[jnp.maximum(block_tables, 0)]     # [B, NBPER, HKV, bs, hd]
    return g.transpose(0, 2, 1, 3, 4).reshape(b, hkv, nbper * bs, hd)


def _block_gather_one(leaf, ids):
    """Single-shard body of :func:`paged_block_gather` for one pool leaf:
    ``[L, NB, ...] -> [L, M, ...]`` along the physical-block dim."""
    return leaf[:, ids]


def paged_block_gather(pool, ids):
    """Gather whole physical blocks out of a (stacked, possibly multi-leaf)
    pool for host demotion: every leaf ``[L, NB, *rest]`` yields
    ``[L, M, *rest]`` at the ``int32 [M]`` block ids (``M`` is the engine's
    fixed ``swap_batch`` — pad with scratch block 0, whose gathered bytes
    the caller discards).  Quantized records travel whole: the ``qp`` codes
    AND their ``ps`` scale rows are ordinary leaves of the tree, so a
    demoted int8 block carries its scales with it.

    This is the device half of the tiered-KV demotion path
    (``inference/serving.py``): ONE fixed-shape compiled program per
    engine, one ``jax.device_get`` of its output per demotion batch.
    Under a configured tp context each chip gathers only its own head
    shard (dims: block at 1, head at 2 on every leaf — the pool layout
    contract), and the host-side ``device_get`` then assembles the full
    blocks from the addressable shards; ids replicate.
    """
    ids = jnp.asarray(ids, jnp.int32)
    leaves = jax.tree_util.tree_leaves(pool)
    n = head_shards(*[l.shape[2] for l in leaves])
    if n <= 1:
        return jax.tree_util.tree_map(
            lambda l: _block_gather_one(l, ids), pool)
    hs = P(None, None, _TP_AXIS)
    return head_shard_map(
        lambda p, i: jax.tree_util.tree_map(
            lambda l: _block_gather_one(l, i), p),
        (hs, P()), hs)(pool, ids)


def paged_block_scatter(pool, staged, ids):
    """Scatter host-promoted blocks back into the pool: every staged leaf
    ``[L, M, *rest]`` lands at the ``int32 [M]`` block ids of the matching
    pool leaf — the inverse of :func:`paged_block_gather`, so a
    demote → promote round trip is bit-identical (int8 codes and scale
    rows included).  Pad columns target scratch block 0 (duplicate
    scratch writes are fine — scratch is never read unmasked).

    Device half of tiered-KV promotion: the engine ``jax.device_put``\\ s
    the staged blocks ahead of admission (overlapping H2D with the decode
    step) and this ONE fixed-shape program commits them.  Under a
    configured tp context each chip scatters its own head shard (the
    staged array arrives already head-sharded from the engine's
    sharding-annotated ``device_put``); ids replicate.
    """
    ids = jnp.asarray(ids, jnp.int32)
    leaves = jax.tree_util.tree_leaves(pool)
    n = head_shards(*[l.shape[2] for l in leaves])

    def scatter(p, s, i):
        return jax.tree_util.tree_map(
            lambda pl, sl: pl.at[:, i].set(sl.astype(pl.dtype)), p, s)

    if n <= 1:
        return scatter(pool, staged, ids)
    hs = P(None, None, _TP_AXIS)
    return head_shard_map(scatter, (hs, hs, P()), hs)(pool, staged, ids)


def paged_gather(pool_leaf, block_tables, out_dtype=None):
    """Materialize each row's logical cache view from the pool:
    ``[NB, HKV, bs, hd]`` through ``int32 [B, NBPER]`` tables ->
    ``[B, HKV, NBPER*bs, hd]`` (int8 records dequantize — to ``out_dtype``
    when given, f32 otherwise; float pools ignore ``out_dtype``).  Unset
    (scratch) entries gather garbage that sits past every row's valid
    length — callers mask by position.  Under a configured tp context each
    chip gathers only its own head shard (output sharded
    ``[B, HKV/tp, S, hd]`` per chip)."""
    import functools

    n = head_shards(pool_payload(pool_leaf).shape[1])
    if n <= 1:
        return _paged_gather(pool_leaf, block_tables, out_dtype)
    hs = P(None, _TP_AXIS)
    return head_shard_map(
        functools.partial(_paged_gather, out_dtype=out_dtype),
        (hs, P()), hs)(pool_leaf, jnp.asarray(block_tables, jnp.int32))
