"""Device-side ops for the block-paged KV cache (vLLM PagedAttention
layout, JAX/TPU edition).

Layout contract (per layer slice of the stacked pool):

 - pool leaf: ``[NB, HKV, block_size, hd]`` — the batch dim of the
   contiguous layout becomes the physical-block dim and the length dim
   becomes the in-block offset, so the models' ``init_cache(num_blocks,
   block_size, dtype)`` hook builds a pool unchanged.
 - block table: ``int32 [B, NBPER]`` — each row maps a sequence's logical
   block index (``position // block_size``) to a physical block.  Entry 0
   is the reserved scratch block (``inference/paged.py``), which doubles as
   the "unset" marker: reads of unset blocks are masked by position, writes
   of invalid tokens are routed there explicitly.

Speculative-decoding windows lean on two properties of this contract:

 - **Scratch routing is the write-side safety net**: a T = K+1 verify
   window may reach positions past a row's allocated table entries (the
   tail of a draft that cannot fit the request's remaining budget) — those
   writes land in scratch block 0 and are never read back unmasked, so the
   verify program keeps one fixed shape for every row regardless of how
   much budget each row has left.
 - **Rollback is free**: rejected draft tokens leave stale KV at positions
   ``committed_len .. committed_len + K``.  Nothing is copied or zeroed —
   the scheduler just keeps its host-side length at the committed value;
   position-based causal masking hides the stale tail from every read, and
   the next committed write at a position deterministically overwrites it
   (``pos // block_size`` / ``pos % block_size`` addressing — same block,
   same offset).  Refcounts never move on rollback.

Everything here is pure XLA (scatter / gather), shared by prefill and the
CPU/correctness decode path; the TPU kernels that walk the block table
in-kernel live in ``ops/decode_attention.py``
(``paged_decode_attention_pallas`` / ``paged_verify_attention_pallas``).
"""

from __future__ import annotations

import jax.numpy as jnp


def blocks_for(num_tokens: int, block_size: int) -> int:
    """Blocks needed to cover ``num_tokens`` positions (ceil division) —
    the one accounting formula the allocator, scheduler, and speculative
    budget caps must all agree on."""
    return -(-int(num_tokens) // int(block_size))


def paged_cache_update(ck, cv, k, v, pos, block_tables, valid=None):
    """Scatter a window of new keys/values into the paged pool.

    ck/cv:         [NB, HKV, block_size, hd] pool (one layer)
    k/v:           [B, HKV, T, hd] — T new tokens per row
    pos:           int32 scalar or [B] — global position of ``k[:, :, 0]``
                   per row (T == 1 decode: each row's own position; T > 1
                   chunked prefill: each row's chunk base)
    block_tables:  int32 [B, NBPER]
    valid:         optional int32 [B] — tokens of the T-window that are
                   real (default all T).  Invalid tokens, and positions
                   past the table's reach, write to scratch block 0.
    """
    b, hkv, t, hd = k.shape
    bs = ck.shape[2]
    nbper = block_tables.shape[1]
    pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(-1), (b,))
    p = pos[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]      # [B, T]
    ok = (jnp.arange(t, dtype=jnp.int32)[None, :] <
          jnp.asarray(valid, jnp.int32)[:, None]) if valid is not None \
        else jnp.ones((b, t), bool)
    li = p // bs
    ok = ok & (li >= 0) & (li < nbper)
    phys = jnp.take_along_axis(block_tables.astype(jnp.int32),
                               jnp.clip(li, 0, nbper - 1), axis=1)
    phys = jnp.where(ok, jnp.maximum(phys, 0), 0)                   # [B, T]
    off = jnp.where(ok, p % bs, 0)                                  # [B, T]
    # advanced indices at dims 0 and 2 around the ':' slice put the [B, T]
    # index shape in front: value layout is [B, T, HKV, hd].  Duplicate
    # targets only ever occur on the scratch block (any write order is fine
    # — scratch is never read unmasked).
    ck = ck.at[phys, :, off].set(k.transpose(0, 2, 1, 3).astype(ck.dtype))
    cv = cv.at[phys, :, off].set(v.transpose(0, 2, 1, 3).astype(cv.dtype))
    return ck, cv


def paged_gather(pool_leaf, block_tables):
    """Materialize each row's logical cache view from the pool:
    ``[NB, HKV, bs, hd]`` through ``int32 [B, NBPER]`` tables ->
    ``[B, HKV, NBPER*bs, hd]``.  Unset (scratch) entries gather garbage
    that sits past every row's valid length — callers mask by position."""
    nb, hkv, bs, hd = pool_leaf.shape
    b, nbper = block_tables.shape
    g = pool_leaf[jnp.maximum(block_tables, 0)]     # [B, NBPER, HKV, bs, hd]
    return g.transpose(0, 2, 1, 3, 4).reshape(b, hkv, nbper * bs, hd)
