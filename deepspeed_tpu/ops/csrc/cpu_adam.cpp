// Host-side vectorized optimizers for ZeRO-Offload-style stepping of
// offloaded optimizer partitions.
//
// TPU-native equivalent of the reference's csrc/adam/cpu_adam.cpp /
// csrc/adagrad/cpu_adagrad.cpp (AVX512/AVX2 SIMD + OpenMP over host memory).
// Instead of hand-written intrinsics we use OpenMP `parallel for simd` and let
// the compiler emit the widest SIMD the host supports (-march=native at build
// time); the update math matches the reference semantics:
//   - adamw_mode=1: decoupled weight decay (param -= lr*wd*param)
//   - adamw_mode=0: classic L2 (grad += wd*param before the moments)
//   - bias_correction toggles the 1/(1-beta^t) terms
//
// C ABI so Python binds via ctypes (no pybind11 in this image).

#include <cmath>
#include <cstdint>

extern "C" {

void ds_adam_step(float* p, const float* g, float* m, float* v, int64_t n,
                  float lr, float beta1, float beta2, float eps, float wd,
                  int64_t step, int bias_correction, int adamw_mode) {
  float bc1 = 1.0f, bc2 = 1.0f;
  if (bias_correction) {
    bc1 = 1.0f - std::pow(beta1, (float)step);
    bc2 = 1.0f - std::pow(beta2, (float)step);
  }
  const float step_size = lr / bc1;
  const float bc2_sqrt = std::sqrt(bc2);
  const float decay = (adamw_mode && wd > 0.0f) ? (1.0f - lr * wd) : 1.0f;

#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float grad = g[i];
    if (!adamw_mode && wd > 0.0f) grad += wd * p[i];
    float mi = beta1 * m[i] + (1.0f - beta1) * grad;
    float vi = beta2 * v[i] + (1.0f - beta2) * grad * grad;
    m[i] = mi;
    v[i] = vi;
    float denom = std::sqrt(vi) / bc2_sqrt + eps;
    p[i] = decay * p[i] - step_size * (mi / denom);
  }
}

void ds_adagrad_step(float* p, const float* g, float* s, int64_t n, float lr,
                     float eps, float wd) {
#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float grad = g[i] + wd * p[i];
    float si = s[i] + grad * grad;
    s[i] = si;
    p[i] -= lr * grad / (std::sqrt(si) + eps);
  }
}

// Squared L2 norm of a buffer (for host-side global grad-norm clipping).
double ds_sq_norm(const float* x, int64_t n) {
  double acc = 0.0;
#pragma omp parallel for simd reduction(+ : acc) schedule(static)
  for (int64_t i = 0; i < n; ++i) acc += (double)x[i] * (double)x[i];
  return acc;
}

// In-place scale (applies the clip coefficient).
void ds_scale(float* x, int64_t n, float scale) {
#pragma omp parallel for simd schedule(static)
  for (int64_t i = 0; i < n; ++i) x[i] *= scale;
}

// 1 if every element is finite (host-side overflow check for fp16 paths).
int ds_all_finite(const float* x, int64_t n) {
  int ok = 1;
#pragma omp parallel for simd reduction(&& : ok) schedule(static)
  for (int64_t i = 0; i < n; ++i) ok = ok && std::isfinite(x[i]);
  return ok;
}

}  // extern "C"
