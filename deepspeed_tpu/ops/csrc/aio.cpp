// Async file I/O library for the NVMe offload tier (ZeRO-Infinity).
//
// TPU-native equivalent of the reference's csrc/aio/ (libaio-backed
// deepspeed_aio_thread.cpp / deepspeed_py_aio_handle.cpp): a worker-thread
// pool draining a submission queue of pread/pwrite requests against offload
// files, with a wait() barrier.  POSIX pread/pwrite per worker gives the same
// queue-depth parallelism libaio provides on the reference without requiring
// io_uring/libaio in the image; the Python-facing handle API (submit async
// read/write, wait for completions) mirrors the reference aio_handle.
//
// C ABI for ctypes binding.

#include <fcntl.h>
#include <unistd.h>

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Request {
  std::string path;
  void* buf;
  int64_t nbytes;
  int64_t offset;
  bool write;
};

struct Handle {
  std::vector<std::thread> workers;
  std::queue<Request> queue;
  std::mutex mu;
  std::condition_variable cv_submit;
  std::condition_variable cv_done;
  int64_t pending = 0;
  int64_t errors = 0;
  bool shutdown = false;

  explicit Handle(int num_threads) {
    for (int i = 0; i < num_threads; ++i) {
      workers.emplace_back([this] { this->run(); });
    }
  }

  ~Handle() {
    {
      std::lock_guard<std::mutex> lock(mu);
      shutdown = true;
    }
    cv_submit.notify_all();
    for (auto& t : workers) t.join();
  }

  void submit(Request req) {
    {
      std::lock_guard<std::mutex> lock(mu);
      queue.push(std::move(req));
      ++pending;
    }
    cv_submit.notify_one();
  }

  // Waits for all submitted ops; returns number of failed ops since last wait.
  int64_t wait() {
    std::unique_lock<std::mutex> lock(mu);
    cv_done.wait(lock, [this] { return pending == 0; });
    int64_t e = errors;
    errors = 0;
    return e;
  }

  void run() {
    for (;;) {
      Request req;
      {
        std::unique_lock<std::mutex> lock(mu);
        cv_submit.wait(lock, [this] { return shutdown || !queue.empty(); });
        if (queue.empty()) {
          if (shutdown) return;
          continue;
        }
        req = std::move(queue.front());
        queue.pop();
      }
      bool ok = execute(req);
      {
        std::lock_guard<std::mutex> lock(mu);
        if (!ok) ++errors;
        if (--pending == 0) cv_done.notify_all();
      }
    }
  }

  static bool execute(const Request& req) {
    int flags = req.write ? (O_WRONLY | O_CREAT) : O_RDONLY;
    int fd = ::open(req.path.c_str(), flags, 0644);
    if (fd < 0) return false;
    char* p = static_cast<char*>(req.buf);
    int64_t left = req.nbytes;
    int64_t off = req.offset;
    bool ok = true;
    while (left > 0) {
      ssize_t n = req.write ? ::pwrite(fd, p, left, off)
                            : ::pread(fd, p, left, off);
      if (n <= 0) {
        ok = false;
        break;
      }
      p += n;
      off += n;
      left -= n;
    }
    ::close(fd);
    return ok;
  }
};

}  // namespace

extern "C" {

void* ds_aio_handle_new(int num_threads) {
  if (num_threads < 1) num_threads = 1;
  return new Handle(num_threads);
}

void ds_aio_handle_free(void* h) { delete static_cast<Handle*>(h); }

void ds_aio_pread(void* h, const char* path, void* buf, int64_t nbytes,
                  int64_t offset) {
  static_cast<Handle*>(h)->submit({path, buf, nbytes, offset, false});
}

void ds_aio_pwrite(void* h, const char* path, const void* buf, int64_t nbytes,
                   int64_t offset) {
  static_cast<Handle*>(h)->submit(
      {path, const_cast<void*>(buf), nbytes, offset, true});
}

int64_t ds_aio_wait(void* h) { return static_cast<Handle*>(h)->wait(); }

}  // extern "C"
