// Async file I/O library for the NVMe offload tier (ZeRO-Infinity).
//
// TPU-native equivalent of the reference's csrc/aio/ (libaio-backed
// deepspeed_aio_thread.cpp / deepspeed_py_aio_handle.cpp).  Two engines
// behind one C ABI:
//
//  1. io_uring (preferred): raw-syscall ring (no liburing dependency) —
//     kernel-level async submission/completion like the reference's libaio,
//     with queue-depth parallelism and no per-op thread handoff.
//  2. worker-thread pool fallback (when io_uring_setup is unavailable —
//     seccomp'd containers, old kernels): POSIX pread/pwrite per worker.
//
// The Python-facing handle API (submit async read/write, wait for
// completions) mirrors the reference aio_handle; short transfers complete
// synchronously for the remainder so partial reads/writes never succeed
// silently.
//
// C ABI for ctypes binding.

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <vector>

#if defined(__linux__) && __has_include(<linux/io_uring.h>)
#define DS_HAVE_URING 1
#include <linux/io_uring.h>
#else
#define DS_HAVE_URING 0
#endif

namespace {

struct Request {
  std::string path;
  void* buf;
  int64_t nbytes;
  int64_t offset;
  bool write;
};

// ---------------------------------------------------------------- interface
struct Engine {
  virtual ~Engine() = default;
  virtual void submit(Request req) = 0;
  virtual int64_t wait() = 0;  // drain; returns #failures since last wait
};

// ------------------------------------------------------------- thread pool
struct ThreadEngine : Engine {
  std::vector<std::thread> workers;
  std::queue<Request> queue;
  std::mutex mu;
  std::condition_variable cv_submit;
  std::condition_variable cv_done;
  int64_t pending = 0;
  int64_t errors = 0;
  bool shutdown = false;

  explicit ThreadEngine(int num_threads) {
    for (int i = 0; i < num_threads; ++i) {
      workers.emplace_back([this] { this->run(); });
    }
  }

  ~ThreadEngine() override {
    {
      std::lock_guard<std::mutex> lock(mu);
      shutdown = true;
    }
    cv_submit.notify_all();
    for (auto& t : workers) t.join();
  }

  void submit(Request req) override {
    {
      std::lock_guard<std::mutex> lock(mu);
      queue.push(std::move(req));
      ++pending;
    }
    cv_submit.notify_one();
  }

  int64_t wait() override {
    std::unique_lock<std::mutex> lock(mu);
    cv_done.wait(lock, [this] { return pending == 0; });
    int64_t e = errors;
    errors = 0;
    return e;
  }

  void run() {
    for (;;) {
      Request req;
      {
        std::unique_lock<std::mutex> lock(mu);
        cv_submit.wait(lock, [this] { return shutdown || !queue.empty(); });
        if (queue.empty()) {
          if (shutdown) return;
          continue;
        }
        req = std::move(queue.front());
        queue.pop();
      }
      bool ok = execute(req);
      {
        std::lock_guard<std::mutex> lock(mu);
        if (!ok) ++errors;
        if (--pending == 0) cv_done.notify_all();
      }
    }
  }

  static bool execute(const Request& req) {
    int flags = req.write ? (O_WRONLY | O_CREAT) : O_RDONLY;
    int fd = ::open(req.path.c_str(), flags, 0644);
    if (fd < 0) return false;
    bool ok = transfer(fd, req.write, req.buf, req.nbytes, req.offset);
    ::close(fd);
    return ok;
  }

  // Synchronous pread/pwrite loop; also finishes io_uring short transfers.
  static bool transfer(int fd, bool write, void* buf, int64_t nbytes,
                       int64_t offset) {
    char* p = static_cast<char*>(buf);
    int64_t left = nbytes;
    int64_t off = offset;
    while (left > 0) {
      ssize_t n = write ? ::pwrite(fd, p, left, off)
                        : ::pread(fd, p, left, off);
      if (n <= 0) return false;
      p += n;
      off += n;
      left -= n;
    }
    return true;
  }
};

#if DS_HAVE_URING
// ---------------------------------------------------------------- io_uring
// Raw-syscall ring (the image ships linux/io_uring.h but not liburing).
// Opcode numbers are spelled out (stable kernel ABI) so this compiles
// against pre-5.6 headers whose enum lacks IORING_OP_READ/WRITE; whether
// the RUNNING kernel supports them is probed at init with a trial read,
// falling back to the thread engine otherwise.
constexpr uint8_t kOpRead = 22;   // IORING_OP_READ  (kernel >= 5.6)
constexpr uint8_t kOpWrite = 23;  // IORING_OP_WRITE (kernel >= 5.6)

struct UringEngine : Engine {
  static constexpr unsigned kEntries = 256;

  int ring_fd = -1;
  io_uring_params params{};
  // SQ ring
  void* sq_ptr = nullptr;
  size_t sq_len = 0;
  unsigned* sq_head = nullptr;
  unsigned* sq_tail = nullptr;
  unsigned* sq_mask = nullptr;
  unsigned* sq_array = nullptr;
  io_uring_sqe* sqes = nullptr;
  size_t sqes_len = 0;
  // CQ ring
  void* cq_ptr = nullptr;
  size_t cq_len = 0;
  unsigned* cq_head = nullptr;
  unsigned* cq_tail = nullptr;
  unsigned* cq_mask = nullptr;
  io_uring_cqe* cqes = nullptr;

  struct Inflight {
    int fd;
    Request req;
    bool used = false;
  };
  std::vector<Inflight> table;
  std::mutex mu;
  unsigned inflight = 0;
  unsigned unsubmitted = 0;  // queued SQEs the kernel has not consumed yet
  int64_t errors = 0;

  static UringEngine* create(int /*unused*/) {
    auto* e = new UringEngine();
    if (!e->init() || !e->probe_ops()) {
      delete e;
      return nullptr;
    }
    return e;
  }

  // io_uring_setup succeeding (5.1+) does not imply IORING_OP_READ support
  // (5.6+): trial-read /dev/zero and require success before committing.
  // enqueue_locked takes ownership of the fd (complete() closes it).
  bool probe_ops() {
    int fd = ::open("/dev/zero", O_RDONLY);
    if (fd < 0) return false;
    char byte = 0;
    std::lock_guard<std::mutex> lock(mu);
    if (!enqueue_locked(fd, Request{"", &byte, 1, 0, false})) {
      ::close(fd);
      return false;
    }
    while (inflight > 0) reap_locked(inflight);
    bool ok = errors == 0;
    errors = 0;
    return ok;
  }

  bool init() {
    std::memset(&params, 0, sizeof(params));
    ring_fd = static_cast<int>(
        ::syscall(__NR_io_uring_setup, kEntries, &params));
    if (ring_fd < 0) return false;

    sq_len = params.sq_off.array + params.sq_entries * sizeof(unsigned);
    cq_len = params.cq_off.cqes + params.cq_entries * sizeof(io_uring_cqe);
    if (params.features & IORING_FEAT_SINGLE_MMAP) {
      sq_len = cq_len = std::max(sq_len, cq_len);
    }
    sq_ptr = ::mmap(nullptr, sq_len, PROT_READ | PROT_WRITE,
                    MAP_SHARED | MAP_POPULATE, ring_fd, IORING_OFF_SQ_RING);
    if (sq_ptr == MAP_FAILED) return false;
    cq_ptr = (params.features & IORING_FEAT_SINGLE_MMAP)
                 ? sq_ptr
                 : ::mmap(nullptr, cq_len, PROT_READ | PROT_WRITE,
                          MAP_SHARED | MAP_POPULATE, ring_fd,
                          IORING_OFF_CQ_RING);
    if (cq_ptr == MAP_FAILED) return false;

    auto* sqb = static_cast<char*>(sq_ptr);
    sq_head = reinterpret_cast<unsigned*>(sqb + params.sq_off.head);
    sq_tail = reinterpret_cast<unsigned*>(sqb + params.sq_off.tail);
    sq_mask = reinterpret_cast<unsigned*>(sqb + params.sq_off.ring_mask);
    sq_array = reinterpret_cast<unsigned*>(sqb + params.sq_off.array);

    sqes_len = params.sq_entries * sizeof(io_uring_sqe);
    sqes = static_cast<io_uring_sqe*>(
        ::mmap(nullptr, sqes_len, PROT_READ | PROT_WRITE,
               MAP_SHARED | MAP_POPULATE, ring_fd, IORING_OFF_SQES));
    if (sqes == MAP_FAILED) return false;

    auto* cqb = static_cast<char*>(cq_ptr);
    cq_head = reinterpret_cast<unsigned*>(cqb + params.cq_off.head);
    cq_tail = reinterpret_cast<unsigned*>(cqb + params.cq_off.tail);
    cq_mask = reinterpret_cast<unsigned*>(cqb + params.cq_off.ring_mask);
    cqes = reinterpret_cast<io_uring_cqe*>(cqb + params.cq_off.cqes);

    table.resize(params.cq_entries);
    return true;
  }

  ~UringEngine() override {
    if (sqes && sqes != MAP_FAILED) ::munmap(sqes, sqes_len);
    if (cq_ptr && cq_ptr != sq_ptr && cq_ptr != MAP_FAILED)
      ::munmap(cq_ptr, cq_len);
    if (sq_ptr && sq_ptr != MAP_FAILED) ::munmap(sq_ptr, sq_len);
    if (ring_fd >= 0) ::close(ring_fd);
  }

  void submit(Request req) override {
    std::lock_guard<std::mutex> lock(mu);
    if (req.nbytes > INT32_MAX) {
      // SQE len is 32-bit; oversized requests complete synchronously
      if (!ThreadEngine::execute(req)) ++errors;
      return;
    }
    int flags = req.write ? (O_WRONLY | O_CREAT) : O_RDONLY;
    int fd = ::open(req.path.c_str(), flags, 0644);
    if (fd < 0) {
      ++errors;
      return;
    }
    Request fallback = req;  // enqueue_locked moves from req
    if (!enqueue_locked(fd, std::move(req))) {
      bool ok = ThreadEngine::transfer(fd, fallback.write, fallback.buf,
                                       fallback.nbytes, fallback.offset);
      if (!ok) ++errors;
      ::close(fd);
    }
  }

  // Queue one op on the ring; owns ``fd`` from here on (closed by
  // complete()).  Returns false only if no slot could be obtained.
  bool enqueue_locked(int fd, Request req) {
    // free table slot + SQ room (reap if the ring is saturated)
    int slot = -1;
    for (;;) {
      for (size_t i = 0; i < table.size(); ++i) {
        if (!table[i].used) {
          slot = static_cast<int>(i);
          break;
        }
      }
      unsigned head = __atomic_load_n(sq_head, __ATOMIC_ACQUIRE);
      unsigned t = __atomic_load_n(sq_tail, __ATOMIC_RELAXED);
      if (slot >= 0 && t - head < params.sq_entries) break;
      if (inflight == 0) return false;  // saturated with nothing to reap
      slot = -1;
      reap_locked(1);
    }
    table[slot].fd = fd;
    table[slot].req = std::move(req);
    table[slot].used = true;

    unsigned tail = __atomic_load_n(sq_tail, __ATOMIC_ACQUIRE);
    unsigned idx = tail & *sq_mask;
    io_uring_sqe* sqe = &sqes[idx];
    std::memset(sqe, 0, sizeof(*sqe));
    sqe->opcode = table[slot].req.write ? kOpWrite : kOpRead;
    sqe->fd = fd;
    sqe->addr = reinterpret_cast<uint64_t>(table[slot].req.buf);
    sqe->len = static_cast<unsigned>(table[slot].req.nbytes);
    sqe->off = static_cast<uint64_t>(table[slot].req.offset);
    sqe->user_data = static_cast<uint64_t>(slot);
    sq_array[idx] = idx;
    __atomic_store_n(sq_tail, tail + 1, __ATOMIC_RELEASE);
    ++inflight;
    ++unsubmitted;
    long n = ::syscall(__NR_io_uring_enter, ring_fd, unsubmitted, 0, 0,
                       nullptr, 0);
    if (n > 0) unsubmitted -= static_cast<unsigned>(n);
    // on transient enter failure the SQE stays queued; the next enter
    // (submit or reap) passes the updated unsubmitted count
    return true;
  }

  // Process one CQE; resubmission-free: finish short transfers with
  // synchronous pread/pwrite of the remainder (matches the thread engine
  // and keeps failure semantics identical).
  void complete(const io_uring_cqe& cqe) {
    auto slot = static_cast<size_t>(cqe.user_data);
    Inflight& fl = table[slot];
    const Request& r = fl.req;
    bool ok = cqe.res >= 0;
    int64_t done = ok ? cqe.res : 0;
    if (ok && done < r.nbytes) {
      ok = ThreadEngine::transfer(fl.fd, r.write,
                                  static_cast<char*>(r.buf) + done,
                                  r.nbytes - done, r.offset + done);
    }
    if (!ok) ++errors;
    ::close(fl.fd);
    fl.used = false;
    --inflight;
  }

  void reap_locked(unsigned min_complete) {
    if (inflight == 0) return;
    if (min_complete > inflight) min_complete = inflight;
    long n = ::syscall(__NR_io_uring_enter, ring_fd, unsubmitted, min_complete,
                       IORING_ENTER_GETEVENTS, nullptr, 0);
    if (n > 0) unsubmitted -= static_cast<unsigned>(n);
    unsigned head = __atomic_load_n(cq_head, __ATOMIC_ACQUIRE);
    unsigned tail = __atomic_load_n(cq_tail, __ATOMIC_ACQUIRE);
    while (head != tail) {
      complete(cqes[head & *cq_mask]);
      ++head;
    }
    __atomic_store_n(cq_head, head, __ATOMIC_RELEASE);
  }

  int64_t wait() override {
    std::lock_guard<std::mutex> lock(mu);
    while (inflight > 0) reap_locked(inflight);
    int64_t e = errors;
    errors = 0;
    return e;
  }
};
#endif  // DS_HAVE_URING

struct Handle {
  Engine* engine;
  bool uring;
};

}  // namespace

extern "C" {

void* ds_aio_handle_new(int num_threads) {
  if (num_threads < 1) num_threads = 1;
  auto* h = new Handle{nullptr, false};
#if DS_HAVE_URING
  if (auto* u = UringEngine::create(num_threads)) {
    h->engine = u;
    h->uring = true;
    return h;
  }
#endif
  h->engine = new ThreadEngine(num_threads);
  return h;
}

void ds_aio_handle_free(void* h) {
  auto* handle = static_cast<Handle*>(h);
  delete handle->engine;
  delete handle;
}

// 1 = io_uring, 0 = worker-thread fallback
int ds_aio_backend(void* h) { return static_cast<Handle*>(h)->uring ? 1 : 0; }

void ds_aio_pread(void* h, const char* path, void* buf, int64_t nbytes,
                  int64_t offset) {
  static_cast<Handle*>(h)->engine->submit({path, buf, nbytes, offset, false});
}

void ds_aio_pwrite(void* h, const char* path, const void* buf, int64_t nbytes,
                   int64_t offset) {
  static_cast<Handle*>(h)->engine->submit(
      {path, const_cast<void*>(buf), nbytes, offset, true});
}

int64_t ds_aio_wait(void* h) { return static_cast<Handle*>(h)->engine->wait(); }

}  // extern "C"
