"""Sparsity structures — block-level attention layouts.

Reference ``ops/sparse_attention/sparsity_config.py`` (classes at
:94/:243/:421/:559/:686): each config builds a **layout** — a
``[num_heads, S/block, S/block]`` 0/1 matrix saying which (q-block, k-block)
pairs are computed.  The structures (re-derived here from their published
semantics — Sparse Transformers' fixed pattern, BigBird's random+window+
global, Longformer's sliding-window+global) are framework-neutral numpy; the
execution is the Pallas kernel's block gating (``ops/flash_attention.py``
``layout`` argument) instead of Triton block-sparse matmuls.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class SparsityConfig:
    """Base: dense layout scaffold + utilities."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(
                f"sequence length {seq_len} must be a multiple of the "
                f"sparsity block {self.block}")
        n = seq_len // self.block
        return np.zeros((self.num_heads, n, n), np.int64)

    def check_and_propagate_first_head_layout(self, layout) -> np.ndarray:
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All blocks visible (reference :243 — for testing/parity)."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Sparse-Transformers fixed pattern (reference :94): local blocks within
    a stride window, plus "summary" global columns — the last
    ``num_global_blocks`` block-columns of each window attend/are attended
    across windows (unidirectional keeps only past windows)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 num_different_global_patterns: int = 1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        assert attention in ("unidirectional", "bidirectional")
        self.attention = attention
        if horizontal_global_attention:
            assert attention == "bidirectional", (
                "horizontal (row) global attention is only meaningful "
                "bidirectionally")
        self.horizontal_global_attention = horizontal_global_attention
        if num_different_global_patterns > 1:
            assert different_layout_per_head, (
                "different global patterns require different_layout_per_head")
        assert num_local_blocks % num_global_blocks == 0, (
            f"num_local_blocks {num_local_blocks} must be a multiple of "
            f"num_global_blocks {num_global_blocks}")
        assert num_different_global_patterns * num_global_blocks <= \
            num_local_blocks, (
                f"{num_different_global_patterns} global patterns x "
                f"{num_global_blocks} global blocks don't fit a window of "
                f"{num_local_blocks} local blocks")
        self.num_different_global_patterns = num_different_global_patterns

    def _set_local(self, layout, h, n):
        for start in range(0, n, self.num_local_blocks):
            end = min(start + self.num_local_blocks, n)
            for q in range(start, end):
                hi = (q + 1) if self.attention == "unidirectional" else end
                layout[h, q, start:hi] = 1
        return layout

    def _global_cols(self, h, window_start: int) -> List[int]:
        """Summary block-columns of one window for head ``h``: the window's
        tail ``num_global_blocks`` columns, shifted back per head when
        ``num_different_global_patterns > 1`` so heads summarize different
        positions."""
        pat = h % self.num_different_global_patterns
        first = window_start + self.num_local_blocks - \
            (pat + 1) * self.num_global_blocks
        return list(range(max(first, window_start),
                          max(first, window_start) + self.num_global_blocks))

    def _set_global(self, layout, h, n):
        for window_start in range(0, n, self.num_local_blocks):
            for c in self._global_cols(h, window_start):
                if c >= n:
                    continue
                if self.attention == "unidirectional":
                    # later queries attend back to past summary columns
                    layout[h, c + 1:, c] = 1
                else:
                    layout[h, :, c] = 1
                    if self.horizontal_global_attention:
                        layout[h, c, :] = 1
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        heads = range(self.num_heads) if self.different_layout_per_head \
            else range(1)
        for h in heads:
            self._set_local(layout, h, n)
            self._set_global(layout, h, n)
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Fixed's generalization (reference :421): custom local window sizes
    (``local_window_blocks``: first windows get listed sizes, last size
    repeats) and explicit global block indices."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 0,
                 local_window_blocks: Optional[List[int]] = None,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        if global_block_end_indices is not None:
            assert len(global_block_end_indices) == \
                len(self.global_block_indices)
        assert attention in ("unidirectional", "bidirectional")
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention

    def _set_local(self, layout, h, n):
        start = 0
        sizes = list(self.local_window_blocks)
        while start < n:
            size = sizes.pop(0) if sizes else self.local_window_blocks[-1]
            end = min(start + size, n)
            for q in range(start, end):
                hi = (q + 1) if self.attention == "unidirectional" else end
                layout[h, q, start:hi] = 1
            start = end
        return layout

    def _globals(self, n):
        cols = []
        if self.global_block_end_indices is None:
            cols = [c for c in self.global_block_indices if c < n]
        else:
            for s, e in zip(self.global_block_indices,
                            self.global_block_end_indices):
                cols.extend(range(s, min(e, n)))
        return cols

    def _set_global(self, layout, h, n):
        for c in self._globals(n):
            if self.attention == "unidirectional":
                layout[h, c:, c] = 1
            else:
                layout[h, :, c] = 1
                if self.horizontal_global_attention:
                    layout[h, c, :] = 1
        return layout

    def _set_random(self, layout, h, n, rng):
        for q in range(n):
            hi = (q + 1) if self.attention == "unidirectional" else n
            if hi <= 0:
                continue
            ks = rng.integers(0, hi, size=self.num_random_blocks)
            layout[h, q, ks] = 1
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        rng = np.random.default_rng(0)  # deterministic layouts
        heads = range(self.num_heads) if self.different_layout_per_head \
            else range(1)
        for h in heads:
            self._set_local(layout, h, n)
            self._set_global(layout, h, n)
            if self.num_random_blocks:
                self._set_random(layout, h, n, rng)
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird (reference :559): random + sliding-window + global blocks."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 1, num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1,
                 attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        assert attention in ("unidirectional", "bidirectional")
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        g = self.num_global_blocks
        rng = np.random.default_rng(0)
        heads = range(self.num_heads) if self.different_layout_per_head \
            else range(1)
        for h in heads:
            for q in range(n):
                lo, hi = max(0, q - w), min(n, q + w + 1)
                if self.attention == "unidirectional":
                    hi = q + 1
                layout[h, q, lo:hi] = 1                      # sliding window
            layout[h, :, :g] = 1                             # global columns
            if self.attention == "bidirectional":
                layout[h, :g, :] = 1                         # global rows
            for q in range(n):                               # random
                hi = (q + 1) if self.attention == "unidirectional" else n
                ks = rng.integers(0, max(hi, 1),
                                  size=self.num_random_blocks)
                layout[h, q, ks] = 1
        if self.attention == "unidirectional":
            tri = np.tril(np.ones((n, n), np.int64))
            layout *= tri[None]
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer (reference :686): sliding window + explicit
    global block indices."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        if self.global_block_end_indices is None:
            cols = [c for c in self.global_block_indices if c < n]
        else:
            cols = []
            for s, e in zip(self.global_block_indices,
                            self.global_block_end_indices):
                cols.extend(range(s, min(e, n)))
        for h in range(self.num_heads if self.different_layout_per_head
                       else 1):
            for q in range(n):
                layout[h, q, max(0, q - w):min(n, q + w + 1)] = 1
            for c in cols:
                layout[h, :, c] = 1
                layout[h, c, :] = 1
        if self.attention == "unidirectional":
            layout *= np.tril(np.ones((n, n), np.int64))[None]
        return self.check_and_propagate_first_head_layout(layout)


class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Pure sliding window (reference local attention variant)."""

    def __init__(self, num_heads: int, block: int = 16,
                 num_sliding_window_blocks: int = 3,
                 attention: str = "unidirectional"):
        super().__init__(num_heads, block, False)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for q in range(n):
            lo = max(0, q - w)
            hi = (q + 1) if self.attention == "unidirectional" \
                else min(n, q + w + 1)
            layout[0, q, lo:hi] = 1
        return self.check_and_propagate_first_head_layout(layout)
