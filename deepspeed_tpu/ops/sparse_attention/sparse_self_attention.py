"""Block-sparse self-attention execution.

Reference ``ops/sparse_attention/sparse_self_attention.py:11
SparseSelfAttention`` runs Triton block-sparse sddmm/softmax/dsd kernels
over a ``SparsityConfig`` layout; here the same layouts gate the Pallas
flash kernel's (q-block, k-block) grid (``ops/flash_attention.py``
``_sparse_attention_bh``): gated-off blocks are skipped in forward *and*
both backward kernels, so compute scales with layout density.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..flash_attention import _sparse_attention_bh, _use_interpret
from .sparsity_config import SparsityConfig


def sparse_attention(q, k, v, layout, block: int,
                     sm_scale: Optional[float] = None,
                     causal: bool = False,
                     interpret: Optional[bool] = None):
    """q: [B, H, S, D]; k/v: [B, Hkv, S, D] (GQA: Hkv | H); layout:
    [H, S/block, S/block] 0/1.

    Returns [B, H, S, D].  ``causal=True`` additionally lower-triangularizes
    inside diagonal blocks (configs built with ``attention="unidirectional"``
    already gate strictly-upper blocks off)."""
    b, h, s, d = q.shape
    hkv = k.shape[1]
    assert h % hkv == 0, f"GQA needs num_heads {h} % kv_heads {hkv} == 0"
    rep = h // hkv
    n = layout.shape[-1]
    assert s % block == 0 and s // block == n, (
        f"seq {s} != layout blocks {n} x block {block}")
    assert layout.shape[0] in (1, h)
    if interpret is None:
        interpret = _use_interpret()
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    layout = jnp.asarray(layout, jnp.float32)
    if layout.shape[0] == 1:
        layout = jnp.broadcast_to(layout, (h,) + layout.shape[1:])

    qf = q.reshape(b * h, s, d)
    kf = k.reshape(b * hkv, s, d)
    vf = v.reshape(b * hkv, s, d)
    o = _sparse_attention_bh(qf, kf, vf, layout, sm_scale, causal, block,
                             block, interpret, rep)
    return o.reshape(b, h, s, d)


class SparseSelfAttention:
    """Config-driven wrapper (reference ``sparse_self_attention.py:11``)."""

    def __init__(self, sparsity_config: SparsityConfig,
                 attn_mask_mode: str = "mul", max_seq_length: int = 2048):
        self.sparsity_config = sparsity_config
        self._layouts = {}

    def get_layout(self, seq_len: int):
        if seq_len not in self._layouts:
            self._layouts[seq_len] = self.sparsity_config.make_layout(seq_len)
        return self._layouts[seq_len]

    def __call__(self, q, k, v, rpe=None, key_padding_mask=None,
                 attn_mask=None):
        assert rpe is None and key_padding_mask is None and attn_mask is None, (
            "rpe/masks not supported yet (reference supports them via "
            "kernel arguments)")
        s = q.shape[2]
        layout = self.get_layout(s)
        causal = getattr(self.sparsity_config, "attention",
                         "bidirectional") == "unidirectional"
        return sparse_attention(q, k, v, layout,
                                self.sparsity_config.block, causal=causal)


def sparse_attention_reference(q, k, v, layout, block: int,
                               causal: bool = False):
    """Dense einsum reference honoring the block layout (for tests)."""
    b, h, s, d = q.shape
    if k.shape[1] != h:  # GQA: repeat KV heads for the dense math
        rep = h // k.shape[1]
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    mask = np.kron(np.asarray(layout, bool),
                   np.ones((block, block), bool))  # [H, S, S]
    if causal:
        mask = mask & np.tril(np.ones((s, s), bool))[None]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / math.sqrt(d)
    scores = jnp.where(jnp.asarray(mask)[None], scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
