"""Block-sparse attention (reference ``deepspeed/ops/sparse_attention``):
pluggable sparsity *structures* (Fixed, Variable, BigBird, BSLongformer,
LocalSlidingWindow) producing block-level layouts, executed by the Pallas
flash kernel's layout gating instead of Triton block-sparse matmuls."""

from .sparse_self_attention import SparseSelfAttention, sparse_attention
from .sparsity_config import (BigBirdSparsityConfig, BSLongformerSparsityConfig,
                              DenseSparsityConfig, FixedSparsityConfig,
                              LocalSlidingWindowSparsityConfig,
                              SparsityConfig, VariableSparsityConfig)

__all__ = ["SparsityConfig", "DenseSparsityConfig", "FixedSparsityConfig",
           "VariableSparsityConfig", "BigBirdSparsityConfig",
           "BSLongformerSparsityConfig", "LocalSlidingWindowSparsityConfig",
           "SparseSelfAttention", "sparse_attention"]
