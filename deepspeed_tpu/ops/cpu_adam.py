"""DeepSpeedCPUAdam — host-memory Adam for offloaded optimizer partitions.

Reference: ``deepspeed/ops/adam/cpu_adam.py`` wrapping
``csrc/adam/cpu_adam.cpp`` (AVX-vectorized host Adam used by ZeRO-Offload /
ZeRO-Infinity to step optimizer state living in host DRAM).  Here the state is
flat numpy fp32 buffers and the kernel is the OpenMP/SIMD C++ library built by
``op_builder.CPUAdamBuilder`` (ctypes), with a numpy fallback when no
toolchain is available.
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from .op_builder import CPUAdamBuilder

_F32P = ctypes.POINTER(ctypes.c_float)


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(_F32P)


class DeepSpeedCPUAdam:
    """Adam/AdamW over flat fp32 host buffers.

    ``params``/``m``/``v`` are caller-owned contiguous fp32 numpy arrays (the
    offloaded partition); ``step(grads)`` updates them in place.
    """

    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, bias_correction: bool = True,
                 adamw_mode: bool = True):
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.bias_correction = bias_correction
        self.adamw_mode = adamw_mode
        self.step_count = 0
        self._lib = CPUAdamBuilder.bind()

    @property
    def has_native(self) -> bool:
        return self._lib is not None

    def step(self, params: np.ndarray, grads: np.ndarray, m: np.ndarray,
             v: np.ndarray, lr: Optional[float] = None) -> None:
        assert params.dtype == np.float32 and params.flags.c_contiguous
        self.step_count += 1
        lr = self.lr if lr is None else lr
        b1, b2 = self.betas
        if self._lib is not None:
            grads = np.ascontiguousarray(grads, np.float32)
            self._lib.ds_adam_step(
                _ptr(params), _ptr(grads), _ptr(m), _ptr(v), params.size,
                lr, b1, b2, self.eps, self.weight_decay, self.step_count,
                int(self.bias_correction), int(self.adamw_mode))
            return
        # numpy fallback (same math as csrc/cpu_adam.cpp)
        g = grads.astype(np.float32, copy=False)
        if not self.adamw_mode and self.weight_decay > 0:
            g = g + self.weight_decay * params
        np.multiply(m, b1, out=m)
        m += (1 - b1) * g
        np.multiply(v, b2, out=v)
        v += (1 - b2) * g * g
        bc1 = 1 - b1 ** self.step_count if self.bias_correction else 1.0
        bc2 = 1 - b2 ** self.step_count if self.bias_correction else 1.0
        denom = np.sqrt(v) / np.sqrt(bc2) + self.eps
        if self.adamw_mode and self.weight_decay > 0:
            params *= 1 - lr * self.weight_decay
        params -= (lr / bc1) * (m / denom)


class DeepSpeedCPUAdagrad:
    """Adagrad counterpart (reference csrc/adagrad/cpu_adagrad.cpp)."""

    def __init__(self, lr: float = 1e-2, eps: float = 1e-10,
                 weight_decay: float = 0.0):
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self._lib = CPUAdamBuilder.bind()

    def step(self, params: np.ndarray, grads: np.ndarray, state: np.ndarray,
             lr: Optional[float] = None) -> None:
        lr = self.lr if lr is None else lr
        if self._lib is not None:
            grads = np.ascontiguousarray(grads, np.float32)
            self._lib.ds_adagrad_step(_ptr(params), _ptr(grads), _ptr(state),
                                      params.size, lr, self.eps,
                                      self.weight_decay)
            return
        g = grads.astype(np.float32, copy=False) + self.weight_decay * params
        state += g * g
        params -= lr * g / (np.sqrt(state) + self.eps)


def sq_norm(x: np.ndarray) -> float:
    lib = CPUAdamBuilder.bind()
    if lib is not None and x.dtype == np.float32 and x.flags.c_contiguous:
        return float(lib.ds_sq_norm(_ptr(x), x.size))
    return float(np.vdot(x.astype(np.float64), x.astype(np.float64)))


def scale_(x: np.ndarray, scale: float) -> None:
    lib = CPUAdamBuilder.bind()
    if lib is not None and x.dtype == np.float32 and x.flags.c_contiguous:
        lib.ds_scale(_ptr(x), x.size, scale)
    else:
        x *= scale


def all_finite(x: np.ndarray) -> bool:
    lib = CPUAdamBuilder.bind()
    if lib is not None and x.dtype == np.float32 and x.flags.c_contiguous:
        return bool(lib.ds_all_finite(_ptr(x), x.size))
    return bool(np.isfinite(x).all())
