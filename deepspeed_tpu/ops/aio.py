"""Async file I/O for the NVMe offload tier.

Reference: ``csrc/aio/py_lib/deepspeed_py_aio_handle.cpp`` (libaio-backed
``aio_handle`` with async_pread/async_pwrite/wait used by
``runtime/swap_tensor/*``).  Here the backend is the worker-thread C++ library
from ``op_builder.AsyncIOBuilder``; a synchronous numpy fallback keeps the API
working without a toolchain.

Submissions return an **op ticket** and :meth:`AsyncIOHandle.wait_statuses`
reports per-ticket success — the serving NVMe tier
(``inference/paged.py NvmeBlockStore``) needs to know *which* block read
failed so exactly that chain entry is dropped and recomputed, instead of a
batch-level failure count poisoning (or worse: silently passing) every
staged buffer in the batch.  :meth:`AsyncIOHandle.wait` keeps the original
aggregate-count contract.  :func:`swap_chain_write` / :func:`swap_chain_read`
are the batched chain helpers: submit every block of a chain, wait ONCE,
return per-block status aligned to the input order.
"""

from __future__ import annotations

import ctypes
import os
from typing import Dict, List, Sequence

import numpy as np

from .op_builder import AsyncIOBuilder

__all__ = ["AsyncIOHandle", "swap_chain_write", "swap_chain_read"]


class AsyncIOHandle:
    """Submit async reads/writes of numpy buffers against files; wait() joins.

    Mirrors the reference aio_handle: ops are queued to worker threads at
    submit time; ``wait()`` blocks until all submitted ops complete and
    returns the number of failures since the last wait.  Every submission
    returns a monotonically increasing op ticket; ``wait_statuses()`` is
    the per-op variant of ``wait()`` — ``{ticket: ok}`` for the batch.
    """

    def __init__(self, num_threads: int = 8):
        self._lib = AsyncIOBuilder.bind()
        self._handle = None
        self._inflight = []   # keep buffer refs alive until wait()
        self._ops: List[int] = []      # tickets submitted since last wait
        self._failed: set = set()      # python-fallback per-op failures
        self._next_op = 0
        if self._lib is not None:
            self._handle = self._lib.ds_aio_handle_new(num_threads)

    @property
    def has_native(self) -> bool:
        return self._handle is not None

    @property
    def backend(self) -> str:
        """"io_uring" (kernel-async ring, the libaio analog), "threads"
        (worker-pool fallback), or "python" (no toolchain)."""
        if self._handle is None:
            return "python"
        return "io_uring" if self._lib.ds_aio_backend(self._handle) else \
            "threads"

    def _ticket(self) -> int:
        t = self._next_op
        self._next_op += 1
        self._ops.append(t)
        return t

    def async_pread(self, buf: np.ndarray, path: str, offset: int = 0) -> int:
        assert buf.flags.c_contiguous
        t = self._ticket()
        if self._handle is not None:
            self._inflight.append(buf)
            self._lib.ds_aio_pread(self._handle, path.encode(),
                                   buf.ctypes.data_as(ctypes.c_void_p),
                                   buf.nbytes, offset)
            return t
        try:
            with open(path, "rb") as f:
                f.seek(offset)
                data = f.read(buf.nbytes)
            flat = buf.reshape(-1).view(np.uint8)
            flat[:len(data)] = np.frombuffer(data, np.uint8)
            if len(data) < buf.nbytes:
                # short read = failure, matching the native path's semantics
                # (a truncated swap file must not be silently consumed)
                self._failed.add(t)
        except OSError:
            self._failed.add(t)
        return t

    def async_pwrite(self, buf: np.ndarray, path: str, offset: int = 0) -> int:
        assert buf.flags.c_contiguous
        t = self._ticket()
        if self._handle is not None:
            self._inflight.append(buf)
            self._lib.ds_aio_pwrite(self._handle, path.encode(),
                                    buf.ctypes.data_as(ctypes.c_void_p),
                                    buf.nbytes, offset)
            return t
        try:
            mode = "r+b" if os.path.exists(path) else "wb"
            with open(path, mode) as f:
                f.seek(offset)
                f.write(buf.tobytes())
        except OSError:
            self._failed.add(t)
        return t

    def wait(self) -> int:
        """Block until all submitted ops finish; returns failure count."""
        if self._handle is not None:
            self._ops.clear()
            n = int(self._lib.ds_aio_wait(self._handle))
            self._inflight.clear()
            return n
        self._ops.clear()
        n = len(self._failed)
        self._failed.clear()
        return n

    def wait_statuses(self) -> Dict[int, bool]:
        """Block until all submitted ops finish; returns ``{ticket: ok}``
        for every op submitted since the last wait.

        The python fallback attributes failures exactly.  The native
        library only reports an aggregate count, so any nonzero count
        marks the WHOLE batch failed — a conservative overestimate that
        sends every suspect block through the caller's recompute fallback
        rather than trusting bytes whose op may be the one that failed.
        """
        ops, self._ops = self._ops, []
        if self._handle is not None:
            n = int(self._lib.ds_aio_wait(self._handle))
            self._inflight.clear()
            ok = n == 0
            return {t: ok for t in ops}
        failed, self._failed = self._failed, set()
        return {t: t not in failed for t in ops}

    def close(self) -> None:
        if self._handle is not None:
            self.wait()
            self._lib.ds_aio_handle_free(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def swap_chain_write(handle: AsyncIOHandle, path: str,
                     bufs: Sequence[np.ndarray],
                     offsets: Sequence[int]) -> List[bool]:
    """Write a chain of block buffers at the given file offsets; one
    submit per block, ONE wait for the batch; per-block status aligned
    to the input order (the sanctioned swap commit point for NVMe spill,
    ``inference/paged.py``)."""
    tickets = [handle.async_pwrite(np.ascontiguousarray(b), path, int(off))
               for b, off in zip(bufs, offsets)]
    statuses = handle.wait_statuses()
    return [statuses.get(t, False) for t in tickets]


def swap_chain_read(handle: AsyncIOHandle, path: str,
                    bufs: Sequence[np.ndarray],
                    offsets: Sequence[int]) -> List[bool]:
    """Read a chain of block buffers from the given file offsets into
    preallocated ``bufs``; one wait for the batch; per-block status
    aligned to the input order.  A ``False`` entry means that buffer's
    bytes must NOT be trusted (short read / OS error) — the NVMe load
    path drops that entry and falls back to recompute."""
    tickets = [handle.async_pread(b, path, int(off))
               for b, off in zip(bufs, offsets)]
    statuses = handle.wait_statuses()
    return [statuses.get(t, False) for t in tickets]
