"""Async file I/O for the NVMe offload tier.

Reference: ``csrc/aio/py_lib/deepspeed_py_aio_handle.cpp`` (libaio-backed
``aio_handle`` with async_pread/async_pwrite/wait used by
``runtime/swap_tensor/*``).  Here the backend is the worker-thread C++ library
from ``op_builder.AsyncIOBuilder``; a synchronous numpy fallback keeps the API
working without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from .op_builder import AsyncIOBuilder


class AsyncIOHandle:
    """Submit async reads/writes of numpy buffers against files; wait() joins.

    Mirrors the reference aio_handle: ops are queued to worker threads at
    submit time; ``wait()`` blocks until all submitted ops complete and
    returns the number of failures since the last wait.
    """

    def __init__(self, num_threads: int = 8):
        self._lib = AsyncIOBuilder.bind()
        self._handle = None
        self._inflight = []   # keep buffer refs alive until wait()
        self._sync_failures = 0
        if self._lib is not None:
            self._handle = self._lib.ds_aio_handle_new(num_threads)

    @property
    def has_native(self) -> bool:
        return self._handle is not None

    @property
    def backend(self) -> str:
        """"io_uring" (kernel-async ring, the libaio analog), "threads"
        (worker-pool fallback), or "python" (no toolchain)."""
        if self._handle is None:
            return "python"
        return "io_uring" if self._lib.ds_aio_backend(self._handle) else \
            "threads"

    def async_pread(self, buf: np.ndarray, path: str, offset: int = 0) -> None:
        assert buf.flags.c_contiguous
        if self._handle is not None:
            self._inflight.append(buf)
            self._lib.ds_aio_pread(self._handle, path.encode(),
                                   buf.ctypes.data_as(ctypes.c_void_p),
                                   buf.nbytes, offset)
            return
        try:
            with open(path, "rb") as f:
                f.seek(offset)
                data = f.read(buf.nbytes)
            flat = buf.reshape(-1).view(np.uint8)
            flat[:len(data)] = np.frombuffer(data, np.uint8)
            if len(data) < buf.nbytes:
                # short read = failure, matching the native path's semantics
                # (a truncated swap file must not be silently consumed)
                self._sync_failures += 1
        except OSError:
            self._sync_failures += 1

    def async_pwrite(self, buf: np.ndarray, path: str, offset: int = 0) -> None:
        assert buf.flags.c_contiguous
        if self._handle is not None:
            self._inflight.append(buf)
            self._lib.ds_aio_pwrite(self._handle, path.encode(),
                                    buf.ctypes.data_as(ctypes.c_void_p),
                                    buf.nbytes, offset)
            return
        try:
            mode = "r+b" if os.path.exists(path) else "wb"
            with open(path, mode) as f:
                f.seek(offset)
                f.write(buf.tobytes())
        except OSError:
            self._sync_failures += 1

    def wait(self) -> int:
        """Block until all submitted ops finish; returns failure count."""
        if self._handle is not None:
            n = int(self._lib.ds_aio_wait(self._handle))
            self._inflight.clear()
            return n
        n = self._sync_failures
        self._sync_failures = 0
        return n

    def close(self) -> None:
        if self._handle is not None:
            self.wait()
            self._lib.ds_aio_handle_free(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
