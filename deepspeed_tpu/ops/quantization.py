"""Group-wise INT8 weight quantization (storage-level, not fake-quant).

Reference parity: ``csrc/quantization/{quantize.cu,dequantize.cu,
pt_binding.cpp}`` (symmetric/asymmetric group (de)quantization) and the
INT8 weight path of DS-Inference (``module_inject/replace_module.py:152
GroupQuantizer``) / ZeRO-Inference weight quantization
(``docs/_posts/2022-09-10-zero-inference.md``).

TPU design: weights are stored in HBM as int8 plus per-group f32 scales
(groups along the LAST axis); dequantization happens inside the jitted
forward right at the point of use, where XLA fuses the
``(q - zero) * scale`` expansion into the consumer — there is no separate
kernel to launch, so the "kernel" here is the storage format + the fused
expansion.  The training-time fake-quant STE lives in
:mod:`deepspeed_tpu.compression.ops`.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

PyTree = Any

_QKEYS = frozenset({"q", "scale", "zero"})


def is_quantized(leaf) -> bool:
    """True for a quantized-weight record produced by :func:`quantize`."""
    return isinstance(leaf, dict) and _QKEYS.issuperset(leaf) and "q" in leaf


def quantize(w, num_bits: int = 8, group_size: int = 64,
             symmetric: bool = True) -> dict:
    """w: float array, last dim divisible by ``group_size`` ->
    ``{"q": int8 (w.shape), "scale": f32 [..., G], ["zero": f32 [..., G]]}``.
    """
    assert num_bits == 8, "int8 storage only (num_bits=8)"
    shape = w.shape
    assert shape[-1] % group_size == 0, (shape, group_size)
    g = w.astype(jnp.float32).reshape(shape[:-1] + (-1, group_size))
    if symmetric:
        amax = jnp.max(jnp.abs(g), axis=-1)
        scale = jnp.where(amax == 0, 1.0, amax / 127.0)
        q = jnp.clip(jnp.round(g / scale[..., None]), -127, 127)
        return {"q": q.astype(jnp.int8).reshape(shape), "scale": scale}
    lo = jnp.min(g, axis=-1)
    hi = jnp.max(g, axis=-1)
    scale = jnp.where(hi == lo, 1.0, (hi - lo) / 255.0)
    zero = lo
    q = jnp.clip(jnp.round((g - zero[..., None]) / scale[..., None]),
                 0, 255) - 128
    return {"q": q.astype(jnp.int8).reshape(shape), "scale": scale,
            "zero": zero}


def dequantize(rec: dict, dtype=jnp.bfloat16):
    """Group size is implicit: q.shape[-1] // scale.shape[-1] (keeps the
    record free of non-array leaves so device_put/tree_map stay trivial)."""
    q = rec["q"]
    gs = q.shape[-1] // rec["scale"].shape[-1]
    shape = q.shape
    g = q.astype(jnp.float32).reshape(shape[:-1] + (-1, gs))
    if "zero" in rec:
        w = (g + 128.0) * rec["scale"][..., None] + rec["zero"][..., None]
    else:
        w = g * rec["scale"][..., None]
    return w.reshape(shape).astype(dtype)


def quantize_pytree(params: PyTree, num_bits: int = 8, group_size: int = 64,
                    symmetric: bool = True, min_size: int = 4096,
                    min_penultimate: int = 64) -> PyTree:
    """Quantize WEIGHT-MATRIX-like float leaves; others pass through.

    A leaf qualifies when it has >= ``min_size`` elements, >= 2 dims, a
    last dim divisible by ``group_size``, and ``shape[-2] >=
    min_penultimate``.  The penultimate-dim test is what separates real
    matrices ([.., d_in, d_out], embeddings [V, d]) from per-layer-STACKED
    norm scales and biases ([L, d] with small L) — quantizing those would
    inject multiplicative error into every normalization while saving
    almost nothing (the weight-only posture of the reference INT8 path).
    Because a deep stack ([L, d] with L >= min_penultimate, e.g. 80-layer
    Llama) defeats the shape test alone, any leaf whose key path names a
    norm/bias/scale parameter is excluded outright."""
    def is_norm_path(path) -> bool:
        flat = "/".join(str(getattr(k, "key", getattr(k, "name", k)))
                        for k in path).lower()
        return any(t in flat for t in
                   ("ln", "norm", "bias", "scale", "gamma", "beta"))

    def one(path, x):
        if (hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
                and getattr(x, "ndim", 0) >= 2 and x.size >= min_size
                and x.shape[-1] % group_size == 0
                and x.shape[-2] >= min_penultimate
                and not is_norm_path(path)):
            return quantize(x, num_bits, group_size, symmetric)
        return x

    return jax.tree_util.tree_map_with_path(one, params)


def dequantize_pytree(params: PyTree, dtype=jnp.bfloat16) -> PyTree:
    """Inverse of :func:`quantize_pytree` (called INSIDE jit so XLA fuses
    the expansion into consumers)."""
    return jax.tree_util.tree_map(
        lambda x: dequantize(x, dtype) if is_quantized(x) else x,
        params, is_leaf=is_quantized)


def quantized_nbytes(params: PyTree) -> int:
    """Storage accounting (int8 + scales), for memory reports."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(
            params, is_leaf=is_quantized):
        if is_quantized(leaf):
            total += leaf["q"].size + leaf["scale"].size * 4
            if "zero" in leaf:
                total += leaf["zero"].size * 4
        elif hasattr(leaf, "nbytes"):
            total += leaf.nbytes
    return total
