"""Group-wise INT8 weight quantization (storage-level, not fake-quant).

Reference parity: ``csrc/quantization/{quantize.cu,dequantize.cu,
pt_binding.cpp}`` (symmetric/asymmetric group (de)quantization) and the
INT8 weight path of DS-Inference (``module_inject/replace_module.py:152
GroupQuantizer``) / ZeRO-Inference weight quantization
(``docs/_posts/2022-09-10-zero-inference.md``).

TPU design: weights are stored in HBM as int8 plus per-group f32 scales
(groups along the LAST axis); dequantization happens inside the jitted
forward right at the point of use, where XLA fuses the
``(q - zero) * scale`` expansion into the consumer — there is no separate
kernel to launch, so the "kernel" here is the storage format + the fused
expansion.  The training-time fake-quant STE lives in
:mod:`deepspeed_tpu.compression.ops`.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

PyTree = Any

_QKEYS = frozenset({"q", "scale", "zero"})


def is_quantized(leaf) -> bool:
    """True for a quantized-weight record produced by :func:`quantize`."""
    return isinstance(leaf, dict) and _QKEYS.issuperset(leaf) and "q" in leaf


def quantize(w, num_bits: int = 8, group_size: int = 64,
             symmetric: bool = True) -> dict:
    """w: float array, last dim divisible by ``group_size`` ->
    ``{"q": int8 (w.shape), "scale": f32 [..., G], ["zero": f32 [..., G]]}``.
    """
    assert num_bits == 8, "int8 storage only (num_bits=8)"
    shape = w.shape
    assert shape[-1] % group_size == 0, (shape, group_size)
    g = w.astype(jnp.float32).reshape(shape[:-1] + (-1, group_size))
    if symmetric:
        amax = jnp.max(jnp.abs(g), axis=-1)
        scale = jnp.where(amax == 0, 1.0, amax / 127.0)
        q = jnp.clip(jnp.round(g / scale[..., None]), -127, 127)
        return {"q": q.astype(jnp.int8).reshape(shape), "scale": scale}
    lo = jnp.min(g, axis=-1)
    hi = jnp.max(g, axis=-1)
    scale = jnp.where(hi == lo, 1.0, (hi - lo) / 255.0)
    zero = lo
    q = jnp.clip(jnp.round((g - zero[..., None]) / scale[..., None]),
                 0, 255) - 128
    return {"q": q.astype(jnp.int8).reshape(shape), "scale": scale,
            "zero": zero}


def dequantize(rec: dict, dtype=jnp.bfloat16):
    """Group size is implicit: q.shape[-1] // scale.shape[-1] (keeps the
    record free of non-array leaves so device_put/tree_map stay trivial)."""
    q = rec["q"]
    gs = q.shape[-1] // rec["scale"].shape[-1]
    shape = q.shape
    g = q.astype(jnp.float32).reshape(shape[:-1] + (-1, gs))
    if "zero" in rec:
        w = (g + 128.0) * rec["scale"][..., None] + rec["zero"][..., None]
    else:
        w = g * rec["scale"][..., None]
    return w.reshape(shape).astype(dtype)


def _is_norm_path(path) -> bool:
    flat = "/".join(str(getattr(k, "key", getattr(k, "name", k)))
                    for k in path).lower()
    return any(t in flat for t in
               ("ln", "norm", "bias", "scale", "gamma", "beta"))


def quantize_pytree(params: PyTree, num_bits: int = 8, group_size: int = 64,
                    symmetric: bool = True, min_size: int = 4096,
                    min_penultimate: int = 64, min_ndim: int = 2) -> PyTree:
    """Quantize WEIGHT-MATRIX-like float leaves; others pass through.

    A leaf qualifies when it has >= ``min_size`` elements, >= 2 dims, a
    last dim divisible by ``group_size``, and ``shape[-2] >=
    min_penultimate``.  The penultimate-dim test is what separates real
    matrices ([.., d_in, d_out], embeddings [V, d]) from per-layer-STACKED
    norm scales and biases ([L, d] with small L) — quantizing those would
    inject multiplicative error into every normalization while saving
    almost nothing (the weight-only posture of the reference INT8 path).
    Because a deep stack ([L, d] with L >= min_penultimate, e.g. 80-layer
    Llama) defeats the shape test alone, any leaf whose key path names a
    norm/bias/scale parameter is excluded outright — and callers
    quantizing a STACKED-blocks subtree pass ``min_ndim=3``, which
    excludes every per-layer 1D param ([L, d] stacked biases named
    ``*_b`` defeat both the name filter and, at L >= 64, the
    penultimate-dim test)."""
    def one(path, x):
        if (hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
                and getattr(x, "ndim", 0) >= min_ndim
                and getattr(x, "ndim", 0) >= 2 and x.size >= min_size
                and x.shape[-1] % group_size == 0
                and x.shape[-2] >= min_penultimate
                and not _is_norm_path(path)):
            return quantize(x, num_bits, group_size, symmetric)
        return x

    return jax.tree_util.tree_map_with_path(one, params)


def dequantize_pytree(params: PyTree, dtype=jnp.bfloat16) -> PyTree:
    """Inverse of :func:`quantize_pytree` (called INSIDE jit so XLA fuses
    the expansion into consumers)."""
    return jax.tree_util.tree_map(
        lambda x: dequantize(x, dtype) if is_quantized(x) else x,
        params, is_leaf=is_quantized)


def quantized_nbytes(params: PyTree) -> int:
    """Storage accounting (int8 + scales), for memory reports."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(
            params, is_leaf=is_quantized):
        if is_quantized(leaf):
            total += leaf["q"].size + leaf["scale"].size * 4
            if "zero" in leaf:
                total += leaf["zero"].size * 4
        elif hasattr(leaf, "nbytes"):
            total += leaf.nbytes
    return total


# ---------------------------------------------------------------- W8A8 (s8 MXU)
# K-GROUPED weight records for the s8xs8 matmul path
# (ops/quantized_matmul.w8a8_matmul): scales are constant over each
# contraction-axis chunk of ``k_group`` rows, so they factor OUT of the
# k-sum — the MXU runs a native int8 dot and the (activation_scale x
# weight_scale) product applies to the int32 partial AFTER the dot.  The
# reference analog is MoQ's combined weight+activation INT8 quantization
# (``deepspeed/compression/basic_layer.py`` QuantAct + the int8 GEMMs of
# DS-Inference); on TPU this is the only int8 layout that reaches the
# MXU's s8 path — N-grouped scales can't leave the accumulation.

_QK_KEYS = frozenset({"qk", "kscale"})


def is_k_quantized(leaf) -> bool:
    """True for a K-grouped record produced by :func:`quantize_k_grouped`."""
    return isinstance(leaf, dict) and _QK_KEYS == set(leaf)


def quantize_k_grouped(w, k_group: int = 256) -> dict:
    """w: [..., K, N] float, K divisible by ``k_group`` ->
    ``{"qk": int8 (w.shape), "kscale": f32 [..., K/G, 1, N]}`` (the
    middle 1 keeps every kscale block lane-legal in Pallas)."""
    shape = w.shape
    k_dim, n_dim = shape[-2], shape[-1]
    assert k_dim % k_group == 0, (shape, k_group)
    g = w.astype(jnp.float32).reshape(
        shape[:-2] + (k_dim // k_group, k_group, n_dim))
    amax = jnp.max(jnp.abs(g), axis=-2, keepdims=True)   # [.., K/G, 1, N]
    scale = jnp.where(amax == 0, 1.0, amax / 127.0)
    qk = jnp.clip(jnp.round(g / scale), -127, 127).astype(jnp.int8)
    return {"qk": qk.reshape(shape), "kscale": scale}


def dequantize_k(rec: dict, dtype=jnp.bfloat16):
    """Expand a K-grouped record (fallback / non-decode path)."""
    qk, scale = rec["qk"], rec["kscale"]
    shape = qk.shape
    k_group = shape[-2] // scale.shape[-3]
    g = qk.astype(jnp.float32).reshape(
        shape[:-2] + (shape[-2] // k_group, k_group, shape[-1]))
    return (g * scale).reshape(shape).astype(dtype)


def quantize_pytree_k_grouped(params: PyTree, k_group: int = 256,
                              min_size: int = 4096,
                              min_ndim: int = 2) -> PyTree:
    """W8A8 variant of :func:`quantize_pytree`: same weight-matrix
    selection rules (incl. ``min_ndim=3`` for stacked-blocks subtrees),
    K-grouped records; leaves whose K doesn't divide ``k_group`` stay
    dense."""
    def one(path, x):
        if (hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
                and getattr(x, "ndim", 0) >= min_ndim
                and getattr(x, "ndim", 0) >= 2 and x.size >= min_size
                and x.shape[-2] % k_group == 0
                and x.shape[-1] % 128 == 0
                and not _is_norm_path(path)):
            return quantize_k_grouped(x, k_group)
        return x

    return jax.tree_util.tree_map_with_path(one, params)
