"""Group-wise INT8 weight quantization (storage-level, not fake-quant).

Reference parity: ``csrc/quantization/{quantize.cu,dequantize.cu,
pt_binding.cpp}`` (symmetric/asymmetric group (de)quantization) and the
INT8 weight path of DS-Inference (``module_inject/replace_module.py:152
GroupQuantizer``) / ZeRO-Inference weight quantization
(``docs/_posts/2022-09-10-zero-inference.md``).

TPU design: weights are stored in HBM as int8 plus per-group f32 scales
(groups along the LAST axis); dequantization happens inside the jitted
forward right at the point of use, where XLA fuses the
``(q - zero) * scale`` expansion into the consumer — there is no separate
kernel to launch, so the "kernel" here is the storage format + the fused
expansion.  The training-time fake-quant STE lives in
:mod:`deepspeed_tpu.compression.ops`.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

PyTree = Any

_QKEYS = frozenset({"q", "scale", "zero"})


def is_quantized(leaf) -> bool:
    """True for a quantized-weight record produced by :func:`quantize`."""
    return isinstance(leaf, dict) and _QKEYS.issuperset(leaf) and "q" in leaf


def quantize(w, num_bits: int = 8, group_size: int = 64,
             symmetric: bool = True) -> dict:
    """w: float array, last dim divisible by ``group_size`` ->
    ``{"q": int8 (w.shape), "scale": f32 [..., G], ["zero": f32 [..., G]]}``.
    """
    assert num_bits == 8, "int8 storage only (num_bits=8)"
    shape = w.shape
    assert shape[-1] % group_size == 0, (shape, group_size)
    g = w.astype(jnp.float32).reshape(shape[:-1] + (-1, group_size))
    if symmetric:
        amax = jnp.max(jnp.abs(g), axis=-1)
        scale = jnp.where(amax == 0, 1.0, amax / 127.0)
        q = jnp.clip(jnp.round(g / scale[..., None]), -127, 127)
        return {"q": q.astype(jnp.int8).reshape(shape), "scale": scale}
    lo = jnp.min(g, axis=-1)
    hi = jnp.max(g, axis=-1)
    scale = jnp.where(hi == lo, 1.0, (hi - lo) / 255.0)
    zero = lo
    q = jnp.clip(jnp.round((g - zero[..., None]) / scale[..., None]),
                 0, 255) - 128
    return {"q": q.astype(jnp.int8).reshape(shape), "scale": scale,
            "zero": zero}


def dequantize(rec: dict, dtype=jnp.bfloat16):
    """Group size is implicit: q.shape[-1] // scale.shape[-1] (keeps the
    record free of non-array leaves so device_put/tree_map stay trivial)."""
    q = rec["q"]
    gs = q.shape[-1] // rec["scale"].shape[-1]
    shape = q.shape
    g = q.astype(jnp.float32).reshape(shape[:-1] + (-1, gs))
    if "zero" in rec:
        w = (g + 128.0) * rec["scale"][..., None] + rec["zero"][..., None]
    else:
        w = g * rec["scale"][..., None]
    return w.reshape(shape).astype(dtype)


def _is_norm_path(path) -> bool:
    flat = "/".join(str(getattr(k, "key", getattr(k, "name", k)))
                    for k in path).lower()
    return any(t in flat for t in
               ("ln", "norm", "bias", "scale", "gamma", "beta"))


def quantize_pytree(params: PyTree, num_bits: int = 8, group_size: int = 64,
                    symmetric: bool = True, min_size: int = 4096,
                    min_penultimate: int = 64, min_ndim: int = 2) -> PyTree:
    """Quantize WEIGHT-MATRIX-like float leaves; others pass through.

    A leaf qualifies when it has >= ``min_size`` elements, >= 2 dims, a
    last dim divisible by ``group_size``, and ``shape[-2] >=
    min_penultimate``.  The penultimate-dim test is what separates real
    matrices ([.., d_in, d_out], embeddings [V, d]) from per-layer-STACKED
    norm scales and biases ([L, d] with small L) — quantizing those would
    inject multiplicative error into every normalization while saving
    almost nothing (the weight-only posture of the reference INT8 path).
    Because a deep stack ([L, d] with L >= min_penultimate, e.g. 80-layer
    Llama) defeats the shape test alone, any leaf whose key path names a
    norm/bias/scale parameter is excluded outright — and callers
    quantizing a STACKED-blocks subtree pass ``min_ndim=3``, which
    excludes every per-layer 1D param ([L, d] stacked biases named
    ``*_b`` defeat both the name filter and, at L >= 64, the
    penultimate-dim test)."""
    def one(path, x):
        if (hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
                and getattr(x, "ndim", 0) >= min_ndim
                and getattr(x, "ndim", 0) >= 2 and x.size >= min_size
                and x.shape[-1] % group_size == 0
                and x.shape[-2] >= min_penultimate
                and not _is_norm_path(path)):
            return quantize(x, num_bits, group_size, symmetric)
        return x

    return jax.tree_util.tree_map_with_path(one, params)


def dequantize_pytree(params: PyTree, dtype=jnp.bfloat16) -> PyTree:
    """Inverse of :func:`quantize_pytree` (called INSIDE jit so XLA fuses
    the expansion into consumers)."""
    return jax.tree_util.tree_map(
        lambda x: dequantize(x, dtype) if is_quantized(x) else x,
        params, is_leaf=is_quantized)


def quantized_nbytes(params: PyTree) -> int:
    """Storage accounting (int8 + scales), for memory reports."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(
            params, is_leaf=is_quantized):
        if is_quantized(leaf):
            total += leaf["q"].size + leaf["scale"].size * 4
            if "zero" in leaf:
                total += leaf["zero"].size * 4
        elif hasattr(leaf, "nbytes"):
            total += leaf.nbytes
    return total


# ---------------------------------------------------------------- W8A8 (s8 MXU)
# K-GROUPED weight records for the s8xs8 matmul path
# (ops/quantized_matmul.w8a8_matmul): scales are constant over each
# contraction-axis chunk of ``k_group`` rows, so they factor OUT of the
# k-sum — the MXU runs a native int8 dot and the (activation_scale x
# weight_scale) product applies to the int32 partial AFTER the dot.  The
# reference analog is MoQ's combined weight+activation INT8 quantization
# (``deepspeed/compression/basic_layer.py`` QuantAct + the int8 GEMMs of
# DS-Inference); on TPU this is the only int8 layout that reaches the
# MXU's s8 path — N-grouped scales can't leave the accumulation.

_QK_KEYS = frozenset({"qk", "kscale"})


def is_k_quantized(leaf) -> bool:
    """True for a K-grouped record produced by :func:`quantize_k_grouped`."""
    return isinstance(leaf, dict) and _QK_KEYS == set(leaf)


def is_record(leaf) -> bool:
    """True for ANY quantization record kind (N-grouped weight-only or
    K-grouped w8a8) — the tree-traversal ``is_leaf`` predicate."""
    return is_quantized(leaf) or is_k_quantized(leaf)


def pick_k_group(k_dim: int, cap: int, shard_multiple: int = 1) -> int:
    """Largest k-chunk size ``g <= cap`` (multiple of 8 — the TPU sublane
    quantum of the in-kernel ``[.., g]`` activation tiles) with
    ``k_dim % g == 0``, and — when the contraction dim divides over
    ``shard_multiple`` row-parallel TP shards — whole groups on every
    shard (``(k_dim // g) % shard_multiple == 0``), so a K sharding never
    splits a quant group and the custom_partitioning lowering
    (ops/quantized_matmul._w8a8_partition) stays sharded instead of
    gathering the weight.  Returns 0 when no such ``g`` exists."""
    need_align = shard_multiple > 1 and k_dim % shard_multiple == 0
    g = (min(cap, k_dim) // 8) * 8
    while g >= 8:
        if k_dim % g == 0 and (
                not need_align or (k_dim // g) % shard_multiple == 0):
            return g
        g -= 8
    return 0


_HOST_QUANT_CHUNK_BYTES = 1 << 30


def _quantize_k_grouped_np(w, k_group: int) -> dict:
    """Chunked pure-numpy K-grouped quantization for HOST arrays.

    Multi-billion-param host trees must not run the eager jnp pipeline:
    every elementwise op materializes a full f32 copy (a stacked OPT-13B
    fc leaf is 16.8GB — the chain of astype/div/round/clip OOM-killed a
    125GB host).  Slices of the leading dim bound the transient to
    ~chunk-size; outputs write into preallocated arrays."""
    shape = w.shape
    k_dim, n_dim = shape[-2], shape[-1]
    lead = int(np.prod(shape[:-2], dtype=np.int64)) if len(shape) > 2 else 1
    w3 = np.asarray(w).reshape(lead, k_dim, n_dim)
    qk = np.empty((lead, k_dim, n_dim), np.int8)
    scale = np.empty((lead, k_dim // k_group, 1, n_dim), np.float32)
    step = max(1, _HOST_QUANT_CHUNK_BYTES //
               max(k_dim * n_dim * 4, 1))
    for i in range(0, lead, step):
        # astype always copies: the in-place divide/round below must
        # never write through a view into the caller's weights
        g = w3[i:i + step].astype(np.float32).reshape(
            -1, k_dim // k_group, k_group, n_dim)
        amax = np.max(np.abs(g), axis=-2, keepdims=True)
        s = np.where(amax == 0, np.float32(1.0),
                     amax / np.float32(127.0)).astype(np.float32)
        np.divide(g, s, out=g)
        np.round(g, out=g)
        np.clip(g, -127, 127, out=g)
        qk[i:i + step] = g.astype(np.int8).reshape(-1, k_dim, n_dim)
        scale[i:i + step] = s
    return {"qk": qk.reshape(shape),
            "kscale": scale.reshape(shape[:-2] +
                                    (k_dim // k_group, 1, n_dim))}


def quantize_k_grouped(w, k_group: int = 256) -> dict:
    """w: [..., K, N] float, K divisible by ``k_group`` ->
    ``{"qk": int8 (w.shape), "kscale": f32 [..., K/G, 1, N]}`` (the
    middle 1 keeps every kscale block lane-legal in Pallas).  Host
    (numpy) inputs above ~1GB take a chunked numpy path that bounds the
    transient working set (see :func:`_quantize_k_grouped_np`)."""
    shape = w.shape
    k_dim, n_dim = shape[-2], shape[-1]
    assert k_dim % k_group == 0, (shape, k_group)
    if isinstance(w, np.ndarray) and w.nbytes >= _HOST_QUANT_CHUNK_BYTES:
        return _quantize_k_grouped_np(w, k_group)
    g = w.astype(jnp.float32).reshape(
        shape[:-2] + (k_dim // k_group, k_group, n_dim))
    amax = jnp.max(jnp.abs(g), axis=-2, keepdims=True)   # [.., K/G, 1, N]
    scale = jnp.where(amax == 0, 1.0, amax / 127.0)
    qk = jnp.clip(jnp.round(g / scale), -127, 127).astype(jnp.int8)
    return {"qk": qk.reshape(shape), "kscale": scale}


# ------------------------------------------------------------- int8 KV cache
# Per-vector symmetric absmax quantization for the paged KV pool
# (ops/paged_kv quantized pool records): each (layer, block, head, slot)
# token vector of ``hd`` values carries its own scale, computed at WRITE
# time from that vector alone.  Tokens are written exactly once (paged
# writes are append-only; speculative rollback overwrites a position with
# the same deterministic codes), so no stored code is ever re-scaled —
# unlike a per-block scalar scale, which would force a read-modify-write
# requantization of the whole block every time a later token raised the
# block's absmax.  Scales store in bf16: absmax/127 of activation-range
# values always fits, and the 2^-9 relative rounding sits below the int8
# quantization error itself (same argument as the w8a8 kernel's bf16
# dequant, ops/quantized_matmul).


def quantize_kv(x, scale_dtype=jnp.bfloat16):
    """Symmetric int8 quantization over the LAST axis of ``x``: returns
    ``(codes int8 x.shape, scale scale_dtype x.shape[:-1])`` with
    ``dequant = codes * scale[..., None]``.  Codes are computed against
    the ROUNDED stored scale so write and read agree exactly; all-zero
    vectors store scale 1 (codes 0)."""
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1)
    scale = jnp.where(amax == 0, 1.0, amax / 127.0).astype(scale_dtype)
    codes = jnp.clip(jnp.round(x32 / scale[..., None].astype(jnp.float32)),
                     -127, 127).astype(jnp.int8)
    return codes, scale


def dequantize_kv(codes, scale, dtype=jnp.float32):
    """Inverse of :func:`quantize_kv` (f32 expand, cast once)."""
    return (codes.astype(jnp.float32) *
            scale[..., None].astype(jnp.float32)).astype(dtype)


def dequantize_k(rec: dict, dtype=jnp.bfloat16):
    """Expand a K-grouped record (fallback / non-decode path)."""
    qk, scale = rec["qk"], rec["kscale"]
    shape = qk.shape
    k_group = shape[-2] // scale.shape[-3]
    g = qk.astype(jnp.float32).reshape(
        shape[:-2] + (shape[-2] // k_group, k_group, shape[-1]))
    return (g * scale).reshape(shape).astype(dtype)


def _k_dim_sharded(sharding, ndim: int) -> bool:
    """True when a (Named)Sharding places a mesh axis on a ``[..., K, N]``
    leaf's contraction dim — the row-parallel layout whose shards must
    hold whole quant groups."""
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return False
    spec = tuple(spec) + (None,) * (ndim - len(tuple(spec)))
    return ndim >= 2 and spec[ndim - 2] is not None


def quantize_pytree_k_grouped(params: PyTree, k_group: int = 256,
                              min_size: int = 4096,
                              min_ndim: int = 2,
                              shard_multiple: int = 1,
                              spec_tree: PyTree = None) -> PyTree:
    """W8A8 variant of :func:`quantize_pytree`: same weight-matrix
    selection rules (incl. ``min_ndim=3`` for stacked-blocks subtrees),
    K-grouped records; leaves whose K doesn't divide ``k_group`` stay
    dense (selection is independent of ``shard_multiple`` so every tp
    degree quantizes the same leaf set).  ``shard_multiple`` (the serving
    tp degree) refines the group SIZE of eligible leaves when the default
    would split quant groups across row-parallel shards: e.g. OPT-2.7B
    (K=2560, K/128=20 groups) under tp=8 quantizes at g=80 (32 groups,
    :func:`pick_k_group`) and stays TP-sharded instead of gathering.
    Finer groups only tighten the quantization error — but they also grow
    the f32 scale storage and the decode kernel's per-block trip count,
    so with ``spec_tree`` (a matching tree of shardings) only leaves whose
    K dim is actually sharded refine; N-sharded and replicated leaves
    keep the cap.  Without ``spec_tree`` the refinement is uniform, which
    keeps records bit-identical across tp degrees (the
    ``quant.shard_multiple`` pinning contract, inference/config.py)."""
    def one(path, x, sharding=None):
        if (hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
                and getattr(x, "ndim", 0) >= min_ndim
                and getattr(x, "ndim", 0) >= 2 and x.size >= min_size
                and x.shape[-2] % k_group == 0
                and x.shape[-1] % 128 == 0
                and not _is_norm_path(path)):
            sm = shard_multiple
            if spec_tree is not None and not _k_dim_sharded(sharding, x.ndim):
                sm = 1
            g = pick_k_group(x.shape[-2], k_group, sm) or k_group
            return quantize_k_grouped(x, g)
        return x

    if spec_tree is not None:
        return jax.tree_util.tree_map_with_path(one, params, spec_tree)
    return jax.tree_util.tree_map_with_path(one, params)
