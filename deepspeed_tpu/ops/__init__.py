from . import optimizers
from .optimizers import adagrad, fused_adam, fused_lamb, get_optimizer, lion, sgd
