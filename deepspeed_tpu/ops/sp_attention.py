"""Sequence-parallel (DeepSpeed-Ulysses-style) prefill attention over the
mesh ``sp`` axis.

Long prompts make prefill compute the bottleneck: one chip owns the whole
``[T, S]`` score matrix of every head.  Ulysses sequence parallelism
shards the PROMPT over ``sp`` ranks instead — each rank projects QKV for
its own ``T/sp`` token slice (the model families' ``shard_seq`` hint
makes GSPMD keep hidden states token-sharded through the projections) —
and converts between the two layouts around attention with a pair of
``lax.all_to_all`` collectives:

 1. heads -> sequence: ``[B, H/tp, T/sp, D] -> [B, H/(tp*sp), T, D]`` —
    every rank now holds ALL chunk positions for its own 1/sp slice of
    the query heads, so attention itself stays embarrassingly parallel
    over heads (exactly the property the tp path exploits);
 2. attention against the row's full paged-KV view (gathered through the
    block table, same pool, same scatter ops — nothing downstream of the
    pool changes);
 3. sequence -> heads: the inverse all-to-all restores the token-sharded
    layout the output projection expects.

Like ``paged_kv``'s tp/dp contexts, the sp context is module state
installed by the serving engine around *prefill* program invocations
only — tracing happens inside the call, so prefill programs bake in the
sp shard_map while decode/verify programs (traced outside the context)
are untouched, and ``sp=1`` engines never enter this module at all.
"""

from __future__ import annotations

import contextlib
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from . import paged_kv
from .paged_kv import _paged_gather, pool_payload, tp_axis

# ------------------------------------------------------------- sp context
_SP_MESH = None
_SP_AXIS = "sp"


def configure_sp(mesh=None, axis: str = "sp") -> None:
    """Install (mesh + axis name) or clear (``None``) the sequence-parallel
    context.  With a mesh installed, paged prefill attention (T > 1) whose
    shapes divide the axis runs the Ulysses all-to-all path."""
    global _SP_MESH, _SP_AXIS
    _SP_MESH = mesh
    _SP_AXIS = axis


@contextlib.contextmanager
def sp_context(mesh, axis: str = "sp"):
    """Scoped :func:`configure_sp` — the serving engine wraps prefill
    invocations (and only those) in this, so each engine's prefill
    programs bake in ITS sp mesh even when engines of different sp
    degrees coexist in one process."""
    prev = (_SP_MESH, _SP_AXIS)
    configure_sp(mesh, axis)
    try:
        yield
    finally:
        configure_sp(*prev)


def sp_mesh():
    return _SP_MESH


def sp_axis() -> str:
    return _SP_AXIS


def sp_shards(h: int, hkv: int, t: int) -> int:
    """Shard count the configured sp context puts on a ``[B, H, T, D]``
    prefill: the mesh's sp-axis size when the chunk width and the
    per-tp-shard query heads both divide it (and GQA groups divide
    evenly), else 1 — the replicated fallback, mirroring
    ``paged_kv.head_shards``."""
    if _SP_MESH is None:
        return 1
    sp = int(dict(_SP_MESH.shape).get(_SP_AXIS, 1))
    if sp <= 1 or t <= 1:
        return 1
    tp = paged_kv.head_shards(h, hkv)
    if t % sp or h % hkv or (h // tp) % sp:
        return 1
    return sp


def shard_seq(x):
    """Prefill hook for the model families: constrain hidden states
    ``[B, T, D]`` token-sharded over the sp axis so the QKV/MLP
    projections around attention run on 1/sp of the chunk per rank
    (GSPMD propagates the layout through the elementwise/matmul chain).
    No-op without an sp context, on decode (T == 1), or when T doesn't
    divide the axis — so the hook is safe to leave in every family's
    cached-forward path unconditionally."""
    if _SP_MESH is None:
        return x
    sp = int(dict(_SP_MESH.shape).get(_SP_AXIS, 1))
    if sp <= 1 or x.ndim != 3 or x.shape[1] <= 1 or x.shape[1] % sp:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(_SP_MESH, P(None, _SP_AXIS, None)))


def sp_prefill_attention(q, k_pool, v_pool, block_tables, q_pos, *,
                         sm_scale: Optional[float] = None):
    """Ulysses sequence-parallel paged prefill attention.

    q:            [B, H, T, D] — a T-token prefill chunk (T % sp == 0)
    k/v_pool:     [NB, HKV, block_size, D] shared paged pool (optionally
                  tp-head-sharded; sp composes with tp in ONE shard_map)
    block_tables: int32 [B, NBPER]
    q_pos:        scalar or int32 [B] — per-row chunk base positions

    The KV side is each row's full logical cache view gathered through
    its block table (replicated across sp ranks — the pool has no
    sequence dim to shard); the [T, S] score/softmax work, which is what
    actually scales quadratically with context, splits sp-ways over query
    heads after the first all-to-all.
    """
    from jax.experimental.shard_map import shard_map

    from .decode_attention import decode_attention_reference

    mesh, ax = _SP_MESH, _SP_AXIS
    b, h, t, d = q.shape
    hkv = pool_payload(k_pool).shape[1]
    sp = sp_shards(h, hkv, t)
    if sp <= 1:
        raise ValueError("sp_prefill_attention called without a dividing "
                         "sp context; dispatch should have fallen back")
    tp = paged_kv.head_shards(h, hkv)
    rep = h // hkv
    hq_loc = h // (tp * sp)        # query heads per program after the a2a
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    tp_ax = tp_axis() if tp > 1 else None
    qs = P(None, tp_ax, ax)        # [B, H, T, D]: heads over tp, T over sp
    ps = P(None, tp_ax)            # pool leaves: heads over tp only

    def body(q, kp, vp, bt, pos):
        # q arrives [B, H/tp, T/sp, D]; heads -> sequence
        q = jax.lax.all_to_all(q, ax, split_axis=1, concat_axis=2,
                               tiled=True)                # [B, hq_loc, T, D]
        k = _paged_gather(kp, bt, out_dtype=q.dtype)      # [B, HKV/tp, S, D]
        v = _paged_gather(vp, bt, out_dtype=q.dtype)
        if rep > 1:
            k = jnp.repeat(k, rep, axis=1)                # [B, H/tp, S, D]
            v = jnp.repeat(v, rep, axis=1)
        # this rank's query heads are the idx-th hq_loc-slice of the tp
        # shard (tiled all_to_all concatenates source parts in rank order)
        idx = jax.lax.axis_index(ax)
        k = jax.lax.dynamic_slice_in_dim(k, idx * hq_loc, hq_loc, axis=1)
        v = jax.lax.dynamic_slice_in_dim(v, idx * hq_loc, hq_loc, axis=1)
        out = decode_attention_reference(q, k, v, pos, sm_scale=scale)
        # sequence -> heads: restore the token-sharded layout
        return jax.lax.all_to_all(out, ax, split_axis=2, concat_axis=1,
                                  tiled=True)

    pos = jnp.broadcast_to(jnp.asarray(q_pos, jnp.int32).reshape(-1), (b,))
    return shard_map(body, mesh=mesh, in_specs=(qs, ps, ps, P(), P()),
                     out_specs=qs, check_rep=False)(
        q, k_pool, v_pool, jnp.asarray(block_tables, jnp.int32), pos)


def alltoall_bytes(n_layers: int, rows: int, width: int, heads: int,
                   head_dim: int, itemsize: int, sp: int) -> int:
    """Host-side accounting of cross-rank bytes moved by the two Ulysses
    all-to-alls of one prefill call (per layer: q in, attention out —
    each a [rows, heads, width, head_dim] tensor of which the (sp-1)/sp
    off-diagonal fraction crosses ranks).  Feeds the
    ``serving_sp_alltoall_bytes_total`` counter; an estimate from shapes,
    not a device measurement."""
    per = 2 * int(n_layers) * int(rows) * int(width) * int(heads) * \
        int(head_dim) * int(itemsize)
    return (per * (int(sp) - 1)) // max(int(sp), 1)
