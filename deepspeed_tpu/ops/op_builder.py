"""Native op build system — the TPU analog of the reference's ``op_builder/``.

The reference JIT-compiles CUDA/C++ extensions through torch cpp_extension
(``op_builder/builder.py:112 OpBuilder``, ``:487 jit_load``) and probes
compatibility before building (``:236 is_compatible``).  Here the native
surface is host-side C++ only (TPU device code is Pallas), so the builder is
lean: g++ compiles a shared library once into ``_build/`` keyed by a source
hash, and Python binds it via ctypes (no pybind11 in the image).  Every
builder has a pure-python/numpy fallback path so missing toolchains degrade
gracefully rather than fail (same contract as the reference's
``is_compatible`` warnings).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from pathlib import Path
from typing import List, Optional

from ..utils.logging import logger

CSRC = Path(__file__).resolve().parent / "csrc"
BUILD_DIR = Path(__file__).resolve().parent / "_build"


class OpBuilder:
    """Compile ``sources`` into a cached .so and load it with ctypes."""

    NAME: str = "op"
    SOURCES: List[str] = []
    EXTRA_FLAGS: List[str] = []

    _lib_cache: dict = {}

    @classmethod
    def _source_paths(cls) -> List[Path]:
        return [CSRC / s for s in cls.SOURCES]

    @classmethod
    def _host_id(cls) -> str:
        """Host identifier folded into the cache key: -march=native output is
        host-specific, so a _build/ dir shared across heterogeneous machines
        (NFS, prebuilt container) must not serve another host's .so."""
        import platform

        ident = platform.machine()
        seen = set()
        try:
            with open("/proc/cpuinfo") as f:
                for line in f:
                    key = line.split(":", 1)[0].strip()
                    # model name alone is not enough: a hypervisor can mask
                    # ISA features (e.g. AVX-512) on one of two same-model VMs
                    if key in ("model name", "flags", "Features") and \
                            key not in seen:
                        seen.add(key)
                        ident += line
                        if len(seen) == 2:
                            break
        except OSError:
            pass
        return ident

    @classmethod
    def _hash(cls, flags: List[str]) -> str:
        h = hashlib.sha256()
        for p in cls._source_paths():
            h.update(p.read_bytes())
        h.update(" ".join(flags).encode())
        if "-march=native" in flags:
            h.update(cls._host_id().encode())
        return h.hexdigest()[:16]

    @classmethod
    def _flags(cls) -> List[str]:
        return ["-O3", "-march=native", "-std=c++17", "-shared", "-fPIC",
                "-fopenmp"] + cls.EXTRA_FLAGS

    @classmethod
    def is_compatible(cls) -> bool:
        try:
            subprocess.run(["g++", "--version"], capture_output=True,
                           check=True)
            return all(p.exists() for p in cls._source_paths())
        except (OSError, subprocess.CalledProcessError):
            return False

    @classmethod
    def load(cls) -> Optional[ctypes.CDLL]:
        """Build (if needed) and load the native library; None on failure."""
        if cls.NAME in cls._lib_cache:
            return cls._lib_cache[cls.NAME]
        lib = cls._build_and_load()
        cls._lib_cache[cls.NAME] = lib
        return lib

    @classmethod
    def _build_and_load(cls) -> Optional[ctypes.CDLL]:
        if not cls.is_compatible():
            logger.warning(f"op {cls.NAME}: toolchain/sources unavailable, "
                           "using python fallback")
            return None
        BUILD_DIR.mkdir(exist_ok=True)
        # -march=native can fail on exotic hosts; retry portable (with its
        # own cache key, so the portable .so never shadows a native one)
        flag_sets = [cls._flags(),
                     [f for f in cls._flags() if f != "-march=native"]]
        so_path = None
        last_err = None
        for flags in flag_sets:
            candidate = BUILD_DIR / f"{cls.NAME}_{cls._hash(flags)}.so"
            if candidate.exists():
                so_path = candidate
                break
            cmd = (["g++"] + flags +
                   [str(p) for p in cls._source_paths()] +
                   ["-o", str(candidate)])
            try:
                subprocess.run(cmd, capture_output=True, check=True, text=True)
                logger.info(f"op {cls.NAME}: built {candidate.name}")
                so_path = candidate
                break
            except subprocess.CalledProcessError as e:
                last_err = e
        if so_path is None:
            err = last_err.stderr[-500:] if (last_err and last_err.stderr) \
                else last_err
            logger.warning(f"op {cls.NAME}: build failed ({err}); "
                           "using python fallback")
            return None
        try:
            return ctypes.CDLL(str(so_path), mode=ctypes.RTLD_GLOBAL)
        except OSError as e:
            logger.warning(f"op {cls.NAME}: load failed ({e}); python fallback")
            return None


class CPUAdamBuilder(OpBuilder):
    """Vectorized host Adam/Adagrad (reference csrc/adam/cpu_adam.cpp)."""

    NAME = "cpu_adam"
    SOURCES = ["cpu_adam.cpp"]

    @classmethod
    def bind(cls):
        lib = cls.load()
        if lib is None:
            return None
        i64, f32p, f64 = ctypes.c_int64, ctypes.POINTER(ctypes.c_float), \
            ctypes.c_double
        lib.ds_adam_step.argtypes = [f32p, f32p, f32p, f32p, i64,
                                     ctypes.c_float, ctypes.c_float,
                                     ctypes.c_float, ctypes.c_float,
                                     ctypes.c_float, i64, ctypes.c_int,
                                     ctypes.c_int]
        lib.ds_adagrad_step.argtypes = [f32p, f32p, f32p, i64, ctypes.c_float,
                                        ctypes.c_float, ctypes.c_float]
        lib.ds_sq_norm.argtypes = [f32p, i64]
        lib.ds_sq_norm.restype = f64
        lib.ds_scale.argtypes = [f32p, i64, ctypes.c_float]
        lib.ds_all_finite.argtypes = [f32p, i64]
        lib.ds_all_finite.restype = ctypes.c_int
        return lib


class AsyncIOBuilder(OpBuilder):
    """Threaded async file I/O (reference csrc/aio/)."""

    NAME = "aio"
    SOURCES = ["aio.cpp"]
    EXTRA_FLAGS = ["-pthread"]

    @classmethod
    def bind(cls):
        lib = cls.load()
        if lib is None:
            return None
        i64, vp = ctypes.c_int64, ctypes.c_void_p
        lib.ds_aio_handle_new.argtypes = [ctypes.c_int]
        lib.ds_aio_handle_new.restype = vp
        lib.ds_aio_handle_free.argtypes = [vp]
        lib.ds_aio_pread.argtypes = [vp, ctypes.c_char_p, vp, i64, i64]
        lib.ds_aio_pwrite.argtypes = [vp, ctypes.c_char_p, vp, i64, i64]
        lib.ds_aio_wait.argtypes = [vp]
        lib.ds_aio_wait.restype = i64
        lib.ds_aio_backend.argtypes = [vp]
        lib.ds_aio_backend.restype = ctypes.c_int
        return lib


ALL_OPS = {"cpu_adam": CPUAdamBuilder, "aio": AsyncIOBuilder}


def op_report() -> dict:
    """Compat/availability report (feeds the ds_report CLI analog)."""
    report = {}
    for name, builder in ALL_OPS.items():
        compatible = builder.is_compatible()
        loaded = builder.load() is not None if compatible else False
        report[name] = {"compatible": compatible, "loaded": loaded}
    return report
