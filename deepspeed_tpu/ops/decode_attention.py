"""Decode attention over a static KV cache — TPU replacement for the
reference's fused ``softmax_context`` inference kernels
(``csrc/transformer/inference/csrc/pt_binding.cpp`` attention variants, KV
workspace ``csrc/transformer/inference/includes/inference_context.h``).

The cache is a statically-shaped HBM buffer ``[B, HKV, S_max, D]`` sized by
``max_out_tokens`` exactly like the reference's ``InferenceContext`` workspace;
the valid prefix length is a traced scalar — or, for continuous-batching
serving, a traced ``int32[B]`` vector so every cache slot attends over its own
valid prefix — so one compiled program serves every decode step (the reference
gets the same effect from CUDA-graph replay; here it falls out of ``jit`` +
static shapes).

Two paths, one API:
 - ``decode_attention_reference``: q of one or more new positions against the
   cache, with position-aware causal masking (query at global position p sees
   keys ``<= p``) and grouped-query (GQA) head sharing.  Pure XLA — used for
   prefill and as the CPU/correctness path.
 - ``decode_attention_pallas``: single-token kernel that streams the cache in
   ``block_k`` chunks with an online softmax (f32 accumulation, no [S] score
   materialisation).  Chunks past the valid prefix are skipped with ``pl.when``
   so FLOPs scale with the *valid* length, not the workspace size.
"""

from __future__ import annotations

import contextlib
import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from . import paged_kv
from .paged_kv import (_paged_gather, head_shard_map, head_shards,
                       is_quantized_pool, pool_payload, tp_axis)

NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)
LANES = 128

# jax renamed TPUCompilerParams -> CompilerParams across releases; accept both
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


# --------------------------------------------------------- window context
#: Resident-window attention state for 100k+-token serving
#: (``ServingEngine(resident_window_blocks=N)``): ``(window_start,
#: landmark_tokens)`` where ``window_start`` is a TRACED int32 [B] operand
#: of the serving program (per-row first token of the device-resident
#: window) and ``landmark_tokens`` a static int — the pinned leading span
#: that never leaves the device.  A query keeps a key iff it is causal AND
#: (``key < landmark_tokens`` OR ``key >= window_start[b]``); the masked
#: middle region's blocks have been demoted to the host/NVMe tiers and
#: their table entries re-point at scratch, so the mask is what makes the
#: scratch garbage unreachable.  Like the tp/dp contexts this is module
#: state read at TRACE time — the engine enters it INSIDE the jitted
#: program body, so only windowed programs bake in the extra mask and
#: ``window_start = landmark_tokens`` rows reduce to exact full attention.
_WINDOW = None


@contextlib.contextmanager
def window_context(window_start, landmark_tokens: int):
    """Scoped install of the resident-window mask state (see ``_WINDOW``).
    Entered inside a traced serving-program body; nesting restores the
    previous state on exit."""
    global _WINDOW
    prev = _WINDOW
    _WINDOW = (window_start, int(landmark_tokens))
    try:
        yield
    finally:
        _WINDOW = prev


def window_state():
    """``(window_start int32 [B], landmark_tokens int)`` or ``None``."""
    return _WINDOW


def decode_attention_reference(q, k_cache, v_cache, q_pos, *,
                               sm_scale: Optional[float] = None):
    """Masked attention of new queries against the KV cache (pure XLA).

    q:        [B, H, T, D]  — T new query positions (T=1 for decode,
                              T=prompt_len for prefill)
    k_cache:  [B, HKV, S, D], v_cache: [B, HKV, S, D] — the *already updated*
              cache (new keys written at q_pos .. q_pos+T-1)
    q_pos:    scalar int32 — global position of q[:, :, 0]; or int32 [B] for
              per-sequence positions (continuous-batching slots, each row
              attends over its own valid prefix)
    """
    b, h, t, d = q.shape
    hkv, s = k_cache.shape[1], k_cache.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    if h != hkv:
        rep = h // hkv
        k_cache = jnp.repeat(k_cache, rep, axis=1)
        v_cache = jnp.repeat(v_cache, rep, axis=1)
    scores = jnp.einsum("bhtd,bhsd->bhts", q, k_cache).astype(jnp.float32)
    scores = scores * scale
    q_pos = jnp.asarray(q_pos, jnp.int32)
    key_idx = jnp.arange(s)
    if q_pos.ndim == 0:
        query_idx = q_pos + jnp.arange(t)[:, None]
        mask = key_idx[None, :] <= query_idx          # [T, S]
        mask = mask[None, None]                       # [1, 1, T, S]
    else:
        query_idx = q_pos[:, None] + jnp.arange(t)[None, :]
        mask = key_idx[None, None, :] <= query_idx[:, :, None]  # [B, T, S]
        mask = mask[:, None]                          # [B, 1, T, S]
    win = window_state()
    if win is not None:
        wstart, landmark = win
        wstart = jnp.asarray(wstart, jnp.int32).reshape(-1)
        keep = (key_idx[None, :] < landmark) | \
            (key_idx[None, :] >= wstart[:, None])     # [B, S]
        mask = mask & keep[:, None, None, :]
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bhsd->bhtd", probs, v_cache)


# ---------------------------------------------------------------------------
# Pallas single-token decode kernel
# ---------------------------------------------------------------------------
def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
                   *, sm_scale: float, block_k: int,
                   ks_ref=None, vs_ref=None):
    """Grid: (B, HKV, S // block_k), KV innermost so scratch carries across.

    q_ref: [1, 1, rep, D] — the ``rep`` query heads sharing this KV head.
    k_ref/v_ref: [1, 1, block_k, D] chunk of the cache.
    pos_ref: int32 [B] in SMEM — per-row query position (a scalar q_pos is
    broadcast before the call), read for the row this grid step covers, so
    chunk skipping scales FLOPs with each slot's own valid length.

    ``ks_ref``/``vs_ref`` (int8-KV pools only): [1, 1, block_k] per-token
    dequant scales riding next to the code chunks.  They fold into the
    math on its 2-D lane-dim tiles — ``q·(code*s_k) = (q·code)*s_k`` on
    the score columns, ``Σ p·(code*s_v) = (p*s_v)·code`` on the prob
    columns — so no dequantized [bk, D] copy is ever materialized and the
    online softmax (which normalizes over UNscaled probabilities) is
    untouched.
    """
    kb = pl.program_id(2)
    nk = pl.num_programs(2)
    pos = pos_ref[pl.program_id(0)]

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    start = kb * block_k

    @pl.when(start <= pos)  # skip chunks entirely past the valid prefix
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)           # [rep, D]
        k = k_ref[0, 0].astype(jnp.float32)           # [bk, D]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale                              # [rep, bk]
        if ks_ref is not None:
            s = s * ks_ref[...].reshape(1, -1).astype(jnp.float32)
        idx = start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(idx <= pos, s, NEG_INF)

        m_prev = m_scr[...][:, :1]                    # [rep, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                        # [rep, bk]
        l_new = l_scr[...][:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)           # [bk, D]
        pv = p * vs_ref[...].reshape(1, -1).astype(jnp.float32) \
            if vs_ref is not None else p
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            pv, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(kb == nk - 1)
    def _finish():
        l = l_scr[...][:, :1]
        o_ref[0, 0] = (acc_scr[...] /
                       jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


def decode_attention_pallas(q, k_cache, v_cache, q_pos, *,
                            sm_scale: Optional[float] = None,
                            block_k: int = 256,
                            interpret: Optional[bool] = None):
    """Single-token decode: q [B, H, 1, D] vs cache [B, HKV, S, D].
    ``q_pos``: scalar or per-row int32 [B] query positions."""
    b, h, t, d = q.shape
    assert t == 1, "pallas decode kernel is single-token; use the XLA path"
    hkv, s = k_cache.shape[1], k_cache.shape[2]
    rep = h // hkv
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    block_k = min(block_k, s)
    while s % block_k:  # largest divisor of s not above the requested block
        block_k -= 1
    if interpret is None:
        interpret = _use_interpret()

    qg = q[:, :, 0, :].reshape(b, hkv, rep, d)        # [B, HKV, rep, D]
    pos = jnp.broadcast_to(jnp.asarray(q_pos, jnp.int32).reshape(-1), (b,))

    out = pl.pallas_call(
        functools.partial(_decode_kernel, sm_scale=scale, block_k=block_k),
        grid=(b, hkv, s // block_k),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, rep, d), lambda i, j, k: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda i, j, k: (i, j, k, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda i, j, k: (i, j, k, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, rep, d), lambda i, j, k: (i, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hkv, rep, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((rep, LANES), jnp.float32),    # m
            pltpu.VMEM((rep, LANES), jnp.float32),    # l
            pltpu.VMEM((rep, d), jnp.float32),        # acc
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(pos, qg, k_cache, v_cache)
    return out.reshape(b, h, 1, d)


def decode_attention(q, k_cache, v_cache, q_pos, *,
                     sm_scale: Optional[float] = None):
    """Dispatch: Pallas kernel for single-token decode on TPU, XLA otherwise."""
    if q.shape[2] == 1 and jax.default_backend() == "tpu":
        return decode_attention_pallas(q, k_cache, v_cache, q_pos,
                                       sm_scale=sm_scale)
    return decode_attention_reference(q, k_cache, v_cache, q_pos,
                                      sm_scale=sm_scale)


# ---------------------------------------------------------------------------
# Block-paged attention (vLLM PagedAttention layout; ops/paged_kv.py holds
# the layout contract).  KV lives in a shared pool [NB, HKV, bs, D]; each
# row reaches its tokens through an int32 [B, NBPER] block table.
#
# Tensor parallelism: when ops/paged_kv carries a configured tp context and
# the head counts divide its axis, each paged-attention entry point runs
# its body inside shard_map — every chip attends its own HKV/tp (and H/tp
# query) head shard against its own pool shard, block tables and positions
# replicated.  Attention is embarrassingly parallel over heads, so no
# collective appears here; the tensor-parallel all-reduce happens after the
# model's output projection, exactly like the Megatron matmul path.
# ---------------------------------------------------------------------------
def _tp_shard_heads(body, q, k_pool, v_pool, block_tables, q_pos):
    """Run ``body(q, k_pool, v_pool, bt, pos)`` sharded over the head dims
    when the configured tp context divides them, else directly.  Int8 pool
    records shard whole: codes and their scale table both carry the head
    dim at index 1, so the one head spec broadcasts over the record.

    Under a configured dp context (``paged_kv.dp_context`` —
    ``engine_mode='dp_tp'`` serving) the batch rows and the pool's
    physical-block dim additionally shard over the mesh ``dp`` axis: each
    dp shard attends its own contiguous row span against its own pool
    chunk, localizing the global block-table ids into that chunk first
    (``paged_kv.localize_block_tables``) — group-scoped allocation makes
    the localization exact, so no cross-shard gather ever happens."""
    n = head_shards(pool_payload(k_pool).shape[1], q.shape[1])
    if paged_kv.dp_groups() > 1:
        from jax.experimental.shard_map import shard_map

        mesh, _, gsize = paged_kv.dp_state()
        dp = paged_kv.dp_axis()
        pos = jnp.broadcast_to(jnp.asarray(q_pos, jnp.int32).reshape(-1),
                               (q.shape[0],))
        qs = P(dp, tp_axis()) if n > 1 else P(dp)     # [B, H, T, D]
        ps = P(dp, tp_axis()) if n > 1 else P(dp)     # [NB, HKV, bs, D]
        rs = P(dp)                                    # [B, ...] row args

        def dp_body(q, kp, vp, bt, pos):
            bt = paged_kv.localize_block_tables(bt, gsize)
            return body(q, kp, vp, bt, pos)

        return shard_map(dp_body, mesh=mesh,
                         in_specs=(qs, ps, ps, rs, rs),
                         out_specs=qs, check_rep=False)(
            q, k_pool, v_pool,
            jnp.asarray(block_tables, jnp.int32), pos)
    if n <= 1:
        return body(q, k_pool, v_pool, block_tables, q_pos)
    pos = jnp.broadcast_to(jnp.asarray(q_pos, jnp.int32).reshape(-1),
                           (q.shape[0],))
    hs = P(None, tp_axis())
    return head_shard_map(body, (hs, hs, hs, P(), P()), hs)(
        q, k_pool, v_pool, jnp.asarray(block_tables, jnp.int32), pos)


def paged_decode_attention_reference(q, k_pool, v_pool, block_tables, q_pos,
                                     *, sm_scale: Optional[float] = None):
    """Gather-based paged attention (pure XLA): materialize each row's
    logical cache view through its block table, then run the contiguous
    reference path.  Serves prefill (T > 1) and the CPU decode path.

    q:            [B, H, T, D]
    k/v_pool:     [NB, HKV, block_size, D] shared pool
    block_tables: int32 [B, NBPER]
    q_pos:        scalar or int32 [B] — global position of q[:, :, 0]
    """
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])

    def body(q, kp, vp, bt, pos):
        # int8 records dequantize to the query dtype so downstream
        # residual math keeps the model's compute dtype (float pools
        # ignore the hint — reads stay bit-identical)
        k = _paged_gather(kp, bt, out_dtype=q.dtype)
        v = _paged_gather(vp, bt, out_dtype=q.dtype)
        return decode_attention_reference(q, k, v, pos, sm_scale=scale)

    return _tp_shard_heads(body, q, k_pool, v_pool, block_tables, q_pos)


def _paged_decode_kernel(pos_ref, bt_ref, q_ref, *refs, sm_scale: float,
                         block_size: int, quant: bool = False):
    """Grid: (B, HKV, NBPER), logical blocks innermost so scratch carries.

    ``pos_ref`` int32 [B] and ``bt_ref`` int32 [B, NBPER] arrive via scalar
    prefetch — the k/v BlockSpec index maps read ``bt_ref[b, i]`` so each
    grid step DMAs the row's *physical* block straight from the pool.  The
    paging indirection lives entirely in those index maps: the body is the
    contiguous kernel's online softmax unchanged (a logical block at grid
    step ``kb`` holds positions ``kb*block_size ..``, exactly like a
    contiguous chunk), including the ``pl.when`` skip of blocks past the
    row's valid prefix.

    ``quant``: the pool is int8 — two extra scale operands ([1, 1, bs]
    rows of the per-block scale table, same index maps) ride next to the
    code blocks and dequantize in-kernel, so HBM traffic is codes +
    scales only.
    """
    del bt_ref                       # consumed by the BlockSpec index maps
    if quant:
        k_ref, ks_ref, v_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = refs
    else:
        k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = refs
        ks_ref = vs_ref = None
    _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr,
                   acc_scr, sm_scale=sm_scale, block_k=block_size,
                   ks_ref=ks_ref, vs_ref=vs_ref)


def _paged_pool_operands(k_pool, v_pool, bs, d):
    """(operand list, BlockSpec list, quant flag) for a k/v pool pair —
    float pools contribute two operands, int8 records four (codes +
    per-block scale rows), all walking the same ``bt_ref[i, k]`` physical-
    block index map."""
    quant = is_quantized_pool(k_pool)
    blk = pl.BlockSpec((1, 1, bs, d),
                       lambda i, j, k, pos_ref, bt_ref: (bt_ref[i, k], j, 0, 0))
    if not quant:
        return [k_pool, v_pool], [blk, blk], False
    sblk = pl.BlockSpec((1, 1, bs),
                        lambda i, j, k, pos_ref, bt_ref: (bt_ref[i, k], j, 0))
    return ([k_pool["qp"], k_pool["ps"], v_pool["qp"], v_pool["ps"]],
            [blk, sblk, blk, sblk], True)


def _paged_decode_pallas(q, k_pool, v_pool, block_tables, q_pos, *,
                         sm_scale: float, interpret: bool):
    """Single-shard kernel launch of :func:`paged_decode_attention_pallas`
    (shapes may be the full head count or one tp shard's slice — the grid
    and GQA grouping are computed from the local arrays either way)."""
    b, h, t, d = q.shape
    nb, hkv, bs, _ = pool_payload(k_pool).shape
    rep = h // hkv
    nbper = block_tables.shape[1]
    scale = sm_scale

    qg = q[:, :, 0, :].reshape(b, hkv, rep, d)        # [B, HKV, rep, D]
    pos = jnp.broadcast_to(jnp.asarray(q_pos, jnp.int32).reshape(-1), (b,))
    bt = jnp.maximum(jnp.asarray(block_tables, jnp.int32), 0)
    pools, pool_specs, quant = _paged_pool_operands(k_pool, v_pool, bs, d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                        # pos, block table
        grid=(b, hkv, nbper),
        in_specs=[
            pl.BlockSpec((1, 1, rep, d),
                         lambda i, j, k, pos_ref, bt_ref: (i, j, 0, 0)),
        ] + pool_specs,
        out_specs=pl.BlockSpec((1, 1, rep, d),
                               lambda i, j, k, pos_ref, bt_ref: (i, j, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rep, LANES), jnp.float32),    # m
            pltpu.VMEM((rep, LANES), jnp.float32),    # l
            pltpu.VMEM((rep, d), jnp.float32),        # acc
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, sm_scale=scale,
                          block_size=bs, quant=quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, rep, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(pos, bt, qg, *pools)
    return out.reshape(b, h, 1, d)


def paged_decode_attention_pallas(q, k_pool, v_pool, block_tables, q_pos, *,
                                  sm_scale: Optional[float] = None,
                                  interpret: Optional[bool] = None):
    """Single-token paged decode: q [B, H, 1, D] against the block pool,
    walking each row's block table in-kernel via scalar prefetch.  Under a
    configured tp context each chip launches the kernel on its own head
    shard of q and the pool."""
    assert q.shape[2] == 1, \
        "pallas paged decode is single-token; use the XLA path"
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = _use_interpret()
    body = functools.partial(_paged_decode_pallas, sm_scale=scale,
                             interpret=interpret)
    return _tp_shard_heads(body, q, k_pool, v_pool, block_tables, q_pos)


def _paged_verify_kernel(pos_ref, bt_ref, q_ref, *refs, sm_scale: float,
                         block_size: int, t: int, quant: bool = False):
    """Multi-token (T = K+1 speculative verify window) variant of the paged
    decode kernel.  Grid: (B, HKV, NBPER), logical blocks innermost.

    q_ref: [1, 1, rep*T, D] — query row ``r*T + i`` is head ``r`` of this KV
    group at window offset ``i``, so its global position is ``base + i``
    with ``base = pos_ref[b]`` (the row's committed length — the verify
    window was just scattered at ``base .. base+T-1``).  The causal mask is
    per query ROW (``key <= base + row % T``): every verify query sees the
    row's history plus the window prefix up to itself, never the
    yet-unverified draft tail.  Blocks wholly past ``base + T - 1`` are
    skipped, so FLOPs track each row's own valid length.

    ``quant``: int8 pool — [1, 1, bs] scale rows ride next to the code
    blocks and fold into the score/prob columns exactly like the decode
    kernel (``_decode_kernel`` docstring).
    """
    del bt_ref                       # consumed by the BlockSpec index maps
    if quant:
        k_ref, ks_ref, v_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = refs
    else:
        k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = refs
        ks_ref = vs_ref = None
    kb = pl.program_id(2)
    nk = pl.num_programs(2)
    base = pos_ref[pl.program_id(0)]

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    start = kb * block_size

    @pl.when(start <= base + t - 1)  # skip blocks past the window's last row
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)           # [rep*T, D]
        k = k_ref[0, 0].astype(jnp.float32)           # [bk, D]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale                              # [rep*T, bk]
        if ks_ref is not None:
            s = s * ks_ref[...].reshape(1, -1).astype(jnp.float32)
        key_idx = start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        q_off = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) % t
        s = jnp.where(key_idx <= base + q_off, s, NEG_INF)

        m_prev = m_scr[...][:, :1]                    # [rep*T, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                        # [rep*T, bk]
        l_new = l_scr[...][:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[0, 0].astype(jnp.float32)           # [bk, D]
        pv = p * vs_ref[...].reshape(1, -1).astype(jnp.float32) \
            if vs_ref is not None else p
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            pv, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(kb == nk - 1)
    def _finish():
        l = l_scr[...][:, :1]
        o_ref[0, 0] = (acc_scr[...] /
                       jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


#: widest window the verify kernel takes; larger T (chunked prefill) uses
#: the gather-based reference path
VERIFY_T_MAX = 16


def _paged_verify_pallas(q, k_pool, v_pool, block_tables, q_pos, *,
                         sm_scale: float, interpret: bool):
    """Single-shard kernel launch of :func:`paged_verify_attention_pallas`
    (shapes may be the full head count or one tp shard's slice)."""
    b, h, t, d = q.shape
    nb, hkv, bs, _ = pool_payload(k_pool).shape
    rep = h // hkv
    nbper = block_tables.shape[1]
    scale = sm_scale

    # [B, H, T, D] -> [B, HKV, rep*T, D]: row r*T + i = (head r of the KV
    # group, window offset i) — matches the repeat-based GQA grouping
    qg = q.reshape(b, hkv, rep, t, d).reshape(b, hkv, rep * t, d)
    pos = jnp.broadcast_to(jnp.asarray(q_pos, jnp.int32).reshape(-1), (b,))
    bt = jnp.maximum(jnp.asarray(block_tables, jnp.int32), 0)
    pools, pool_specs, quant = _paged_pool_operands(k_pool, v_pool, bs, d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                        # pos, block table
        grid=(b, hkv, nbper),
        in_specs=[
            pl.BlockSpec((1, 1, rep * t, d),
                         lambda i, j, k, pos_ref, bt_ref: (i, j, 0, 0)),
        ] + pool_specs,
        out_specs=pl.BlockSpec((1, 1, rep * t, d),
                               lambda i, j, k, pos_ref, bt_ref: (i, j, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rep * t, LANES), jnp.float32),    # m
            pltpu.VMEM((rep * t, LANES), jnp.float32),    # l
            pltpu.VMEM((rep * t, d), jnp.float32),        # acc
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_verify_kernel, sm_scale=scale,
                          block_size=bs, t=t, quant=quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, rep * t, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(pos, bt, qg, *pools)
    return out.reshape(b, hkv, rep, t, d).reshape(b, h, t, d)


def paged_verify_attention_pallas(q, k_pool, v_pool, block_tables, q_pos, *,
                                  sm_scale: Optional[float] = None,
                                  interpret: Optional[bool] = None):
    """Speculative-verify paged attention: q [B, H, T, D] with T = K+1
    window positions per row, each row's window starting at its own
    ``q_pos[b]`` base (scalar q_pos broadcasts).  Same scalar-prefetch
    block-table walk as the single-token kernel; the T query rows ride in
    the row dim of one [rep*T, D] tile per (row, KV-head) grid step.
    Under a configured tp context each chip launches the kernel on its own
    head shard of q and the pool."""
    t = q.shape[2]
    assert 1 <= t <= VERIFY_T_MAX, \
        f"verify kernel takes windows up to {VERIFY_T_MAX}, got T={t}"
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = _use_interpret()
    body = functools.partial(_paged_verify_pallas, sm_scale=scale,
                             interpret=interpret)
    return _tp_shard_heads(body, q, k_pool, v_pool, block_tables, q_pos)


def paged_decode_attention(q, k_pool, v_pool, block_tables, q_pos, *,
                           sm_scale: Optional[float] = None):
    """Dispatch: block-table-walking Pallas kernels on TPU — single-token
    decode (T == 1) or the speculative K+1 verify window (T <=
    ``VERIFY_T_MAX``); gather + XLA reference otherwise (prefill chunks,
    CPU-sim).  A configured sp context (``ops/sp_attention``) routes
    prefill chunks through the Ulysses all-to-all path; a resident-window
    context forces the reference path, which carries the window mask."""
    if q.shape[2] > 1:
        from . import sp_attention

        if sp_attention.sp_shards(q.shape[1], pool_payload(k_pool).shape[1],
                                  q.shape[2]) > 1:
            return sp_attention.sp_prefill_attention(
                q, k_pool, v_pool, block_tables, q_pos, sm_scale=sm_scale)
    if jax.default_backend() == "tpu" and window_state() is None:
        if q.shape[2] == 1:
            return paged_decode_attention_pallas(
                q, k_pool, v_pool, block_tables, q_pos, sm_scale=sm_scale)
        if q.shape[2] <= VERIFY_T_MAX:
            return paged_verify_attention_pallas(
                q, k_pool, v_pool, block_tables, q_pos, sm_scale=sm_scale)
    return paged_decode_attention_reference(q, k_pool, v_pool, block_tables,
                                            q_pos, sm_scale=sm_scale)
