"""Fused INT8-weight matmul in Pallas — dequantization INSIDE the kernel.

Reference parity: the INT8 inference GEMMs of DS-Inference
(``csrc/transformer/inference/csrc/gelu.cu`` dequant epilogues and the
``GroupQuantizer`` weight path of ``module_inject/replace_module.py:152``):
the reference never materializes an fp16 copy of an int8 weight in HBM —
dequant happens in the GEMM's shared-memory staging.

TPU design: XLA cannot fuse an elementwise producer into a ``dot`` operand,
so the point-of-use ``dequantize() @`` pattern (models/gpt2.py
``_maybe_dequant``) round-trips a full bf16 copy of every weight through HBM
each decode step: int8 read + bf16 write + bf16 read = ~5 bytes/param where
the int8 payload is 1.  At bs=1 decode — pure HBM-bandwidth-bound matvecs —
that is the whole latency.  This kernel streams int8 blocks into VMEM,
expands ``q * scale`` on the VPU, and feeds the MXU directly: HBM traffic is
the int8 payload + scales (~1.06 bytes/param), a ~5x cut.

Weight record format is :mod:`deepspeed_tpu.ops.quantization`:
``{"q": int8 [K, N], "scale": f32 [K, N/G]}`` (groups along the LAST axis).
The operands are restructured to ``q [K, N/G, G]`` / ``scale [K, N/G, 1]``
outside the kernel so every in-kernel op is lane-legal; that requires
``G % 128 == 0`` (the serving default, inference/config.py).  Symmetric
records only; asymmetric ("zero"), non-tiling, or off-lane-group records
fall back to dequant+matmul.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams across releases; accept both
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


#: Trace-time gates set by the engine that owns the current trace (same
#: single-active-engine contract as model-config knobs, see
#: runtime/zero/liveness.py).  A pallas_call is opaque to the GSPMD
#: partitioner, so under tensor parallelism the WEIGHT-ONLY fused kernel is
#: disabled (``kernel_ok=False`` — its dequant+matmul fallback shards fine).
#: The W8A8 kernel instead goes through :func:`_w8a8_tp_call`, a
#: ``custom_partitioning`` wrapper that teaches the partitioner the two TP
#: layouts (``w8a8_tp=True``): column-parallel (N sharded — every shard runs
#: the s8 kernel on its weight slice, no communication) and row-parallel
#: (K sharded — local partial on the s8 kernel, one psum after).
#: decode-shaped row cap shared by w8a8_matmul / w8a8_matmul_stacked and
#: the models' indexed-decode gate (gpt2.use_indexed_decode)
W8A8_MAX_ROWS = 8


_KERNEL_OK = True
_W8A8_TP = False


def configure(kernel_ok: bool, w8a8_tp: bool = False) -> None:
    global _KERNEL_OK, _W8A8_TP
    _KERNEL_OK = bool(kernel_ok)
    _W8A8_TP = bool(w8a8_tp)


def _kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, nk: int):
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    bk, gpb, g = q_ref.shape
    # Decode is per-grid-STEP-overhead-bound, so steps must be large; VMEM
    # is bounded by the dequant intermediates, not the int8 block.  Hence:
    # big fetch blocks (bk x bn int8, the pipelining unit), dequantized in
    # small static sub-tiles via REF slicing (loading q_ref[...] whole
    # would put the full block in vector registers).  bf16 dequant: int8
    # values are exact in bf16 and the scale rounding (2^-8 rel) sits
    # below the int8 quantization error itself.  The [sub, gpb, g] ->
    # [sub, bn] merge is the lane-aligned reshape Mosaic supports (a
    # g < 128 split is an unsupported relayout; hence the G % 128
    # eligibility rule and the host-side 3D restructuring).
    _, b, sub = x_ref.shape                        # x_ref: [bk//sub, B, sub]

    def tile(t, _):
        qt = q_ref[pl.ds(t * sub, sub)]            # [sub, gpb, g] int8
        st = s_ref[pl.ds(t * sub, sub)]            # [sub, gpb, 1] f32
        w = (qt.astype(jnp.bfloat16) *
             st.astype(jnp.bfloat16)).reshape(sub, gpb * g)
        xt = x_ref[pl.ds(t, 1)].reshape(b, sub)    # major-dim slice of x
        acc_ref[...] += jax.lax.dot(xt.astype(jnp.bfloat16), w,
                                    preferred_element_type=jnp.float32)
        return _

    # rolled (static-trip) loop: one set of dequant intermediates is
    # reused across sub-tiles — unrolling kept them all live and blew the
    # scoped-VMEM stack (40MB at bk=2048); jax.lax.dynamic_slice on a
    # loaded VALUE is unimplemented in Mosaic, hence the [bk//sub, B, sub]
    # x staging that makes every sub-tile a major-dim REF slice
    jax.lax.fori_loop(0, bk // sub, tile, None)

    @pl.when(ki == nk - 1)
    def _done():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def _pick_block(dim: int, group: int, cap: int, quantum: int) -> int:
    """Largest divisor of ``dim`` that is <= cap, a multiple of ``quantum``
    and of ``group`` (so blocks span whole quant groups)."""
    step = quantum
    while step % group:
        step += quantum
    best = 0
    b = step
    while b <= min(dim, cap):
        if dim % b == 0:
            best = b
        b += step
    return best


@functools.partial(jax.jit, static_argnames=("out_dtype", "block_k",
                                             "block_n", "interpret"))
def _qmm_call(x2d, q3, scale3, out_dtype, block_k, block_n, interpret):
    b, k_dim = x2d.shape
    _, n_groups, g = q3.shape
    n_dim = n_groups * g
    gpb = block_n // g
    # sub-tile rows capped so the bf16 cast + product intermediates stay
    # ~4MB: sub * bn <= 1M elements
    sub = _pick_block(block_k, 1, max(8, (2 ** 20) // block_n), 8) or block_k
    grid = (n_dim // block_n, k_dim // block_k)
    x3 = x2d.reshape(b, k_dim // sub, sub).swapaxes(0, 1)
    return pl.pallas_call(
        functools.partial(_kernel, nk=grid[1]),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_k // sub, b, sub),
                         lambda n, ki: (ki, 0, 0)),
            pl.BlockSpec((block_k, gpb, g), lambda n, ki: (ki, n, 0)),
            pl.BlockSpec((block_k, gpb, 1), lambda n, ki: (ki, n, 0)),
        ],
        out_specs=pl.BlockSpec((b, block_n), lambda n, ki: (0, n)),
        out_shape=jax.ShapeDtypeStruct((b, n_dim), out_dtype),
        scratch_shapes=[pltpu.VMEM((b, block_n), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(x3, q3, scale3)


def quantized_matmul(x, rec: dict, out_dtype=None, *, block_k: int = None,
                     block_n: int = None, max_rows: int = 256):
    """``x @ dequant(rec)`` without materializing the dequantized weight.

    x: [..., K] float; rec: symmetric int8 record (see module docstring).

    OFF BY DEFAULT (``DS_QMM=1`` opts in): measured end-to-end on the v5e
    the fused path LOSES to XLA's dequantize+matmul at every model size
    (OPT-1.3B 68.5 vs 82.2 tok/s; OPT-6.7B 10.1 vs 12.1) — the in-kernel
    int8->bf16 convert is a cross-tiling relayout costing ~7us/MB, 6x the
    fetch+VPU theory, and XLA runs its 5-byte/param materializing pipeline
    at full HBM bandwidth (PROFILE.md round-4 second pass).  The kernel is
    kept as the scaffold for a true s8-MXU (W8A8) path, which avoids the
    relayout entirely.  Also falls back when the shapes don't tile, the
    record is asymmetric, or the row count exceeds the accumulator budget
    (long prefills are compute-bound and amortize the dequant copy).
    """
    from . import quantization as quant

    q, scale = rec["q"], rec["scale"]
    k_dim, n_dim = q.shape[-2], q.shape[-1]
    lead = x.shape[:-1]
    rows = 1
    for d in lead:
        rows *= d
    g = n_dim // scale.shape[-1]
    # FULL-row n blocks (bn = N): a single output block avoids the output
    # double-buffering that put mid-size bn configs over the scoped-VMEM
    # limit (bk=512/bn=4096 fails at 20.1M where bk=512/bn=8192 compiles),
    # and decode is per-grid-step-overhead-bound so steps should be as
    # large as VMEM allows anyway.  k blocks are sized by a per-step byte
    # budget (the double-buffered int8 fetch granularity).
    if block_n is None and "DS_QMM_BN" in os.environ:
        block_n = int(os.environ["DS_QMM_BN"])
    bn = n_dim if block_n is None else _pick_block(n_dim, g, block_n, 128)
    if block_k is None:
        step_bytes = int(float(os.environ.get("DS_QMM_STEP_MB", 1)) * 2**20)
        cap_k = max(1, step_bytes // max(bn, 1))
        block_k = cap_k
    bk = _pick_block(k_dim, 1, block_k, 256) or \
        _pick_block(k_dim, 1, block_k, 8)
    # the f32 accumulator + output block are [rows, bn]-sized: with
    # full-row bn the row budget shrinks accordingly (128 rows x 16384
    # lanes is an 8MB accumulator — prefill-sized inputs take the
    # dequant path, which amortizes its bf16 materialization over the
    # row count anyway)
    row_cap = min(max_rows, max(8, (2 ** 19) // max(bn, 1)))
    eligible = (
        _KERNEL_OK
        and os.environ.get("DS_QMM", "0") == "1"
        and q.ndim == 2
        and "zero" not in rec
        and rows <= row_cap
        and g % 128 == 0          # lane-aligned groups (see _kernel)
        and bk > 0 and bn >= g
    )
    if not eligible:
        return x @ quant.dequantize(rec, x.dtype)
    out_dtype = out_dtype or x.dtype
    x2d = x.reshape(rows, k_dim)
    out = _qmm_call(x2d, q.reshape(k_dim, n_dim // g, g),
                    scale.reshape(k_dim, n_dim // g, 1),
                    out_dtype, bk, bn, _use_interpret())
    return out.reshape(lead + (n_dim,))


# ------------------------------------------------------------------ W8A8 path
# True s8-MXU serving matmul: activations quantize per k-chunk IN-KERNEL,
# the dot runs int8 x int8 -> int32 natively, and (activation_scale x
# K-grouped weight scale) applies to the partial AFTER the dot — no
# int8->bf16 weight relayout anywhere (the cost that sank the weight-only
# fused kernel, see module docstring).  Records come from
# quantization.quantize_k_grouped; accuracy trades ~0.5-1% activation
# rounding for the bandwidth floor (reference analog: MoQ weight+activation
# INT8, deepspeed/compression/basic_layer.py QuantAct).


def _w8a8_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, nk: int,
                 k_group: int, stacked: bool = False):
    # ``stacked``: q_ref/s_ref carry a leading unit layer dim (the stacked
    # entry's scalar-prefetch index maps picked the layer; the body is
    # otherwise identical, so both paths share this one implementation).
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    bk = q_ref.shape[1] if stacked else q_ref.shape[0]
    _, b, sub = x_ref.shape                       # sub == k_group

    def tile(t, _):
        xt = x_ref[pl.ds(t, 1)].reshape(b, sub).astype(jnp.float32)
        ax = jnp.max(jnp.abs(xt), axis=1, keepdims=True)      # [B, 1]
        ax = jnp.where(ax == 0, 1.0, ax)
        xq = jnp.clip(jnp.round(xt * (127.0 / ax)),
                      -127, 127).astype(jnp.int8)
        if stacked:
            qt = q_ref[0, pl.ds(t * sub, sub)]                # [sub, bn] s8
            st = s_ref[0, pl.ds(t, 1)].reshape(1, -1)         # [1, bn] f32
        else:
            qt = q_ref[pl.ds(t * sub, sub)]                   # [sub, bn] s8
            st = s_ref[pl.ds(t, 1)].reshape(1, -1)            # [1, bn] f32
        part = jax.lax.dot(xq, qt,
                           preferred_element_type=jnp.int32)  # s8 MXU
        acc_ref[...] += part.astype(jnp.float32) * (ax / 127.0) * st
        return _

    jax.lax.fori_loop(0, bk // sub, tile, None)

    @pl.when(ki == nk - 1)
    def _done():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def _qmm_vmem_limit():
    """DS_QMM_VMEM_MB raises the w8a8 kernel's per-kernel scoped-vmem
    budget so larger DS_QMM_STEP_MB fetch blocks (2x double-buffered in
    VMEM) can compile for bandwidth experiments.  Resolved OUTSIDE the
    jitted call and passed as a static arg so it keys the jit cache —
    sweep scripts that change it mid-process get fresh compiles."""
    v = os.environ.get("DS_QMM_VMEM_MB")
    return int(float(v) * 2**20) if v else None


@functools.partial(jax.jit, static_argnames=("out_dtype", "block_k",
                                             "interpret", "vmem_limit"))
def _w8a8_call(x2d, qk, kscale, out_dtype, block_k, interpret,
               vmem_limit=None):
    b, k_dim = x2d.shape
    n_dim = qk.shape[1]
    k_group = k_dim // kscale.shape[0]
    grid = (1, k_dim // block_k)
    x3 = x2d.reshape(b, k_dim // k_group, k_group).swapaxes(0, 1)
    return pl.pallas_call(
        functools.partial(_w8a8_kernel, nk=grid[1], k_group=k_group),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_k // k_group, b, k_group),
                         lambda n, ki: (ki, 0, 0)),
            pl.BlockSpec((block_k, n_dim), lambda n, ki: (ki, 0)),
            pl.BlockSpec((block_k // k_group, 1, n_dim),
                         lambda n, ki: (ki, 0, 0)),
        ],
        out_specs=pl.BlockSpec((b, n_dim), lambda n, ki: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, n_dim), out_dtype),
        scratch_shapes=[pltpu.VMEM((b, n_dim), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=vmem_limit),
        interpret=interpret,
    )(x3, qk, kscale)


def _w8a8_pick_bk(k_dim, kg_blocks, n_dim, block_k):
    """Shared W8A8 block sizing: returns ``(bk, k_group)`` with ``bk == 0``
    when the shape cannot tile (the per-layer and stacked entries must stay
    eligible under IDENTICAL conditions)."""
    k_group = k_dim // kg_blocks if kg_blocks else 0
    if not (k_group and k_dim % kg_blocks == 0):
        return 0, k_group
    if block_k is None:
        step_bytes = int(float(os.environ.get("DS_QMM_STEP_MB", 4)) * 2**20)
        block_k = max(1, step_bytes // max(n_dim, 1))
    return _pick_block(k_dim, k_group, block_k, k_group), k_group


def _w8a8_local(x2d, qk, kscale3, block_k=None, out_dtype=None):
    """One shard's worth of the W8A8 matmul: the s8-MXU kernel when the
    LOCAL shapes tile (lane-aligned N, whole k-groups), exact dequant+matmul
    otherwise.  Correct for any shapes, so the custom_partitioning lowering
    below can call it on whatever slice the partitioner hands each device.
    ``out_dtype`` keeps row-parallel partials in f32 so the cross-shard psum
    adds no rounding the unsharded kernel doesn't have."""
    from . import quantization as quant

    out_dtype = out_dtype or x2d.dtype
    k_dim, n_dim = qk.shape
    bk, _ = _w8a8_pick_bk(k_dim, kscale3.shape[0], n_dim, block_k)
    if (bk > 0 and n_dim % 128 == 0
            and os.environ.get("DS_W8A8", "1") != "0"):
        return _w8a8_call(x2d, qk, kscale3, out_dtype, bk, _use_interpret(),
                          vmem_limit=_qmm_vmem_limit())
    deq = quant.dequantize_k({"qk": qk, "kscale": kscale3}, x2d.dtype)
    return jax.lax.dot(x2d, deq, preferred_element_type=out_dtype)


from ..utils.sharding import axis_size  # noqa: E402  (shared helper)


def _qk_spec(arg_shapes):
    spec = getattr(arg_shapes[1].sharding, "spec", None)
    spec = tuple(spec) if spec is not None else ()
    return (spec + (None, None))[:2]


def _b_spec(arg_shapes, *exclude):
    """Batch-dim sharding of the x operand, dropped when it collides with
    an axis the weight layout already uses."""
    spec = getattr(arg_shapes[0].sharding, "spec", None)
    b_s = tuple(spec)[0] if spec else None
    return None if b_s in exclude else b_s


def _w8a8_infer_sharding(mesh, arg_shapes, result_shape):
    from jax.sharding import NamedSharding, PartitionSpec as P

    _, n_s = _qk_spec(arg_shapes)
    return NamedSharding(mesh, P(_b_spec(arg_shapes, n_s), n_s))


def _w8a8_partition(mesh, arg_shapes, result_shape):
    from jax.sharding import NamedSharding, PartitionSpec as P

    k_s, n_s = _qk_spec(arg_shapes)
    k_dim, n_dim = arg_shapes[1].shape
    kg_blocks = arg_shapes[2].shape[0]
    rep = NamedSharding(mesh, P())
    # both lowerings return f32: the single value-rounding cast to the
    # caller's out_dtype happens OUTSIDE the custom_partitioning call
    # (w8a8_matmul), so tp=N matches the unsharded kernel's
    # accumulate-in-f32-round-once numerics for every out_dtype.  The
    # batch dim keeps the x operand's sharding (dp serving: each replica
    # computes only its batch slice).
    # Column shards never split quant groups (scales are per-column), so any
    # even N split is exact — shards whose local N is off-lane just run the
    # sharded dequant+dot inside _w8a8_local; K shards must keep whole
    # k-groups or the record's chunking misaligns (gather + warn below)
    if n_s is not None and n_dim % axis_size(mesh, n_s) == 0:
        b_s = _b_spec(arg_shapes, n_s)
        arg_sh = (NamedSharding(mesh, P(b_s, None)),
                  NamedSharding(mesh, P(None, n_s)),
                  NamedSharding(mesh, P(None, None, n_s)))
        return (mesh, _w8a8_tp_body,
                NamedSharding(mesh, P(b_s, n_s)), arg_sh)
    if k_s is not None and kg_blocks % axis_size(mesh, k_s) == 0:
        b_s = _b_spec(arg_shapes, k_s)
        arg_sh = (NamedSharding(mesh, P(b_s, k_s)),
                  NamedSharding(mesh, P(k_s, None)),
                  NamedSharding(mesh, P(k_s, None, None)))

        def lower(x2d, qk, kscale3):
            # f32 partials: each shard rounds once AFTER the full local K
            # reduction, and the psum itself runs in f32 — matching the
            # unsharded kernel's single-rounding accumulation
            part = _w8a8_local(x2d, qk, kscale3, out_dtype=jnp.float32)
            return jax.lax.psum(part, k_s)

        return mesh, lower, NamedSharding(mesh, P(b_s, None)), arg_sh
    if k_s is not None or n_s is not None:
        # an aligned sharding was suggested but the shard slices would split
        # k-groups: correctness demands a gathered lowering.  This defeats
        # the TP memory goal for THIS weight, so say so (once per shape —
        # the partition callback refires on every retrace) instead of
        # silently eating the gather.
        from ..utils.logging import warning_once

        warning_once(
            f"w8a8 weight [{k_dim}, {n_dim}] (K/G={kg_blocks}) cannot "
            f"shard over spec ({k_s}, {n_s}) without splitting quant "
            f"groups — this matmul runs GATHERED on every device; pick a "
            f"k_group-aligned tp degree to keep it sharded")
    b_s = _b_spec(arg_shapes, k_s, n_s)
    return (mesh, _w8a8_tp_body, NamedSharding(mesh, P(b_s, None)),
            (NamedSharding(mesh, P(b_s, None)), rep, rep))


from jax.experimental.custom_partitioning import custom_partitioning  # noqa: E402

def _w8a8_tp_body(x2d, qk, kscale3):
    # 3-arg body for custom_partitioning: the wrapper derives its operand
    # arity from the signature, so _w8a8_local's block_k/out_dtype knobs
    # must not leak into it.  Always f32 out — every lowering (replicated,
    # column, row+psum) then carries the full accumulator precision and
    # w8a8_matmul's single outer cast supplies the caller's out_dtype,
    # keeping tp=N bit-compatible with the unsharded kernel's
    # round-once-from-f32 result for out_dtypes wider than x.dtype.
    return _w8a8_local(x2d, qk, kscale3, out_dtype=jnp.float32)


#: GSPMD/shardy-aware entry: same math as :func:`_w8a8_local`, but the
#: partitioner is told how to run it sharded instead of gathering the weight
#: (which would defeat TP serving).  The k factor is declared a reduction so
#: shardy's propagation knows a K-sharded weight still yields a full [B, N]
#: result; the partition lowering owns the actual psum.
_w8a8_tp_call = custom_partitioning(_w8a8_tp_body)
try:
    _w8a8_tp_call.def_partition(
        partition=_w8a8_partition,
        infer_sharding_from_operands=_w8a8_infer_sharding,
        propagate_user_sharding=lambda mesh, user_shape: user_shape.sharding,
        sharding_rule="b k, k n, s u n -> b n",
        reduction_factors=("k", "s"),
        need_replication_factors=("u",),
    )
except TypeError:
    # older jax: def_partition predates the shardy sharding_rule kwargs —
    # GSPMD propagation alone still gets the sharded lowering right
    _w8a8_tp_call.def_partition(
        partition=_w8a8_partition,
        infer_sharding_from_operands=_w8a8_infer_sharding,
        propagate_user_sharding=lambda mesh, user_shape: user_shape.sharding,
    )


def w8a8_matmul(x, rec: dict, out_dtype=None, *, block_k: int = None,
                max_rows: int = W8A8_MAX_ROWS):
    """``x @ dequant_k(rec)`` on the s8 MXU with in-kernel activation
    quantization.  Decode-shaped inputs only (``rows <= max_rows``); other
    shapes — and ``DS_W8A8=0`` — fall back to dequantize+matmul (prefill
    is compute-bound and amortizes the bf16 copy; its activations stay
    unquantized there, which is also the more accurate choice for the
    prompt pass)."""
    from . import quantization as quant

    qk, kscale = rec["qk"], rec["kscale"]
    k_dim, n_dim = qk.shape[-2], qk.shape[-1]
    k_group = k_dim // kscale.shape[-3]
    lead = x.shape[:-1]
    rows = 1
    for d in lead:
        rows *= d
    if (qk.ndim == 2 and rows <= max_rows
            and os.environ.get("DS_W8A8", "1") != "0"):
        x2d = x.reshape(rows, k_dim)
        kscale3 = kscale.reshape(k_dim // k_group, 1, n_dim)
        if _W8A8_TP:
            # tensor-parallel serving: the custom_partitioning wrapper
            # keeps the weight sharded (column: per-shard s8 kernel, no
            # comm; row: f32 local partial + psum); per-shard kernel
            # ineligibility degrades to a SHARDED dequant+matmul.  Only a
            # sharding that would split quant groups forces a gathered
            # lowering (warned in _w8a8_partition).  Block sizing is
            # per-shard inside the lowering; ``block_k`` is not threaded.
            out = _w8a8_tp_call(x2d, qk, kscale3)
        elif _KERNEL_OK:
            out = _w8a8_local(x2d, qk, kscale3, block_k=block_k,
                              out_dtype=out_dtype)
        else:
            return x @ quant.dequantize_k(rec, x.dtype)
        return out.astype(out_dtype or x.dtype).reshape(lead + (n_dim,))
    return x @ quant.dequantize_k(rec, x.dtype)


# ------------------------------------------------- stacked (indexed) W8A8
# A scan/fori_loop over stacked per-layer weights hands the kernel a
# dynamic-slice of the [L, K, N] stack; XLA cannot fuse that slice into a
# custom call, so it materializes a per-layer int8 COPY in HBM every decode
# step — read + write + read where the payload is one read.  The stacked
# kernel instead takes the WHOLE stack plus the layer index as a
# scalar-prefetch operand: the BlockSpec index maps add the layer offset
# and every weight block is DMA'd straight from the resident stack.


def _w8a8_stacked_kernel(idx_ref, x_ref, q_ref, s_ref, o_ref, acc_ref, *,
                         nk: int, k_group: int):
    del idx_ref  # consumed by the index maps; kernel body sees the blocks
    _w8a8_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, nk=nk,
                 k_group=k_group, stacked=True)


@functools.partial(jax.jit, static_argnames=("out_dtype", "block_k",
                                             "interpret", "vmem_limit"))
def _w8a8_stacked_call(idx, x2d, qks, kscales, out_dtype, block_k,
                       interpret, vmem_limit=None):
    b, k_dim = x2d.shape
    n_layers, _, n_dim = qks.shape
    k_group = k_dim // kscales.shape[1]
    grid = (1, k_dim // block_k)
    x3 = x2d.reshape(b, k_dim // k_group, k_group).swapaxes(0, 1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_k // k_group, b, k_group),
                         lambda n, ki, idx_ref: (ki, 0, 0)),
            pl.BlockSpec((1, block_k, n_dim),
                         lambda n, ki, idx_ref: (idx_ref[0], ki, 0)),
            pl.BlockSpec((1, block_k // k_group, 1, n_dim),
                         lambda n, ki, idx_ref: (idx_ref[0], ki, 0, 0)),
        ],
        out_specs=pl.BlockSpec((b, n_dim), lambda n, ki, idx_ref: (0, 0)),
        scratch_shapes=[pltpu.VMEM((b, n_dim), jnp.float32)],
    )
    return pl.pallas_call(
        functools.partial(_w8a8_stacked_kernel, nk=grid[1], k_group=k_group),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n_dim), out_dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=vmem_limit),
        interpret=interpret,
    )(jnp.asarray(idx, jnp.int32).reshape(1), x3, qks, kscales)


def stacked_kernel_enabled() -> bool:
    """True when :func:`w8a8_matmul_stacked` would actually select the layer
    in-kernel.  Models gate their layer-indexed decode loop on this: under
    TP, or with the kernel disabled, the indexed loop would pay the
    per-layer dynamic-slice cost the scan path pays WITHOUT the stacked
    kernel's benefit (plus extra KV-stack slice/update traffic)."""
    return (_KERNEL_OK and not _W8A8_TP
            and os.environ.get("DS_W8A8", "1") != "0")


def w8a8_matmul_stacked(x, rec: dict, layer_idx, out_dtype=None, *,
                        block_k: int = None, max_rows: int = W8A8_MAX_ROWS):
    """``x @ dequant_k(rec[layer_idx])`` on the s8 MXU, selecting the layer
    INSIDE the kernel (scalar-prefetch index) so no per-layer weight copy
    is ever materialized.  ``rec`` holds stacked records (``qk [L, K, N]``,
    ``kscale [L, K/G, 1, N]``); ``layer_idx`` may be a traced scalar (a
    ``fori_loop`` induction variable).  Shapes the kernel cannot tile fall
    back to dequantizing the sliced layer — the same cost the scan path
    always pays."""
    from . import quantization as quant

    qk, kscale = rec["qk"], rec["kscale"]
    assert qk.ndim == 3, "stacked records only; use w8a8_matmul for 2D"
    k_dim, n_dim = qk.shape[-2], qk.shape[-1]
    lead = x.shape[:-1]
    rows = 1
    for d in lead:
        rows *= d
    bk, _ = _w8a8_pick_bk(k_dim, kscale.shape[-3], n_dim, block_k)
    eligible = (rows <= max_rows and bk > 0 and n_dim % 128 == 0
                and stacked_kernel_enabled())
    if eligible:
        x2d = x.reshape(rows, k_dim)
        out = _w8a8_stacked_call(layer_idx, x2d, qk, kscale,
                                 out_dtype or x.dtype, bk,
                                 _use_interpret(),
                                 vmem_limit=_qmm_vmem_limit())
        return out.reshape(lead + (n_dim,))
    layer = {
        "qk": jax.lax.dynamic_index_in_dim(qk, layer_idx, keepdims=False),
        "kscale": jax.lax.dynamic_index_in_dim(kscale, layer_idx,
                                               keepdims=False),
    }
    return w8a8_matmul(x, layer, out_dtype=out_dtype, block_k=block_k,
                       max_rows=max_rows)
