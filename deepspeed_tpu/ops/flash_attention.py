"""Fused (flash) attention in Pallas — TPU replacement for the reference's CUDA
attention kernels (training: ``csrc/transformer/softmax_kernels.cu`` +
``strided_batch_gemm`` composition, ``ds_transformer_cuda.cpp:78-121``).

Flash-2 style: online-softmax forward that never materialises the [S, S] score
matrix (the thing that OOMed GPT-2 125M on a 16GB v5e), and a recomputing
backward driven by saved row log-sum-exps.  Causal blocks strictly above the
diagonal are skipped with ``pl.when`` — ~2x fewer MXU flops for causal LM.

Layout: q, k, v are [B, H, S, D]; the grid walks (B*H, Sq/bq, Sk/bk) with the KV
dimension innermost ("arbitrary") so the accumulator scratch carries across KV
blocks.  f32 accumulation regardless of input dtype (bf16 in, bf16 out).

Interpret mode (CPU testing) is selected automatically off the backend.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams across releases; accept both
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)
LANES = 128


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(*refs, sm_scale: float, causal: bool, block_q: int,
                block_k: int, kv_len: int, num_k_blocks: int,
                has_layout: bool = False):
    if has_layout:
        (q_ref, k_ref, v_ref, layout_ref, o_ref, lse_ref,
         m_scr, l_scr, acc_scr) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: block (qi, ki) contributes iff some col <= some row;
    # a sparsity layout gates blocks on top (ops/sparse_attention)
    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True
    if has_layout:
        # per-head layout slice in SMEM (a (1,1,1) VMEM block would violate
        # Mosaic's (8,128) tiling floor — surfaced on hardware only; a
        # whole-array SMEM operand would hit scalar-memory limits at
        # H x (S/block)^2 scale)
        run = jnp.logical_and(run, layout_ref[0, qi, ki] != 0)

    @pl.when(run)
    def _compute():
        # keep native (bf16) dtype into the MXU; f32 comes out via
        # preferred_element_type — f32 MXU inputs run at 1/8 rate on v5e
        q = q_ref[0, ...]  # [bq, d]
        k = k_ref[0, ...]  # [bk, d]
        v = v_ref[0, ...]  # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale

        col = ki * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                      (block_q, block_k), 1)
        mask = col < kv_len  # padded keys never attend
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, row >= col)
        s = jnp.where(mask, s, DEFAULT_MASK_VALUE)

        m_prev = m_scr[...][:, :1]                      # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)       # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)                 # [bq, 1]
        p = jnp.exp(s - m_new)                          # [bq, bk]
        l_prev = l_scr[...][:, :1]
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = l_scr[...][:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, ...] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        lse = m_scr[...][:, :1] + jnp.log(l_safe)
        lse_ref[0, ...] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _fwd(q, k, v, sm_scale: float, causal: bool, block_q: int, block_k: int,
         interpret: bool, true_kv_len: int, head_rep: int = 1, layout=None):
    """``head_rep``: GQA ratio — q has ``bh`` leading entries, k/v have
    ``bh // head_rep``; the KV index map divides so repeated heads read the
    same KV block in place (no ``jnp.repeat`` materialization).
    ``layout``: optional f32 [H, nq, nk] block-sparsity gate."""
    bh, q_len, d = q.shape
    kv_len = true_kv_len  # mask out padded keys beyond the real length
    nq = pl.cdiv(q_len, block_q)
    nk = pl.cdiv(kv_len, block_k)
    rep = head_rep

    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                               block_q=block_q, block_k=block_k, kv_len=kv_len,
                               num_k_blocks=nk, has_layout=layout is not None)
    out_shape = [
        jax.ShapeDtypeStruct((bh, q_len, d), q.dtype),          # o
        jax.ShapeDtypeStruct((bh, q_len, LANES), jnp.float32),  # lse (lane-bcast)
    ]
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // rep, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // rep, j, 0)),
    ]
    inputs = [q, k, v]
    if layout is not None:
        h, lq, lk = layout.shape
        in_specs.append(pl.BlockSpec((1, lq, lk), lambda b, i, j: (b % h, 0, 0),
                                     memory_space=pltpu.SMEM))
        inputs.append(layout.astype(jnp.int32))
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda b, i, j: (b, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),  # m
            pltpu.VMEM((block_q, LANES), jnp.float32),  # l
            pltpu.VMEM((block_q, d), jnp.float32),      # acc
        ],
        out_shape=out_shape,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*inputs)
    return o, lse[:, :, 0]


# ---------------------------------------------------------------------------
# backward: dq kernel (grid kv-innermost) and dkv kernel (grid q-innermost)
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(*refs, sm_scale: float, causal: bool, block_q: int,
                   block_k: int, kv_len: int, num_k_blocks: int,
                   has_layout: bool = False):
    if has_layout:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, layout_ref,
         dq_ref, dq_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
         dq_scr) = refs
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True
    if has_layout:
        # per-head layout slice in SMEM (a (1,1,1) VMEM block would violate
        # Mosaic's (8,128) tiling floor — surfaced on hardware only; a
        # whole-array SMEM operand would hit scalar-memory limits at
        # H x (S/block)^2 scale)
        run = jnp.logical_and(run, layout_ref[0, qi, ki] != 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0, ...]
        k = k_ref[0, ...]
        v = v_ref[0, ...]
        do = do_ref[0, ...]
        lse = lse_ref[0, ...][:, :1]      # [bq, 1]
        delta = delta_ref[0, ...][:, :1]  # [bq, 1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        col = ki * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                      (block_q, block_k), 1)
        mask = col < kv_len
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, row >= col)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        dq_ref[0, ...] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, sm_scale: float, causal: bool,
                    block_q: int, block_k: int, kv_len: int, num_q_blocks: int,
                    rep: int = 1, has_layout: bool = False):
    """Inner grid dim 2 runs over (head_rep, q_blocks) flattened: for GQA the
    dk/dv of one KV head accumulates contributions from all ``rep`` query
    heads without materializing repeated K/V."""
    if has_layout:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, layout_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
         dk_scr, dv_scr) = refs
    ki = pl.program_id(1)
    inner = pl.program_id(2)
    qi = inner % num_q_blocks

    @pl.when(inner == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True
    if has_layout:
        # per-head layout slice in SMEM (a (1,1,1) VMEM block would violate
        # Mosaic's (8,128) tiling floor — surfaced on hardware only; a
        # whole-array SMEM operand would hit scalar-memory limits at
        # H x (S/block)^2 scale)
        run = jnp.logical_and(run, layout_ref[0, qi, ki] != 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0, ...]
        k = k_ref[0, ...]
        v = v_ref[0, ...]
        do = do_ref[0, ...]
        lse = lse_ref[0, ...][:, :1]
        delta = delta_ref[0, ...][:, :1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        col = ki * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                      (block_q, block_k), 1)
        mask = col < kv_len
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, row >= col)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)                 # [bq, bk]
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale                           # [bq, bk]
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(inner == rep * num_q_blocks - 1)
    def _finalize():
        dk_ref[0, ...] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, ...] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_dq_call(q, k, v, do, lse_b, delta_b, *, sm_scale, causal, block_q,
                 block_k, kv_len, interpret, head_rep: int = 1, layout=None):
    """dq for one (q-chunk, kv-chunk) pair given *global* lse/delta.

    Exposed separately so ring attention (parallel/sequence.py) can reuse the
    kernel per ring step with the globally-merged log-sum-exp.
    """
    bh, q_len, d = q.shape
    nq = pl.cdiv(q_len, block_q)
    nk = pl.cdiv(kv_len, block_k)
    rep = head_rep
    dq_kernel = functools.partial(_bwd_dq_kernel, sm_scale=sm_scale,
                                  causal=causal, block_q=block_q,
                                  block_k=block_k, kv_len=kv_len,
                                  num_k_blocks=nk,
                                  has_layout=layout is not None)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // rep, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // rep, j, 0)),
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, LANES), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, LANES), lambda b, i, j: (b, i, 0)),
    ]
    inputs = [q, k, v, do, lse_b, delta_b]
    if layout is not None:
        h, lq, lk = layout.shape
        in_specs.append(pl.BlockSpec((1, lq, lk), lambda b, i, j: (b % h, 0, 0),
                                     memory_space=pltpu.SMEM))
        inputs.append(layout.astype(jnp.int32))
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*inputs)
    return dq


def _bwd_dkv_call(q, k, v, do, lse_b, delta_b, *, sm_scale, causal, block_q,
                  block_k, kv_len, interpret, head_rep: int = 1, layout=None):
    """dk, dv for one (q-chunk, kv-chunk) pair given *global* lse/delta.

    For GQA (``head_rep > 1``) q/do/lse/delta have ``rep`` times more heads
    than k/v; the inner grid walks (rep, q_blocks) and accumulates into the
    single KV head's dk/dv."""
    bh_kv = k.shape[0]
    q_len, d = q.shape[1], q.shape[2]
    rep = head_rep
    nq = pl.cdiv(q_len, block_q)
    nk = pl.cdiv(kv_len, block_k)
    dkv_kernel = functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale,
                                   causal=causal, block_q=block_q,
                                   block_k=block_k, kv_len=kv_len,
                                   num_q_blocks=nq, rep=rep,
                                   has_layout=layout is not None)
    q_map = lambda b, j, i: (b * rep + i // nq, i % nq, 0)
    in_specs = [
        pl.BlockSpec((1, block_q, d), q_map),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_q, d), q_map),
        pl.BlockSpec((1, block_q, LANES), q_map),
        pl.BlockSpec((1, block_q, LANES), q_map),
    ]
    if layout is not None:
        # the layout is per Q-head: follow the q index map through the
        # (rep, q_blocks) inner grid so GQA composes with sparsity
        h, lq, lk = layout.shape
        in_specs.append(pl.BlockSpec(
            (1, lq, lk),
            lambda b, j, i: ((b * rep + i // nq) % h, 0, 0),
            memory_space=pltpu.SMEM))
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh_kv, nk, rep * nq),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*([q, k, v, do, lse_b, delta_b] +
        ([layout.astype(jnp.int32)] if layout is not None else [])))
    return dk, dv


def _bwd(sm_scale, causal, block_q, block_k, interpret, true_kv_len, head_rep,
         residuals, g):
    q, k, v, o, lse = residuals
    do = g
    kv_len = true_kv_len

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    lse_b = jnp.broadcast_to(lse[..., None], lse.shape + (LANES,))
    delta_b = jnp.broadcast_to(delta[..., None], delta.shape + (LANES,))

    kw = dict(sm_scale=sm_scale, causal=causal, block_q=block_q,
              block_k=block_k, kv_len=kv_len, interpret=interpret,
              head_rep=head_rep)
    dq = _bwd_dq_call(q, k, v, do, lse_b, delta_b, **kw)
    dk, dv = _bwd_dkv_call(q, k, v, do, lse_b, delta_b, **kw)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# v2 kernels: full-row forward + ONE fused backward (dq+dk+dv in one pass)
#
# Profiling showed the v1 kernels are VPU/overhead-bound, not MXU-bound: the
# online-softmax rescale machinery, the separate dq/dkv backward kernels that
# EACH recompute the score matrix (9 S^2-matmuls where 6 suffice), and the
# [bh, S, LANES]-broadcast lse/delta operands (200MB of f32 HBM traffic per
# layer at bench shapes) dominate.  When the whole K/V sequence fits VMEM
# (S <= _V2_MAX_KV), a single-row-block design removes all of it:
#  - forward: one [bq, S] score pass, plain softmax (no cross-block rescale),
#    exp2 with the softmax scale folded into q, OUTPUT IS O ONLY — the
#    backward recomputes row max/sum in-kernel, so no lse is ever written.
#  - backward: one kernel computes dq (written once per q block) and
#    accumulates dk/dv in VMEM scratch over the sequential q-block grid
#    dimension; delta = rowsum(do*o) is computed in-kernel and 1/l is folded
#    into do, so no [bq, S] divide and no broadcast operands exist.
# Long sequences (kv_pad > _V2_MAX_KV) take the v3 kernels below; the v1
# kernels remain for the sparse-layout path, the ring-attention building
# blocks (parallel/sequence.py), and the DS_FLASH_V2/V3=0 kill switches.
# ---------------------------------------------------------------------------
_LOG2E = math.log2(math.e)
_LN2 = math.log(2.0)
# v2's fused backward at kv_pad=2048 measured 348KB OVER the 16MB
# scoped-vmem limit on v5e at EVERY block_q (round-4 compile failures; the
# round-3 on-chip fuzz only reached S=512 so the former 2048 limit was
# never hardware-validated).  kv_pad <= 1024 compiles and is the measured
# winner at bench shapes; v3 takes over beyond.
_V2_MAX_KV = 1024
# defensive cap on the [block_q, kv_pad] f32 score intermediates
_V2_MAX_SCORE_ELEMS = 2 ** 20


def _v2_block_q(block_q: int, kv_pad: int) -> int:
    # cap only — never RAISE block_q (it may legitimately be the whole
    # padded q length for short sequences)
    return max(8, min(block_q, _V2_MAX_SCORE_ELEMS // max(kv_pad, 1)))


def _v2_eligible(kv_pad: int, d: int) -> bool:
    import os

    if os.environ.get("DS_FLASH_V2", "1") == "0":  # A/B kill switch
        return False
    # experiment override: a larger scoped-vmem budget (set via
    # --xla_tpu_scoped_vmem_limit_kib) can admit the fused v2 backward past
    # 1024 — DS_V2_MAX_KV raises the gate for A/B runs
    max_kv = int(os.environ.get("DS_V2_MAX_KV", _V2_MAX_KV))
    return kv_pad <= max_kv and kv_pad % 8 == 0 and d <= 256


def _v3_eligible(kv_pad: int, d: int) -> bool:
    """v3 kernels (chunked-grid, compact row stats): the long-sequence path.

    Measured on v5e (round 4, PROFILE.md): ~8-10% faster than the v1
    two-kernel path at S in [2048, 8192] — fwd folds the softmax scale and
    log2(e) into q and uses exp2; bwd reads lse/delta as compact 8-sublane
    operands instead of v1's [bh, S, 128]-broadcast f32 arrays (~200MB of
    HBM traffic per layer at bench shapes).  A fused one-kernel backward and
    a resident-KV chunk-loop variant were both probed and lost (fused: VMEM
    cliff at S=8192 + slower at 4096; see PROFILE.md round-4 notes).
    """
    import os

    if os.environ.get("DS_FLASH_V3", "1") == "0":  # A/B kill switch
        return False
    min_kv = int(os.environ.get("DS_FLASH_V3_MIN_KV", _V2_MAX_KV + 1))
    return kv_pad >= min_kv and kv_pad % 8 == 0 and d <= 256


def _v2_compiler_params(dimension_semantics):
    """CompilerParams for the v2 kernels; ``DS_V2_VMEM_MB`` raises the
    per-kernel scoped-vmem budget (the fused v2 backward at kv_pad=2048
    needs ~16.4MB against the 16MB default — see _V2_MAX_KV note)."""
    import os

    vmem_mb = os.environ.get("DS_V2_VMEM_MB")
    return _CompilerParams(
        dimension_semantics=dimension_semantics,
        vmem_limit_bytes=(int(float(vmem_mb) * 2**20) if vmem_mb else None))


def _fwd_v2_kernel(q_ref, k_ref, v_ref, o_ref, *, scale2: float, causal: bool,
                   block_q: int, kv_pad: int, kv_len: int):
    qi = pl.program_id(1)
    # fold softmax scale AND log2(e) into q (one [bq, d] pass instead of a
    # [bq, S] one); exp2 is the native transcendental
    q = q_ref[0, ...]
    qs = (q.astype(jnp.float32) * scale2).astype(q.dtype)
    k = k_ref[0, ...]
    v = v_ref[0, ...]
    s2 = jax.lax.dot_general(qs, k, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)  # [bq, S]
    col = jax.lax.broadcasted_iota(jnp.int32, (block_q, kv_pad), 1)
    if causal:
        row = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, kv_pad), 0)
        mask = col <= row
        if kv_len != kv_pad:
            mask = jnp.logical_and(mask, col < kv_len)
        s2 = jnp.where(mask, s2, DEFAULT_MASK_VALUE)
    elif kv_len != kv_pad:
        s2 = jnp.where(col < kv_len, s2, DEFAULT_MASK_VALUE)
    m = jnp.max(s2, axis=1, keepdims=True)          # [bq, 1]
    p = jnp.exp2(s2 - m)                            # masked lanes -> 0
    l = jnp.sum(p, axis=1, keepdims=True)           # >= 1 for any valid row
    acc = jax.lax.dot_general(p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    o_ref[0, ...] = (acc / l).astype(o_ref.dtype)


def _fwd_v2(q, k, v, sm_scale, causal, block_q, interpret, true_kv_len,
            head_rep):
    bh, q_len, d = q.shape
    kv_pad = k.shape[1]
    nq = pl.cdiv(q_len, block_q)
    kernel = functools.partial(
        _fwd_v2_kernel, scale2=sm_scale * _LOG2E, causal=causal,
        block_q=block_q, kv_pad=kv_pad, kv_len=true_kv_len)
    rep = head_rep
    return pl.pallas_call(
        kernel,
        grid=(bh, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, kv_pad, d), lambda b, i: (b // rep, 0, 0)),
            pl.BlockSpec((1, kv_pad, d), lambda b, i: (b // rep, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, q_len, d), q.dtype),
        compiler_params=_v2_compiler_params(("parallel", "parallel")),
        interpret=interpret,
    )(q, k, v)


def _bwd_v2_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, dq_ref, dk_ref, dv_ref,
                   dk_scr, dv_scr, *, scale2: float, sm_scale: float,
                   causal: bool, block_q: int, kv_pad: int, kv_len: int,
                   num_q_blocks: int, rep: int):
    inner = pl.program_id(1)
    qi = inner % num_q_blocks

    @pl.when(inner == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    q = q_ref[0, ...]
    qs = (q.astype(jnp.float32) * scale2).astype(q.dtype)
    k = k_ref[0, ...]
    v = v_ref[0, ...]
    o = o_ref[0, ...]
    do = do_ref[0, ...]

    s2 = jax.lax.dot_general(qs, k, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)  # [bq, S]
    col = jax.lax.broadcasted_iota(jnp.int32, (block_q, kv_pad), 1)
    if causal:
        row = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, kv_pad), 0)
        mask = col <= row
        if kv_len != kv_pad:
            mask = jnp.logical_and(mask, col < kv_len)
        s2 = jnp.where(mask, s2, DEFAULT_MASK_VALUE)
    elif kv_len != kv_pad:
        s2 = jnp.where(col < kv_len, s2, DEFAULT_MASK_VALUE)
    m = jnp.max(s2, axis=1, keepdims=True)
    p0 = jnp.exp2(s2 - m)                           # l * softmax(s)
    l = jnp.sum(p0, axis=1, keepdims=True)
    linv = 1.0 / l                                  # [bq, 1]

    do32 = do.astype(jnp.float32)
    delta_s = jnp.sum(do32 * o.astype(jnp.float32), axis=1,
                      keepdims=True) * linv         # delta / l, [bq, 1]
    do_s = (do32 * linv).astype(do.dtype)           # do / l (folded softmax div)
    # dp/l = (do/l) @ v^T ; ds = softmax*(dp-delta) = p0*(dp - delta)/l
    dp_s = jax.lax.dot_general(do_s, v, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)
    ds = p0 * (dp_s - delta_s)
    ds_b = ds.astype(q.dtype)
    # dv += softmax^T @ do = p0^T @ (do/l)
    dv_scr[...] += jax.lax.dot_general(
        p0.astype(do.dtype), do_s, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    # dk_true = ds^T @ (sm_scale*q) = (ds^T @ qs) * ln2   (qs = q*scale*log2e)
    dk_scr[...] += jax.lax.dot_general(
        ds_b, qs, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    # dq = sm_scale * (ds @ k)
    dq = jax.lax.dot_general(ds_b, k, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dq_ref[0, ...] = (dq * sm_scale).astype(dq_ref.dtype)

    @pl.when(inner == rep * num_q_blocks - 1)
    def _finalize():
        dk_ref[0, ...] = (dk_scr[...] * _LN2).astype(dk_ref.dtype)
        dv_ref[0, ...] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_v2(q, k, v, o, do, sm_scale, causal, block_q, interpret, true_kv_len,
            head_rep):
    bh, q_len, d = q.shape
    bh_kv, kv_pad, _ = k.shape
    nq = pl.cdiv(q_len, block_q)
    rep = head_rep
    kernel = functools.partial(
        _bwd_v2_kernel, scale2=sm_scale * _LOG2E, sm_scale=sm_scale,
        causal=causal, block_q=block_q, kv_pad=kv_pad, kv_len=true_kv_len,
        num_q_blocks=nq, rep=rep)
    q_map = lambda b, i: (b * rep + i // nq, i % nq, 0)
    kv_map = lambda b, i: (b, 0, 0)
    dq, dk, dv = pl.pallas_call(
        kernel,
        grid=(bh_kv, rep * nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_map),       # q
            pl.BlockSpec((1, kv_pad, d), kv_map),       # k
            pl.BlockSpec((1, kv_pad, d), kv_map),       # v
            pl.BlockSpec((1, block_q, d), q_map),       # o
            pl.BlockSpec((1, block_q, d), q_map),       # do
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, kv_pad, d), kv_map),
            pl.BlockSpec((1, kv_pad, d), kv_map),
        ],
        scratch_shapes=[
            pltpu.VMEM((kv_pad, d), jnp.float32),
            pltpu.VMEM((kv_pad, d), jnp.float32),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        compiler_params=_v2_compiler_params(("parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, o, do)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# v3 kernels: the long-sequence path (kv_pad > _V2_MAX_KV).
#
# Same chunked-grid structure as v1 (scratch-carried online softmax in the
# forward; separate dq / dkv backward kernels) but with the v2 tricks that
# carry over to chunking, each A/B-measured on-chip (PROFILE.md round 4):
#  - softmax scale AND log2(e) folded into q once per block ([bq, d] pass
#    instead of a [bq, bk] f32 multiply per chunk); exp2 everywhere.
#  - row stats live in COMPACT [bh, 1, S] f32 arrays.  The forward writes
#    lse2 = m2 + log2(l) via a sublane->lane relayout at finalize; the
#    backward reads it back with the reverse relayout (measured free) and
#    reconstructs true probabilities p = exp2(s2 - lse2) directly — no
#    division, no [bh, S, LANES] broadcast operands (v1 ships ~200MB/layer
#    of those at bench shapes).
#  - delta = rowsum(do * o) is one fused XLA pass, also [bh, 1, S].
# Rejected by measurement (do NOT revisit without new evidence):
#  - fused one-kernel backward (dk/dv full-row scratch + resident KV):
#    VMEM cliff at S=8192 (compile failure) and slower at 2048/4096.
#  - dq-partials-summed-by-XLA fused variant: partial-write traffic costs
#    more than the two matmuls it saves.
#  - masked/unmasked chunk-body forking: no measurable win.
# Reference parity: csrc/transformer/ds_transformer_cuda.cpp:78-121 claims
# fused-kernel supremacy at its benchmark shapes; this path is what makes
# the S=4096-8192 driver configs run on the measured-best kernels.
# ---------------------------------------------------------------------------


def _fwd_v3_kernel(*refs, scale2: float, causal: bool, block_q: int,
                   block_k: int, kv_pad: int, kv_len: int, num_k_blocks: int):
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0, ...]
        qs = (q.astype(jnp.float32) * scale2).astype(q.dtype)
        k = k_ref[0, ...]
        v = v_ref[0, ...]
        s2 = jax.lax.dot_general(qs, k, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        col = ki * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                      (block_q, block_k), 1)
        mask = col < kv_len
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, row >= col)
        s2 = jnp.where(mask, s2, DEFAULT_MASK_VALUE)
        m_prev = m_scr[...][:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s2, axis=1, keepdims=True))
        alpha = jnp.exp2(m_prev - m_new)
        p = jnp.exp2(s2 - m_new)
        l_scr[...] = jnp.broadcast_to(
            alpha * l_scr[...][:, :1] + jnp.sum(p, axis=1, keepdims=True),
            l_scr.shape)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = l_scr[...][:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, ...] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        lse2 = m_scr[...][:, :1] + jnp.log2(l_safe)   # exp2-domain lse
        lse_ref[0, ...] = lse2.reshape(1, block_q)    # sublane -> lane


def _fwd_v3(q, k, v, sm_scale, causal, block_q, block_k, interpret,
            true_kv_len, head_rep):
    bh, q_len, d = q.shape
    kv_pad = k.shape[1]
    nq = pl.cdiv(q_len, block_q)
    nk = pl.cdiv(kv_pad, block_k)
    rep = head_rep
    kernel = functools.partial(
        _fwd_v3_kernel, scale2=sm_scale * _LOG2E, causal=causal,
        block_q=block_q, block_k=block_k, kv_pad=kv_pad, kv_len=true_kv_len,
        num_k_blocks=nk)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // rep, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // rep, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),  # m
            pltpu.VMEM((block_q, LANES), jnp.float32),  # l
            pltpu.VMEM((block_q, d), jnp.float32),      # acc
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, q_len, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, q_len), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return o, lse


def _bwd_v3_dq_kernel(*refs, scale2: float, sm_scale: float, causal: bool,
                      block_q: int, block_k: int, kv_len: int,
                      num_k_blocks: int):
    (q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dq_ref, dq_scr) = refs
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0, ...]
        k = k_ref[0, ...]
        v = v_ref[0, ...]
        do = do_ref[0, ...]
        qs = (q.astype(jnp.float32) * scale2).astype(q.dtype)
        lse2 = lse_ref[0, ...].reshape(block_q, 1)   # lane -> sublane
        delta = dl_ref[0, ...].reshape(block_q, 1)
        s2 = jax.lax.dot_general(qs, k, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        col = ki * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                      (block_q, block_k), 1)
        mask = col < kv_len
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, row >= col)
        s2 = jnp.where(mask, s2, DEFAULT_MASK_VALUE)
        p = jnp.exp2(s2 - lse2)                      # true softmax probs
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(k.dtype)
        dq_scr[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        dq_ref[0, ...] = (dq_scr[...] * sm_scale).astype(dq_ref.dtype)


def _bwd_v3_dkv_kernel(*refs, scale2: float, causal: bool, block_q: int,
                       block_k: int, kv_len: int, num_q_blocks: int,
                       rep: int):
    (q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dk_ref, dv_ref,
     dk_scr, dv_scr) = refs
    ki = pl.program_id(1)
    inner = pl.program_id(2)
    qi = inner % num_q_blocks

    @pl.when(inner == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0, ...]
        k = k_ref[0, ...]
        v = v_ref[0, ...]
        do = do_ref[0, ...]
        qs = (q.astype(jnp.float32) * scale2).astype(q.dtype)
        lse2 = lse_ref[0, ...].reshape(block_q, 1)
        delta = dl_ref[0, ...].reshape(block_q, 1)
        s2 = jax.lax.dot_general(qs, k, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        col = ki * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                      (block_q, block_k), 1)
        mask = col < kv_len
        if causal:
            row = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, row >= col)
        s2 = jnp.where(mask, s2, DEFAULT_MASK_VALUE)
        p = jnp.exp2(s2 - lse2)
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q.dtype)
        # dk_true = sm_scale * ds^T @ q = (ds^T @ qs) * ln2
        dk_scr[...] += jax.lax.dot_general(
            ds, qs, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(inner == rep * num_q_blocks - 1)
    def _finalize():
        dk_ref[0, ...] = (dk_scr[...] * _LN2).astype(dk_ref.dtype)
        dv_ref[0, ...] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_v3(q, k, v, o, lse, do, sm_scale, causal, block_q, block_k,
            interpret, true_kv_len, head_rep):
    bh, q_len, d = q.shape
    bh_kv, kv_pad, _ = k.shape
    nq = pl.cdiv(q_len, block_q)
    nk = pl.cdiv(kv_pad, block_k)
    rep = head_rep
    scale2 = sm_scale * _LOG2E
    # delta = rowsum(do * o): one fused XLA pass, compact [bh, 1, S] layout
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, None, :]

    dq_kernel = functools.partial(
        _bwd_v3_dq_kernel, scale2=scale2, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_len=true_kv_len,
        num_k_blocks=nk)
    qspec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    kspec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b // rep, j, 0))
    lspec = pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i))
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, nq, nk),
        in_specs=[qspec, kspec, kspec, qspec, lspec, lspec],
        out_specs=qspec,
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dkv_kernel = functools.partial(
        _bwd_v3_dkv_kernel, scale2=scale2, causal=causal, block_q=block_q,
        block_k=block_k, kv_len=true_kv_len, num_q_blocks=nq, rep=rep)
    q_map = lambda b, j, i: (b * rep + i // nq, i % nq, 0)
    l_map = lambda b, j, i: (b * rep + i // nq, 0, i % nq)
    qspec2 = pl.BlockSpec((1, block_q, d), q_map)
    kspec2 = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0))
    lspec2 = pl.BlockSpec((1, 1, block_q), l_map)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh_kv, nk, rep * nq),
        in_specs=[qspec2, kspec2, kspec2, qspec2, lspec2, lspec2],
        out_specs=[kspec2, kspec2],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_attention_bh(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                        true_kv_len, head_rep):
    if _v2_eligible(k.shape[1], q.shape[2]):
        return _fwd_v2(q, k, v, sm_scale, causal,
                       _v2_block_q(block_q, k.shape[1]), interpret,
                       true_kv_len, head_rep)
    if _v3_eligible(k.shape[1], q.shape[2]):
        o, _ = _fwd_v3(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                       true_kv_len, head_rep)
        return o
    o, _ = _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                true_kv_len, head_rep)
    return o


def _flash_fwd_rule(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                    true_kv_len, head_rep):
    from jax.ad_checkpoint import checkpoint_name

    if _v2_eligible(k.shape[1], q.shape[2]):
        o = _fwd_v2(q, k, v, sm_scale, causal,
                    _v2_block_q(block_q, k.shape[1]), interpret,
                    true_kv_len, head_rep)
        # no lse residual: the fused backward recomputes row stats in-kernel
        o = checkpoint_name(o, "flash_out")
        return o, (q, k, v, o)
    if _v3_eligible(k.shape[1], q.shape[2]):
        o, lse = _fwd_v3(q, k, v, sm_scale, causal, block_q, block_k,
                         interpret, true_kv_len, head_rep)
        o = checkpoint_name(o, "flash_out")
        lse = checkpoint_name(lse, "flash_lse")
        return o, (q, k, v, o, lse)
    o, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                  true_kv_len, head_rep)
    # named so remat policies can pin the kernel's residuals: saving o+lse
    # means the backward under jax.checkpoint reuses them instead of
    # re-running the forward kernel (see gpt2._remat_policy)
    o = checkpoint_name(o, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(sm_scale, causal, block_q, block_k, interpret, true_kv_len,
                    head_rep, res, g):
    if len(res) == 4:  # v2 path (see _flash_fwd_rule)
        q, k, v, o = res
        return _bwd_v2(q, k, v, o, g, sm_scale, causal,
                       _v2_block_q(block_q, k.shape[1]), interpret,
                       true_kv_len, head_rep)
    if res[4].ndim == 3:  # v3 path: compact [bh, 1, S] exp2-domain lse
        q, k, v, o, lse = res
        return _bwd_v3(q, k, v, o, lse, g, sm_scale, causal, block_q,
                       block_k, interpret, true_kv_len, head_rep)
    return _bwd(sm_scale, causal, block_q, block_k, interpret, true_kv_len,
                head_rep, res, g)


_flash_attention_bh.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _sparse_attention_bh(q, k, v, layout, sm_scale, causal, block_q, block_k,
                         interpret, head_rep=1):
    o, _ = _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                k.shape[1], head_rep, layout)
    return o


def _sparse_fwd_rule(q, k, v, layout, sm_scale, causal, block_q, block_k,
                     interpret, head_rep=1):
    o, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                  k.shape[1], head_rep, layout)
    return o, (q, k, v, layout, o, lse)


def _sparse_bwd_rule(sm_scale, causal, block_q, block_k, interpret, head_rep,
                     res, g):
    q, k, v, layout, o, lse = res
    kv_len = k.shape[1]
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    lse_b = jnp.broadcast_to(lse[..., None], lse.shape + (LANES,))
    delta_b = jnp.broadcast_to(delta[..., None], delta.shape + (LANES,))
    kw = dict(sm_scale=sm_scale, causal=causal, block_q=block_q,
              block_k=block_k, kv_len=kv_len, interpret=interpret,
              head_rep=head_rep, layout=layout)
    dq = _bwd_dq_call(q, k, v, g, lse_b, delta_b, **kw)
    dk, dv = _bwd_dkv_call(q, k, v, g, lse_b, delta_b, **kw)
    return dq, dk, dv, jnp.zeros_like(layout)


_sparse_attention_bh.defvjp(_sparse_fwd_rule, _sparse_bwd_rule)


def flash_attention(q, k, v, causal: bool = True,
                    sm_scale: Optional[float] = None, block_q: int = 128,
                    block_k: int = 128, interpret: Optional[bool] = None):
    """Fused attention. q: [B, H, Sq, D]; k, v: [B, Hkv, Sk, D] (GQA: Hkv | H).

    Returns [B, H, Sq, D] in q's dtype.  Sequence lengths are padded internally
    to the block size; padded keys are masked, padded query rows sliced off.
    """
    if interpret is None:
        interpret = _use_interpret()
    b, h, q_len, d = q.shape
    hkv = k.shape[1]
    if hkv != h:
        assert h % hkv == 0, f"GQA needs num_heads {h} % kv_heads {hkv} == 0"
    rep = h // hkv  # repeated heads read KV blocks in place via the index map
    kv_len = k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)

    block_q = min(block_q, max(q_len, 1))
    block_k = min(block_k, max(kv_len, 1))
    pad_q = (-q_len) % block_q
    pad_k = (-kv_len) % block_k
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0))) if pad_q else q
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0))) if pad_k else k
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0))) if pad_k else v

    qf = qp.reshape(b * h, q_len + pad_q, d)
    kf = kp.reshape(b * hkv, kv_len + pad_k, d)
    vf = vp.reshape(b * hkv, kv_len + pad_k, d)
    # kv_len for masking must be the real length: padded keys get masked out
    o = _flash_attention_bh(qf, kf, vf, sm_scale, causal, block_q, block_k,
                            interpret, kv_len, rep)
    o = o.reshape(b, h, q_len + pad_q, d)
    if pad_q:
        o = o[:, :, :q_len, :]
    return o


def mha_reference(q, k, v, causal: bool = True,
                  sm_scale: Optional[float] = None):
    """Plain einsum attention (the thing the kernel replaces); used by tests."""
    b, h, sq, d = q.shape
    if k.shape[1] != h:
        rep = h // k.shape[1]
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        mask = jnp.tril(jnp.ones((sq, k.shape[2]), bool))
        s = jnp.where(mask[None, None], s, DEFAULT_MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)
