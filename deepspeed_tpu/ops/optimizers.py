"""Optimizer ops.

TPU-native replacements for the reference's native optimizer kernels:

 - ``FusedAdam``  (reference ``csrc/adam/multi_tensor_adam.cu`` + ``ops/adam/fused_adam.py``)
 - ``DeepSpeedCPUAdam`` (reference ``csrc/adam/cpu_adam.cpp`` — AVX-vectorized host Adam)
 - ``FusedLamb``  (reference ``csrc/lamb/fused_lamb_cuda.cpp``)
 - Adagrad / SGD / Lion

On TPU the fused multi-tensor-apply pattern is unnecessary: the optimizer update is
part of the jitted train step, and XLA fuses the elementwise update chains across
the whole (flat, sharded) state — the same thing ``multi_tensor_apply`` hand-rolls
with CUDA kernel launches.  Each factory returns an ``optax.GradientTransformation``
so updates compose with clipping/accumulation, and hyperparameters keep the
reference names (betas/eps/weight_decay/bias_correction/adam_w_mode).

The *CPU* Adam variant (for ZeRO-Offload-style host stepping of offloaded
partitions) lives in ``deepspeed_tpu/ops/cpu_adam.py`` with a C++ SIMD backend.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import optax

ScalarOrSchedule = Union[float, Callable]


def fused_adam(lr: ScalarOrSchedule = 1e-3, betas: Tuple[float, float] = (0.9, 0.999),
               eps: float = 1e-8, weight_decay: float = 0.0,
               bias_correction: bool = True, adam_w_mode: bool = True,
               amsgrad: bool = False) -> optax.GradientTransformation:
    """Adam/AdamW over the sharded flat state (reference FusedAdam semantics)."""
    if amsgrad:
        raise ValueError("FusedAdam does not support amsgrad (matches reference)")
    b1, b2 = betas
    chain = [optax.scale_by_adam(b1=b1, b2=b2, eps=eps)]
    if not bias_correction:
        # optax applies bias correction unconditionally; cancel it when disabled
        chain.append(_undo_bias_correction(b1, b2))
    if weight_decay:
        if adam_w_mode:
            chain.append(optax.add_decayed_weights(weight_decay))
        else:
            # classic Adam applies L2 before the moments; approximate by adding
            # decay to the update (reference keeps both modes; adam_w is default)
            chain.insert(0, optax.add_decayed_weights(weight_decay))
    chain.append(_scale_by_learning_rate(lr))
    return optax.chain(*chain)


def _scale_by_learning_rate(lr: ScalarOrSchedule) -> optax.GradientTransformation:
    if callable(lr):
        return optax.scale_by_schedule(lambda step: -lr(step))
    return optax.scale(-lr)


def _undo_bias_correction(b1: float, b2: float) -> optax.GradientTransformation:
    def init_fn(params):
        return optax.ScaleByScheduleState(count=jnp.zeros([], jnp.int32))

    def update_fn(updates, state, params=None):
        count = state.count + 1
        c1 = 1 - b1**count.astype(jnp.float32)
        c2 = 1 - b2**count.astype(jnp.float32)
        factor = c1 / jnp.sqrt(c2)
        updates = jax.tree_util.tree_map(lambda u: u * factor, updates)
        return updates, optax.ScaleByScheduleState(count=count)

    return optax.GradientTransformation(init_fn, update_fn)


def scale_by_clamped_trust_ratio(
        min_coeff: float = 0.01,
        max_coeff: float = 0.3) -> optax.GradientTransformation:
    """``optax.scale_by_trust_ratio`` with the reference LAMB kernel's
    coefficient clamp (``fused_lamb_cuda_kernel.cu``): per-leaf trust ratio
    ``||p|| / ||u||`` clamped to ``[min_coeff, max_coeff]``; a zero param
    or update norm keeps the kernel's neutral ratio of 1 (unclamped — there
    is nothing to trust-scale)."""
    if not 0.0 < min_coeff <= max_coeff:
        raise ValueError(
            f"need 0 < min_coeff <= max_coeff, got [{min_coeff}, "
            f"{max_coeff}]")

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("scale_by_clamped_trust_ratio needs params")

        def scale(u, p):
            p_norm = jnp.linalg.norm(p.astype(jnp.float32))
            u_norm = jnp.linalg.norm(u.astype(jnp.float32))
            ratio = jnp.clip(p_norm / jnp.where(u_norm == 0.0, 1.0, u_norm),
                             min_coeff, max_coeff)
            ratio = jnp.where((p_norm == 0.0) | (u_norm == 0.0), 1.0, ratio)
            return (u * ratio.astype(u.dtype)).astype(u.dtype)

        return jax.tree_util.tree_map(scale, updates, params), state

    return optax.GradientTransformation(init_fn, update_fn)


def fused_lamb(lr: ScalarOrSchedule = 1e-3, betas: Tuple[float, float] = (0.9, 0.999),
               eps: float = 1e-8, weight_decay: float = 0.0,
               min_coeff: float = 0.01,
               max_coeff: float = 0.3) -> optax.GradientTransformation:
    """LAMB with the reference's trust-ratio clamp (``fused_lamb_cuda_kernel.cu``
    clamps the coefficient to [min_coeff, max_coeff])."""
    b1, b2 = betas
    return optax.chain(
        optax.scale_by_adam(b1=b1, b2=b2, eps=eps),
        optax.add_decayed_weights(weight_decay),
        scale_by_clamped_trust_ratio(min_coeff, max_coeff),
        _scale_by_learning_rate(lr))


def adagrad(lr: ScalarOrSchedule = 1e-2, eps: float = 1e-10,
            weight_decay: float = 0.0) -> optax.GradientTransformation:
    """Adagrad (reference ``csrc/adagrad/cpu_adagrad.cpp`` semantics)."""
    tx = optax.adagrad(learning_rate=lr, eps=eps)
    if weight_decay:
        tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
    return tx


def sgd(lr: ScalarOrSchedule = 1e-3, momentum: float = 0.0,
        weight_decay: float = 0.0, nesterov: bool = False
        ) -> optax.GradientTransformation:
    tx = optax.sgd(learning_rate=lr, momentum=momentum or None, nesterov=nesterov)
    if weight_decay:
        tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
    return tx


def lion(lr: ScalarOrSchedule = 1e-4, betas: Tuple[float, float] = (0.9, 0.99),
         weight_decay: float = 0.0) -> optax.GradientTransformation:
    return optax.lion(learning_rate=lr, b1=betas[0], b2=betas[1],
                      weight_decay=weight_decay)


def get_optimizer(name: str, params: dict,
                  lr_schedule: Optional[Callable] = None
                  ) -> optax.GradientTransformation:
    """Map reference optimizer names/params to transformations
    (analog of ``DeepSpeedEngine._configure_basic_optimizer``, engine.py:1307)."""
    name = name.lower()
    params = dict(params)
    params.pop("torch_adam", None)
    params.pop("fused", None)
    lr = lr_schedule if lr_schedule is not None else params.pop("lr", 1e-3)
    if lr_schedule is not None:
        params.pop("lr", None)
    if name in ("adam", "adamw", "fusedadam"):
        # reference: "adam" defaults to adam_w_mode=True unless explicitly
        # disabled (engine.py:1307 region); "adamw" is always decoupled decay
        adam_w_mode = True if name == "adamw" else \
            bool(params.pop("adam_w_mode", True))
        return fused_adam(lr=lr, betas=tuple(params.pop("betas", (0.9, 0.999))),
                          eps=params.pop("eps", 1e-8),
                          weight_decay=params.pop("weight_decay", 0.0),
                          bias_correction=params.pop("bias_correction", True),
                          adam_w_mode=adam_w_mode)
    if name in ("lamb", "fusedlamb"):
        return fused_lamb(lr=lr, betas=tuple(params.pop("betas", (0.9, 0.999))),
                          eps=params.pop("eps", 1e-8),
                          weight_decay=params.pop("weight_decay", 0.0),
                          min_coeff=params.pop("min_coeff", 0.01),
                          max_coeff=params.pop("max_coeff", 0.3))
    if name == "sgd":
        return sgd(lr=lr, momentum=params.pop("momentum", 0.0),
                   weight_decay=params.pop("weight_decay", 0.0),
                   nesterov=params.pop("nesterov", False))
    if name == "adagrad":
        return adagrad(lr=lr, eps=params.pop("eps", 1e-10),
                       weight_decay=params.pop("weight_decay", 0.0))
    if name == "lion":
        return lion(lr=lr, betas=tuple(params.pop("betas", (0.9, 0.99))),
                    weight_decay=params.pop("weight_decay", 0.0))
    if name in ("onebitadam", "onebitlamb", "zerooneadam"):
        # 1-bit optimizers (reference runtime/fp16/onebit/*)
        from ..runtime.fp16.onebit import (onebit_adam, onebit_lamb,
                                           zero_one_adam)

        common = dict(lr=lr, betas=tuple(params.pop("betas", (0.9, 0.999))),
                      eps=params.pop("eps", 1e-8),
                      weight_decay=params.pop("weight_decay", 0.0))
        if name == "onebitadam":
            return onebit_adam(freeze_step=params.pop("freeze_step", 100),
                               **common)
        if name == "onebitlamb":
            return onebit_lamb(freeze_step=params.pop("freeze_step", 100),
                               min_coeff=params.pop("min_coeff", 0.01),
                               max_coeff=params.pop("max_coeff", 0.3),
                               **common)
        return zero_one_adam(
            var_freeze_step=params.pop("var_freeze_step", 100),
            var_update_scaler=params.pop("var_update_scaler", 16), **common)
    raise ValueError(f"unknown optimizer {name!r}")
