"""On-device sampling primitives for the serving engine (PR 20).

Everything here is shaped so the scheduler can thread *per-slot* sampling
state through the compiled decode/fused/verify programs as fixed-shape
operands — ``[slots]`` knob vectors, ``[slots]`` counter vectors, and an
optional ``[slots, vocab]`` logit mask — with **zero recompiles**: a slot
changing temperature, or a mixed greedy+sampled batch, only changes
operand *values*, never program shapes.

Greedy is the ``temperature == 0`` row of the SAME program:
:func:`filtered_logprobs` returns the exact one-hot (``0 / -inf``)
distribution at the (masked) argmax for those rows, so every downstream
draw — :func:`sample_tokens`, the rejection-sampler accept test, the
residual fallback — degenerates bit-exactly to argmax without a single
branch in the traced program.

**Counter-based PRNG.** Each request owns one integer ``seed``; the key
for any draw is ``fold_in(fold_in(PRNGKey(seed), salt), position)`` where
``position`` is the absolute emitted-token index.  Keys are therefore a
pure function of ``(seed, salt, position)`` — no mutable RNG state lives
anywhere — which is what makes crash re-homing and preemption replay
token-exact: the salvage path only needs to carry the request seed and
the emitted count (``docs/inference.md`` "Sampled decoding").  The salts
separate the independent sub-streams one emission position consumes:

========  ===========================================================
TOKEN     plain next-token draws (decode / fused decode / the prefill
          first-token emit)
ACCEPT    the rejection sampler's accept uniforms (verify program)
RESIDUAL  residual + bonus categorical draws (verify fallback row)
DRAFT     draft-model rollout draws (speculative propose program)
========  ===========================================================
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "SALT_TOKEN", "SALT_ACCEPT", "SALT_RESIDUAL", "SALT_DRAFT",
    "slot_keys", "grid_keys", "filtered_logprobs", "sample_tokens",
    "accept_uniforms", "token_probs", "residual_logits",
]

SALT_TOKEN = 1
SALT_ACCEPT = 2
SALT_RESIDUAL = 3
SALT_DRAFT = 4


def slot_keys(seeds, counts, salt):
    """``[rows]`` PRNG keys: ``fold_in(fold_in(PRNGKey(seed), salt),
    count)`` per row — the whole counter-based scheme in one place."""
    def one(seed, count):
        key = jax.random.PRNGKey(seed)
        return jax.random.fold_in(jax.random.fold_in(key, salt), count)

    return jax.vmap(one)(jnp.asarray(seeds, jnp.uint32),
                         jnp.asarray(counts, jnp.int32))


def grid_keys(seeds, counts, salt, width):
    """``[rows, width]`` keys for window draws: row ``s`` position ``i``
    keys the emission index ``counts[s] + i`` (the verify/rollout window
    grid — position ``i`` of the window IS absolute count ``c + i``)."""
    offs = jnp.arange(int(width), dtype=jnp.int32)[None, :]
    cnts = (jnp.asarray(counts, jnp.int32)[:, None] + offs).reshape(-1)
    seeds2 = jnp.repeat(jnp.asarray(seeds, jnp.uint32), int(width))
    flat = slot_keys(seeds2, cnts, salt)
    return flat.reshape((-1, int(width)) + flat.shape[1:])


def filtered_logprobs(logits, temps, top_k, top_p, masks=None):
    """Per-row log-probs of the filtered sampling distribution.

    ``[rows, vocab]`` logits + ``[rows]`` knobs -> ``(greedy [rows] i32,
    logprobs [rows, vocab] f32)``.  The pipeline per row: apply the bool
    logit mask (``-inf`` outside it; an all-False row is treated as
    unmasked rather than poisoning the softmax), scale by ``1/temp``,
    keep the top-k by kth-largest threshold (``top_k == 0`` = off; ties
    at the threshold stay in), then nucleus-filter at ``top_p`` over the
    renormalized top-k distribution (``top_p == 1`` = off; the token
    that crosses the boundary stays in).  Rows with ``temps == 0``
    return the exact one-hot (``0 / -inf``) at the masked argmax —
    the greedy row of the same traced program."""
    logits = logits.astype(jnp.float32)
    vocab = logits.shape[-1]
    if masks is not None:
        ok = jnp.any(masks, axis=-1, keepdims=True)
        logits = jnp.where(jnp.where(ok, masks, True), logits, -jnp.inf)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    temps = jnp.asarray(temps, jnp.float32)[:, None]
    scaled = logits / jnp.maximum(temps, 1e-6)
    srt = jnp.sort(scaled, axis=-1)[:, ::-1]
    k = jnp.asarray(top_k, jnp.int32)
    kidx = jnp.clip(jnp.where(k > 0, k, vocab) - 1, 0, vocab - 1)
    kth = jnp.take_along_axis(srt, kidx[:, None], axis=-1)
    keep = scaled >= kth
    probs = jax.nn.softmax(jnp.where(keep, scaled, -jnp.inf), axis=-1)
    psort = jnp.sort(probs, axis=-1)[:, ::-1]
    before = jnp.cumsum(psort, axis=-1) - psort
    p = jnp.asarray(top_p, jnp.float32)[:, None]
    thr = jnp.min(jnp.where(before < p, psort, jnp.inf),
                  axis=-1, keepdims=True)
    keep = keep & (probs >= thr)
    logprobs = jax.nn.log_softmax(jnp.where(keep, scaled, -jnp.inf),
                                  axis=-1)
    onehot = jnp.where(
        jnp.arange(vocab)[None, :] == greedy[:, None], 0.0, -jnp.inf)
    return greedy, jnp.where(temps > 0, logprobs, onehot)


def sample_tokens(logprobs, keys):
    """One categorical draw per row (``[rows, vocab]`` log-probs +
    ``[rows]`` keys -> ``[rows]`` i32).  One-hot rows (greedy /
    degenerate residual) come out deterministic — the single finite
    entry wins every Gumbel race."""
    return jax.vmap(jax.random.categorical)(keys, logprobs) \
        .astype(jnp.int32)


def accept_uniforms(keys):
    """One ``U[0, 1)`` per key (any leading shape).  ``u < p(token)``
    against a one-hot row is exact: ``p`` is exactly 1.0 or 0.0, so the
    test never depends on ``u`` for greedy rows."""
    flat = keys.reshape((-1,) + keys.shape[-1:])
    u = jax.vmap(lambda k: jax.random.uniform(k))(flat)
    return u.reshape(keys.shape[:-1])


def token_probs(logprobs, tokens):
    """``p(token)`` per row under the filtered distribution (``exp`` of
    the gathered log-prob: exactly 1.0 / 0.0 on one-hot rows)."""
    lp = jnp.take_along_axis(
        logprobs, tokens.astype(jnp.int32)[:, None], axis=-1)[:, 0]
    return jnp.exp(lp)


def residual_logits(logprobs, tokens):
    """Rejection-sampler residual per row: the filtered distribution
    with the rejected ``token`` removed (categorical renormalizes, so
    raw ``-inf``-masked log-probs suffice).  A row with nothing left
    (a temp=0 row whose draft WAS the argmax — only reachable when the
    accept test already passed) falls back to the one-hot argmax so the
    unused lane stays NaN-free."""
    vocab = logprobs.shape[-1]
    idx = jnp.arange(vocab)[None, :]
    resid = jnp.where(idx == tokens[:, None].astype(jnp.int32),
                      -jnp.inf, logprobs)
    dead = ~jnp.any(jnp.isfinite(resid), axis=-1, keepdims=True)
    onehot = jnp.where(idx == jnp.argmax(logprobs, axis=-1)[:, None],
                       0.0, -jnp.inf)
    return jnp.where(dead, onehot, resid)
