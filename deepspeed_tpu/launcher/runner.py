"""Cluster launcher CLI.

Analog of reference ``deepspeed/launcher/runner.py:380 main()`` (the ``deepspeed``
command): parse a hostfile with ``slots=N`` syntax (:184 fetch_hostfile), apply
--include/--exclude filters (:245), pick a master, and dispatch per-node
launchers over a backend (PDSH/OpenMPI/SLURM — ``multinode_runner.py``).

TPU mapping: one *process per host* (not per chip — XLA drives all local chips),
rendezvous via ``jax.distributed`` env vars (JAX_COORDINATOR_ADDRESS/
JAX_NUM_PROCESSES/JAX_PROCESS_ID) instead of MASTER_ADDR/RANK.  ``slots`` counts
chips per host, kept for capacity accounting and include/exclude filtering.
"""

from __future__ import annotations

import argparse
import base64
import collections
import json
import os
import re
import shlex
import subprocess
import sys
from typing import Dict, List, Optional

from ..utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_ENVS = ["PYTHON", "PATH", "LD_LIBRARY_PATH", "JAX_", "XLA_", "TPU_",
               "LIBTPU_"]
DEEPSPEED_ENVIRONMENT_NAME = ".deepspeed_env"


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu launcher: run a training script over one or "
        "more TPU hosts")
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="Hostfile path: lines of '<host> slots=<n>'")
    parser.add_argument("-i", "--include", type=str, default="",
                        help='Include filter, e.g. "worker-0@worker-1:0,2"')
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="Exclude filter, same syntax as --include")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--num_gpus", "--num_chips", dest="num_gpus", type=int,
                        default=-1, help="chips per node to use")
    parser.add_argument("--master_port", type=int,
                        default=int(os.environ.get("DLTS_MASTER_PORT", 29500)))
    parser.add_argument("--master_addr", type=str, default="")
    parser.add_argument("--launcher", type=str, default="pdsh",
                        choices=["pdsh", "openmpi", "mpich", "mvapich",
                                 "slurm", "ssh", "local"])
    parser.add_argument("--launcher_args", type=str, default="")
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("--detect_nvme", action="store_true")
    parser.add_argument("--enable_elastic_training", action="store_true",
                        help="supervise workers with the elastic agent: "
                        "re-elect the world and restart on failure or "
                        "hostfile membership change (reference "
                        "launcher/launch.py:257-310)")
    parser.add_argument("--serve", action="store_true",
                        help="serving-replica mode: supervise one engine-"
                        "replica worker per host (or --replicas N local "
                        "workers without a hostfile) with the elastic "
                        "agent's restart/membership machinery but no "
                        "elastic batch election — each worker sees "
                        "DS_REPLICA_ID / DS_NUM_REPLICAS and hostfile "
                        "edits resize the fleet at the next monitor tick")
    parser.add_argument("--replicas", type=int, default=1,
                        help="local replica count for --serve when no "
                        "hostfile is present")
    parser.add_argument("--prefill_workers", type=int, default=0,
                        help="disaggregate the --serve fleet: the first N "
                        "replicas take role=prefill (admission + chunked "
                        "prefill, then hand sessions off as host-tier KV "
                        "pulls) and the rest role=decode; workers read the "
                        "assignment from DS_REPLICA_ROLE.  0 (default) "
                        "keeps every replica role=both — the colocated "
                        "fleet (serving/supervisor.py plan_roles)")
    parser.add_argument("--elastic_config", type=str, default="",
                        help="ds config json with the elasticity block; "
                        "defaults to the --deepspeed_config in the script "
                        "args")
    parser.add_argument("--elastic_monitor_interval", type=float, default=5.0)
    parser.add_argument("--elastic_max_restarts", type=int, default=100)
    parser.add_argument("user_script", type=str, help="training script")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def fetch_hostfile(hostfile_path: str) -> Optional[Dict[str, int]]:
    """Parse ``host slots=N`` lines (reference ``runner.py:184``)."""
    if not os.path.isfile(hostfile_path):
        logger.warning(f"Unable to find hostfile, will proceed with training "
                       f"with local resources only: {hostfile_path}")
        return None
    resource_pool = collections.OrderedDict()
    with open(hostfile_path) as fd:
        for line in fd:
            line = line.strip()
            if line == "" or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                _, slot_count = slots.split("=")
                slot_count = int(slot_count)
            except ValueError as err:
                logger.error(f"Hostfile is not formatted correctly, unable to "
                             f"proceed with training: {line}")
                raise err
            if hostname in resource_pool:
                logger.error(f"Hostfile contains duplicate hosts, unable to "
                             f"proceed with training: {hostname}")
                raise ValueError(f"host {hostname} is already defined")
            resource_pool[hostname] = slot_count
    return resource_pool


def _parse_hostlist(string: str) -> Dict[str, List[int]]:
    """'worker-0@worker-1:0,2' -> {worker-0: [], worker-1: [0, 2]}."""
    result: Dict[str, List[int]] = {}
    for node_config in string.split("@"):
        if node_config == "":
            continue
        if ":" in node_config:
            hostname, slots = node_config.split(":")
            result[hostname] = [int(x) for x in slots.split(",")]
        else:
            result[node_config] = []
    return result


def parse_resource_filter(host_info: Dict[str, int], include_str: str = "",
                          exclude_str: str = "") -> Dict[str, List[int]]:
    """Apply include/exclude filters (reference ``runner.py:245``)."""
    if include_str and exclude_str:
        raise ValueError("include_str and exclude_str are mutually exclusive")
    pool = {host: list(range(slots)) for host, slots in host_info.items()}
    if include_str:
        include = _parse_hostlist(include_str)
        filtered = {}
        for host, slots in include.items():
            if host not in pool:
                raise ValueError(f"include host {host} not in hostfile")
            filtered[host] = slots if slots else pool[host]
            for s in slots:
                if s not in pool[host]:
                    raise ValueError(f"include slot {host}:{s} does not exist")
        return filtered
    if exclude_str:
        exclude = _parse_hostlist(exclude_str)
        filtered = {}
        for host, slots in pool.items():
            if host in exclude:
                bad = exclude[host]
                if not bad:
                    continue  # whole host excluded
                keep = [s for s in slots if s not in bad]
                if keep:
                    filtered[host] = keep
            else:
                filtered[host] = slots
        return filtered
    return pool


def encode_world_info(resource_pool: Dict[str, List[int]]) -> str:
    world_info = json.dumps(resource_pool)
    return base64.urlsafe_b64encode(world_info.encode()).decode()


class MultiNodeRunner:
    """Backend-pluggable remote command builder (reference
    ``multinode_runner.py``); ``get_cmd`` is pure for testability."""

    name = "base"

    def __init__(self, args, world_info_base64: str):
        self.args = args
        self.world_info_base64 = world_info_base64
        self.user_arguments = list(args.user_args)
        self.user_script = args.user_script

    def backend_exists(self) -> bool:
        raise NotImplementedError()

    def get_cmd(self, environment: Dict[str, str],
                active_resources: Dict[str, List[int]]) -> List[str]:
        raise NotImplementedError()

    @property
    def exports(self) -> Dict[str, str]:
        env = {}
        for var, val in os.environ.items():
            if any(var == v or (v.endswith("_") and var.startswith(v))
                   for v in EXPORT_ENVS):
                env[var] = val
        return env


class PDSHRunner(MultiNodeRunner):
    name = "pdsh"

    def backend_exists(self) -> bool:
        import shutil

        return shutil.which("pdsh") is not None

    def get_cmd(self, environment, active_resources):
        # mutate the caller's env (it is what Popen receives) — pdsh must see this
        environment["PDSH_RCMD_TYPE"] = "ssh"
        active_workers = ",".join(active_resources.keys())
        exports = "".join(f"export {k}={shlex.quote(v)}; "
                          for k, v in self.exports.items())
        launch = (f"cd {os.path.abspath('.')}; {exports}"
                  f"{sys.executable} -m deepspeed_tpu.launcher.launch "
                  f"--world_info={self.world_info_base64} "
                  f"--master_addr={self.args.master_addr} "
                  f"--master_port={self.args.master_port} "
                  f"--node_rank=%n {self.user_script} "
                  + " ".join(map(shlex.quote, self.user_arguments)))
        return ["pdsh", "-S", "-f", "1024", "-w", active_workers, launch]


class OpenMPIRunner(MultiNodeRunner):
    name = "openmpi"

    def backend_exists(self) -> bool:
        import shutil

        return shutil.which("mpirun") is not None

    def get_cmd(self, environment, active_resources):
        total_procs = len(active_resources)  # one proc per host on TPU
        hosts = ",".join(f"{h}:1" for h in active_resources)
        cmd = ["mpirun", "-n", str(total_procs), "-host", hosts,
               "--mca", "btl", "^openib", "--mca", "btl_tcp_if_include", "eth0"]
        for k, v in self.exports.items():
            cmd += ["-x", f"{k}={v}"]
        cmd += [sys.executable, self.user_script] + self.user_arguments
        return cmd


class MPICHRunner(MultiNodeRunner):
    """MPICH/Hydra launch (reference ``multinode_runner.py`` MPICH backend):
    one process per host, env exported per-variable with ``-genv``."""

    name = "mpich"

    def backend_exists(self) -> bool:
        import shutil

        return shutil.which("mpirun") is not None

    def get_cmd(self, environment, active_resources):
        total_procs = len(active_resources)
        hosts = ",".join(active_resources.keys())
        cmd = ["mpirun", "-n", str(total_procs), "-hosts", hosts,
               "-ppn", "1"]
        if self.args.launcher_args:
            cmd += shlex.split(self.args.launcher_args)
        for k, v in self.exports.items():
            cmd += ["-genv", k, v]
        cmd += [sys.executable, self.user_script] + self.user_arguments
        return cmd


class MVAPICHRunner(MultiNodeRunner):
    """MVAPICH launch: hostfile-driven mpirun_rsh with env as KEY=VAL args
    (reference ``multinode_runner.py`` MVAPICH backend writes
    ``/tmp/deepspeed_mvapich_hostfile``; we keep the same contract)."""

    name = "mvapich"
    hostfile_path = "/tmp/deepspeed_tpu_mvapich_hostfile"

    def backend_exists(self) -> bool:
        import shutil

        return shutil.which("mpirun_rsh") is not None

    def get_cmd(self, environment, active_resources):
        with open(self.hostfile_path, "w") as fh:
            for host in active_resources:
                fh.write(f"{host}\n")
        total_procs = len(active_resources)
        cmd = ["mpirun_rsh", "-np", str(total_procs),
               "-hostfile", self.hostfile_path]
        if self.args.launcher_args:
            cmd += shlex.split(self.args.launcher_args)
        for k, v in self.exports.items():
            cmd += [f"{k}={v}"]
        cmd += [sys.executable, self.user_script] + self.user_arguments
        return cmd


class SlurmRunner(MultiNodeRunner):
    name = "slurm"

    def backend_exists(self) -> bool:
        import shutil

        return shutil.which("srun") is not None

    def get_cmd(self, environment, active_resources):
        total_nodes = len(active_resources)
        cmd = ["srun", "-N", str(total_nodes), "--ntasks-per-node=1"]
        if getattr(self.args, "include", ""):
            cmd += ["--include", self.args.include]
        if self.args.launcher_args:
            cmd += shlex.split(self.args.launcher_args)
        exports = ",".join(f"{k}={v}" for k, v in self.exports.items())
        if exports:
            cmd += [f"--export=ALL,{exports}"]
        cmd += [sys.executable, self.user_script] + self.user_arguments
        return cmd


def _find_ds_config(args) -> str:
    if args.elastic_config:
        return args.elastic_config
    ua = list(args.user_args)
    for i, a in enumerate(ua):
        if a in ("--deepspeed_config", "--deepspeed-config") and i + 1 < len(ua):
            return ua[i + 1]
        for prefix in ("--deepspeed_config=", "--deepspeed-config="):
            if a.startswith(prefix):
                return a[len(prefix):]
    raise ValueError(
        "--enable_elastic_training needs --elastic_config or a "
        "--deepspeed_config in the training-script arguments")


def _ssh_wrap(host: str, inner: List[str], env: Dict[str, str]) -> List[str]:
    """Wrap a worker command for ssh launch, exporting the rendezvous /
    replica env inline (shared by the elastic and serve modes)."""
    rendezvous = {k: v for k, v in env.items()
                  if k.startswith(("JAX_", "DS_ELASTIC_", "DS_REPLICA",
                                   "DS_NUM_REPLICAS"))
                  or k in ("WORLD_SIZE", "RANK")
                  or any(k == e or (e.endswith("_") and k.startswith(e))
                         for e in EXPORT_ENVS)}
    exports = "".join(f"export {k}={shlex.quote(str(v))}; "
                      for k, v in rendezvous.items())
    remote = (f"cd {os.path.abspath('.')}; {exports}"
              + " ".join(map(shlex.quote, inner)))
    return ["ssh", host, remote]


def _serve_main(args) -> int:
    """``deepspeed --serve [--replicas N]``: supervise serving-replica
    worker processes with the :class:`ElasticAgent` — its probe/restart/
    membership machinery, with election short-circuited (no elastic batch
    constraint: every live host runs one replica; ``elastic_agent.py
    elect_world``).  With a hostfile, one replica per host and editing the
    file resizes the fleet at the next monitor tick — the process-level
    twin of ``ReplicaRouter`` drain/re-admit (``serving/supervisor.py``
    does the same for in-process replicas).  Without one, ``--replicas N``
    local workers.  Workers read ``DS_REPLICA_ID`` / ``DS_NUM_REPLICAS``
    to build their slice of the fleet."""
    import socket

    from ..elasticity.elastic_agent import ElasticAgent
    from ..serving.supervisor import plan_roles

    local_names = {"localhost", "127.0.0.1", socket.gethostname()}
    n = max(1, int(args.replicas))
    prefill_workers = int(getattr(args, "prefill_workers", 0) or 0)

    def probe_hosts():
        pool = fetch_hostfile(args.hostfile)
        if not pool:
            return {f"replica-{i}": 1 for i in range(n)}
        return {host: len(slots) for host, slots in
                parse_resource_filter(pool, args.include,
                                      args.exclude).items()}

    def launch_cmd(host, env):
        env["DS_REPLICA_ID"] = env.get("JAX_PROCESS_ID", "0")
        env["DS_NUM_REPLICAS"] = env.get("JAX_NUM_PROCESSES", str(n))
        # role assignment rides the same env channel: the worker builds
        # its engine with role=$DS_REPLICA_ROLE.  plan_roles validates
        # the ratio once, at fleet-spec time, so a bad split fails the
        # launch instead of each worker
        roles = plan_roles(int(env["DS_NUM_REPLICAS"]), prefill_workers)
        env["DS_REPLICA_ROLE"] = roles[int(env["DS_REPLICA_ID"]) %
                                       len(roles)]
        inner = [sys.executable, "-u", args.user_script] + \
            list(args.user_args)
        if args.launcher == "local" or host in local_names or \
                host.startswith("replica-"):
            return inner  # env rides through Popen(env=...)
        return _ssh_wrap(host, inner, env)

    agent = ElasticAgent(
        {}, probe_hosts, launch_cmd,
        master_port=args.master_port,
        monitor_interval=args.elastic_monitor_interval,
        max_restarts=args.elastic_max_restarts,
        elect_all=True)
    return agent.run()


def _elastic_main(args) -> int:
    """``deepspeed --enable_elastic_training``: run the training script under
    the ElasticAgent instead of a one-shot multinode launch (reference
    ``launcher/launch.py:257-310`` starts DSElasticAgent the same way).

    The agent probes the HOSTFILE each monitor tick — editing the hostfile
    is the membership-change signal (slice resize / preemption on TPU) —
    elects the largest elastic-compatible world, launches one worker per
    host with the JAX rendezvous env, and restarts the group on worker
    death or membership change.
    """
    import socket

    from ..elasticity.elastic_agent import ElasticAgent

    with open(_find_ds_config(args)) as fh:
        ds_config = json.load(fh)
    local_names = {"localhost", "127.0.0.1", socket.gethostname()}

    def probe_hosts():
        # host -> slots dict: the agent re-derives chips_per_host each
        # probe, so hostfile slot edits (slice resize) take effect at the
        # next election, not just the initial one
        pool = fetch_hostfile(args.hostfile)
        if not pool:
            return {socket.gethostname(): 1}
        return {host: len(slots) for host, slots in
                parse_resource_filter(pool, args.include,
                                      args.exclude).items()}

    def launch_cmd(host, env):
        inner = [sys.executable, "-u", args.user_script] + list(args.user_args)
        if args.launcher == "local" or host in local_names:
            return inner  # env rides through Popen(env=...)
        return _ssh_wrap(host, inner, env)

    agent = ElasticAgent(
        ds_config, probe_hosts, launch_cmd,
        master_port=args.master_port,
        monitor_interval=args.elastic_monitor_interval,
        max_restarts=args.elastic_max_restarts)
    return agent.run()


def main(args=None):
    args = parse_args(args)
    if args.serve:
        sys.exit(_serve_main(args))
    if args.enable_elastic_training:
        sys.exit(_elastic_main(args))
    resource_pool = fetch_hostfile(args.hostfile)

    if not resource_pool or args.launcher == "local":
        # single-host path: exec the script locally, no rendezvous needed
        # ("local" without elastic training means exactly this)
        env = os.environ.copy()
        cmd = [sys.executable, args.user_script] + args.user_args
        logger.info(f"cmd = {' '.join(cmd)}")
        result = subprocess.Popen(cmd, env=env)
        result.wait()
        sys.exit(result.returncode)

    active = parse_resource_filter(resource_pool, args.include, args.exclude)
    if args.num_nodes > 0:
        active = dict(list(active.items())[:args.num_nodes])
    if args.num_gpus > 0:  # cap chips per node (reference runner.py:389-400)
        active = {host: slots[:args.num_gpus] for host, slots in active.items()}
    if not args.master_addr:
        args.master_addr = list(active.keys())[0]

    world_info = encode_world_info(active)
    runner_cls = {"pdsh": PDSHRunner, "ssh": PDSHRunner,
                  "openmpi": OpenMPIRunner, "mpich": MPICHRunner,
                  "mvapich": MVAPICHRunner,
                  "slurm": SlurmRunner}[args.launcher]
    runner = runner_cls(args, world_info)
    if not runner.backend_exists():
        raise RuntimeError(f"launcher backend {args.launcher} not installed")
    env = os.environ.copy()
    cmd = runner.get_cmd(env, active)
    logger.info(f"cmd = {' '.join(cmd)}")
    result = subprocess.Popen(cmd, env=env)
    result.wait()
    sys.exit(result.returncode)


if __name__ == "__main__":
    main()
