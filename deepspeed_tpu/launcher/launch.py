"""Per-node launcher.

Analog of reference ``deepspeed/launcher/launch.py:129 main()``: decode the
base64 world info, derive this node's process id, export the rendezvous env, and
spawn the training script.  TPU difference: ONE process per host (XLA drives all
local chips), so there is no per-device subprocess fan-out; the reference's
process-tree signal handling (:115 terminate_process_tree) is kept.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import signal
import subprocess
import sys

from ..utils.logging import logger


def parse_args(args=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--world_info", type=str, required=True)
    parser.add_argument("--node_rank", type=int, required=True)
    parser.add_argument("--master_addr", type=str, required=True)
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def decode_world_info(world_info_b64: str) -> dict:
    return json.loads(base64.urlsafe_b64decode(world_info_b64).decode())


def build_env(world_info: dict, node_rank: int, master_addr: str,
              master_port: int, base_env=None) -> dict:
    env = dict(base_env if base_env is not None else os.environ)
    hosts = list(world_info.keys())
    env["JAX_COORDINATOR_ADDRESS"] = f"{master_addr}:{master_port}"
    env["JAX_NUM_PROCESSES"] = str(len(hosts))
    env["JAX_PROCESS_ID"] = str(node_rank)
    # reference-compatible names some user scripts read
    env["MASTER_ADDR"] = master_addr
    env["MASTER_PORT"] = str(master_port)
    env["WORLD_SIZE"] = str(sum(len(v) if v else 1 for v in world_info.values()))
    env["RANK"] = str(node_rank)
    env["LOCAL_RANK"] = "0"
    return env


def main(args=None):
    args = parse_args(args)
    world_info = decode_world_info(args.world_info)
    env = build_env(world_info, args.node_rank, args.master_addr,
                    args.master_port)
    cmd = [sys.executable, "-u", args.user_script] + args.user_args
    logger.info(f"node {args.node_rank}: launching {' '.join(cmd)}")
    proc = subprocess.Popen(cmd, env=env)

    def sig_handler(signum, frame):
        proc.send_signal(signum)

    signal.signal(signal.SIGTERM, sig_handler)
    signal.signal(signal.SIGINT, sig_handler)
    proc.wait()
    sys.exit(proc.returncode)


if __name__ == "__main__":
    main()
