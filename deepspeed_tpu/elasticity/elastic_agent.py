"""Elastic agent: supervise a training process group and restart it with a
recomputed world size when membership changes or workers die.

Reference parity: ``elasticity/elastic_agent.py:25 DSElasticAgent`` (extends
torch-elastic's LocalElasticAgent: monitors the worker group, injects DS env,
restarts on membership change) and the ``--enable_elastic_training`` branch
of ``launcher/launch.py:257-310``.

TPU design: there is no torch-elastic rendezvous to extend — on TPU the
slice membership is the host list, and JAX re-initializes its coordinator on
restart.  The agent therefore supervises at the PROCESS level:

 - probe the hostfile (or a callable) for the currently-reachable hosts,
 - pick the largest world size compatible with the elastic batch config
   (``compute_elastic_config`` — the same math the reference validates at
   engine init),
 - launch one worker per host with the JAX rendezvous env,
 - on any worker death or membership change: kill the group, re-probe,
   relaunch.  Training resumes from the latest checkpoint (orbax save/load
   is mesh-shape-agnostic — the universal-checkpoint property proven in
   ``tests/unit/test_universal_checkpoint.py``).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Callable, Dict, List, Mapping, Optional, Sequence

from ..utils.logging import logger
from .elasticity import compute_elastic_config


class ElasticAgent:
    """Process-group supervisor with elastic world-size recomputation.

    ``launch_cmd(host, env) -> list[str]`` builds the per-host command
    (ssh wrapper or local python); ``probe_hosts()`` returns the
    currently-available hosts each round — either a ``list[str]`` (host
    names; ``chips_per_host`` stays as constructed) or a
    ``dict[str, int]`` of host -> chip count, in which case the agent
    re-derives ``chips_per_host = min(counts)`` at every probe (the SPMD
    world needs a uniform per-host chip count, so a heterogeneous pool
    runs at its smallest member) and treats a capacity change like a
    membership change.
    """

    def __init__(self, ds_config: dict,
                 probe_hosts: Callable[[], List[str]],
                 launch_cmd: Callable[[str, Dict[str, str]], List[str]],
                 chips_per_host: int = 1,
                 master_port: int = 29500,
                 monitor_interval: float = 5.0,
                 max_restarts: int = 100,
                 partial_grace_ticks: int = 3,
                 elect_all: bool = False):
        #: serving-replica supervision (launcher --serve): elect every
        #: live host, no elastic batch constraint.  An EXPLICIT flag on
        #: purpose — keying this off a missing/disabled elasticity config
        #: block would silently launch a mis-configured TRAINING run on
        #: every host instead of failing fast at election.
        self.elect_all = bool(elect_all)
        self.ds_config = ds_config
        self.probe_hosts = probe_hosts
        self.launch_cmd = launch_cmd
        self.chips_per_host = chips_per_host
        self.master_port = master_port
        self.monitor_interval = monitor_interval
        self.max_restarts = max_restarts
        #: monitor ticks a 0-exited/still-running mix may persist before a
        #: restart — completion skew must not restart the group; only
        #: survivors genuinely hung waiting on an exited peer should
        self.partial_grace_ticks = partial_grace_ticks
        self.restart_count = 0
        self._procs: Dict[str, subprocess.Popen] = {}
        self._hosts: List[str] = []
        self._chips_running = chips_per_host  # capacity of the live group

    def _probe(self) -> List[str]:
        """Probe hosts; a dict result also refreshes ``chips_per_host``.
        Hosts reporting 0 chips are excluded (a ``slots=0`` hostfile line
        behaves like an excluded host, not a 1-chip one)."""
        res = self.probe_hosts()
        if isinstance(res, Mapping):
            res = {h: c for h, c in res.items() if c > 0}
            if res:
                self.chips_per_host = min(res.values())
            return list(res)
        return list(res)

    # ------------------------------------------------------------------ sizing
    def elect_world(self, hosts: Sequence[str],
                    verbose: bool = True) -> List[str]:
        """Largest prefix of ``hosts`` whose chip count is elastic-valid.
        ``verbose=False`` for the steady-state monitor probe (the election
        log belongs to starts/restarts, not every tick).

        Under ``elect_all`` (serving-replica supervision,
        ``launcher/runner.py --serve``) there is no batch constraint to
        satisfy — every live host runs one independent engine replica,
        so all of them are elected and the agent's value is purely its
        restart/membership machinery.  Training runs (no flag) still
        fail fast through ``compute_elastic_config`` on a missing or
        disabled elasticity block."""
        if self.elect_all:
            if not hosts:
                raise RuntimeError("no hosts available to elect")
            if verbose:
                logger.info(f"elastic: electing all {len(hosts)} hosts "
                            "(no elastic batch constraint — replica mode)")
            return list(hosts)
        final_batch, valid_counts = compute_elastic_config(
            self.ds_config, world_size=0)
        best: Optional[int] = None
        for n in valid_counts:
            if n % self.chips_per_host:
                continue
            if n // self.chips_per_host <= len(hosts):
                best = max(best or 0, n // self.chips_per_host)
        if best is None:
            raise RuntimeError(
                f"no elastic-compatible world size for {len(hosts)} hosts x "
                f"{self.chips_per_host} chips (valid chip counts: "
                f"{valid_counts})")
        if verbose:
            logger.info(f"elastic: electing {best}/{len(hosts)} hosts "
                        f"(global batch {final_batch})")
        return list(hosts)[:best]

    def _elect_retrying(self) -> Optional[List[str]]:
        """Probe + elect, waiting out transient capacity loss: retries every
        ``monitor_interval`` until a compatible world exists or the restart
        budget is spent.  Returns None when the budget runs out."""
        attempts = 0
        while True:
            try:
                return self.elect_world(self._probe())
            except RuntimeError as e:
                attempts += 1
                if self.restart_count + attempts > self.max_restarts:
                    logger.error(f"elastic: {e}; giving up")
                    return None
                logger.warning(f"elastic: {e}; waiting for capacity")
                time.sleep(self.monitor_interval)

    # ------------------------------------------------------------------ launch
    def _env_for(self, host: str, rank: int, hosts: List[str]) -> Dict[str, str]:
        env = dict(os.environ)
        env.update({
            "JAX_COORDINATOR_ADDRESS": f"{hosts[0]}:{self.master_port}",
            "JAX_NUM_PROCESSES": str(len(hosts)),
            "JAX_PROCESS_ID": str(rank),
            "WORLD_SIZE": str(len(hosts) * self.chips_per_host),
            "RANK": str(rank * self.chips_per_host),
            "DS_ELASTIC_RESTART_COUNT": str(self.restart_count),
        })
        return env

    def _start_group(self, hosts: List[str]) -> None:
        self._hosts = hosts
        self._chips_running = self.chips_per_host
        self._procs = {}
        for rank, host in enumerate(hosts):
            env = self._env_for(host, rank, hosts)
            cmd = self.launch_cmd(host, env)
            self._procs[host] = subprocess.Popen(cmd, env=env)
        logger.info(f"elastic: started {len(hosts)} workers "
                    f"(restart #{self.restart_count})")

    def _stop_group(self) -> None:
        for proc in self._procs.values():
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        deadline = time.time() + 15
        for proc in self._procs.values():
            while proc.poll() is None and time.time() < deadline:
                time.sleep(0.2)
            if proc.poll() is None:
                proc.kill()
        self._procs = {}

    def _restart_dead_workers(self) -> bool:
        """Replica-mode (``elect_all``) worker recovery: a crashed
        replica worker restarts ALONE — the survivors keep serving.  A
        whole-group restart here would drop every healthy replica's
        in-flight sessions to recover one dead process; serving workers
        are independent (no collective waits), so individual restart is
        safe in a way it never is for a training group.  This is the
        process-level half of the crash protocol: the in-process router
        fails the dead replica and re-homes its sessions
        (``serving/router.py fail`` via the supervisor's hard-probe
        detection), this restart brings the worker back, and the
        recovered probe re-admits it.  Each restart burns one unit of
        the shared restart budget; returns ``False`` when spent."""
        for rank, host in enumerate(self._hosts):
            proc = self._procs.get(host)
            if proc is None or proc.poll() in (None, 0):
                continue                    # running or finished clean
            self.restart_count += 1
            if self.restart_count > self.max_restarts:
                logger.error("elastic: restart budget exhausted")
                return False
            logger.warning(
                f"elastic: replica worker on {host} exited "
                f"{proc.poll()} — restarting it alone "
                f"(restart #{self.restart_count})")
            env = self._env_for(host, rank, self._hosts)
            self._procs[host] = subprocess.Popen(
                self.launch_cmd(host, env), env=env)
        return True

    # ----------------------------------------------------------------- monitor
    def _group_state(self) -> str:
        """SUCCEEDED (all 0), FAILED (any non-zero), PARTIAL (some exited 0
        while peers run — the survivors will hang in collectives waiting for
        the missing process, so the group must restart), HEALTHY."""
        codes = [p.poll() for p in self._procs.values()]
        if any(c is not None and c != 0 for c in codes):
            return "FAILED"
        if all(c == 0 for c in codes) and codes:
            return "SUCCEEDED"
        if any(c == 0 for c in codes):
            return "PARTIAL"
        return "HEALTHY"

    def run(self) -> int:
        """Supervise until success or restart budget exhaustion (the
        reference's ``_invoke_run`` loop)."""
        self._start_group(self.elect_world(self._probe()))
        partial_ticks = 0
        while True:
            time.sleep(self.monitor_interval)
            state = self._group_state()
            if state == "SUCCEEDED":
                logger.info("elastic: worker group finished")
                return 0
            if state == "FAILED" and self.elect_all:
                # serving replicas are independent processes: restart
                # the dead one(s) alone, never the whole fleet
                if not self._restart_dead_workers():
                    # budget spent: stop the SURVIVORS too before
                    # exiting (the whole-group path below does the
                    # same) — an exiting agent must not orphan worker
                    # processes holding ports/devices
                    self._stop_group()
                    return 1
                partial_ticks = 0
                continue
            if state == "PARTIAL":
                partial_ticks += 1
                if partial_ticks <= self.partial_grace_ticks:
                    continue  # completion skew; give peers time to finish
            else:
                partial_ticks = 0
            membership = None
            if state == "HEALTHY":
                try:
                    membership = self.elect_world(self._probe(),
                                                  verbose=False)
                except RuntimeError:
                    # keep running with who we have, at the running capacity
                    self.chips_per_host = self._chips_running
                    membership = self._hosts
                # Order-insensitive: a probe returning the same host SET in
                # a different order is not a capacity change (elected order
                # is still used for rank assignment on a real restart).
                # A per-host chip-count change IS one (hostfile slots
                # edited), even with an identical host set.
                if sorted(membership) == sorted(self._hosts) and \
                        self.chips_per_host == self._chips_running:
                    continue
                logger.warning(
                    f"elastic: membership change {len(self._hosts)} hosts x "
                    f"{self._chips_running} chips -> {len(membership)} x "
                    f"{self.chips_per_host}; restarting group")
            else:
                logger.warning(
                    f"elastic: worker group {state}; restarting")
            self._stop_group()
            self.restart_count += 1
            if self.restart_count > self.max_restarts:
                logger.error("elastic: restart budget exhausted")
                return 1
            hosts = membership or self._elect_retrying()
            if hosts is None:
                return 1
            self._start_group(hosts)
            partial_ticks = 0
