"""Elastic batch-size math (reference ``elasticity/elasticity.py:287``).

Given a maximum acceptable global batch, a set of candidate micro-batch
sizes, and an accelerator-count range, find the global batch size B and the
set of accelerator counts W such that for every w in W there is a micro
batch m and accumulation steps g with ``m * g * w == B`` — so scaling the
job up or down inside W never changes the effective batch.

v0.2 semantics: accelerator counts are constrained to multiples of
``num_gpus_per_node * model_parallel_size`` and the data-parallel degree is
``w / model_parallel_size`` (reference ``_get_compatible_gpus_v02``:173).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from ..utils.logging import logger
from .config import (ElasticityConfig, ElasticityConfigError,
                     ElasticityIncompatibleWorldSize)


def _valid_counts_for_batch(batch: int, micro_batches: List[int],
                            min_n: int, max_n: int, step: int) -> List[int]:
    """Accelerator counts in [min_n, max_n] (multiples of ``step``) that can
    realize ``batch`` with some (micro, gas) pair."""
    valid = []
    start = -(-max(min_n, step) // step) * step  # round UP to a step multiple
    for w in range(start, max_n + 1, step):
        for m in micro_batches:
            if batch % (m * w) == 0:
                valid.append(w)
                break
    return valid


def get_compatible_accelerator_counts(
        max_batch: int, micro_batches: List[int], min_n: int, max_n: int,
        prefer_larger: bool = True, step: int = 1) -> Tuple[int, List[int]]:
    """Pick the global batch <= max_batch maximizing elastic range.

    Candidates are multiples of each micro batch padded up to highly
    composite values (a batch with many divisors is compatible with many
    world sizes).  Returns (batch, sorted valid counts)."""
    candidates = set()
    base = max(micro_batches)
    # highly-divisible candidates: lcm of micros scaled by 2^k, plus each
    # micro's largest power-of-two multiple under the cap
    l = math.lcm(*micro_batches)
    v = l
    while v <= max_batch:
        candidates.add(v)
        v *= 2
    for m in micro_batches:
        v = m
        while v * 2 <= max_batch:
            v *= 2
        candidates.add(v)
    candidates.add(max_batch - (max_batch % base) or base)

    best: Tuple[int, List[int]] = (0, [])
    for batch in sorted(candidates):
        if batch <= 0 or batch > max_batch:
            continue
        valid = _valid_counts_for_batch(batch, micro_batches, min_n, max_n,
                                        step)
        better = (len(valid), batch if prefer_larger else -batch) > \
            (len(best[1]), best[0] if prefer_larger else -best[0])
        if valid and better:
            best = (batch, valid)
    if not best[1]:
        raise ElasticityConfigError(
            f"no batch <= {max_batch} over micro batches {micro_batches} is "
            f"compatible with any count in [{min_n}, {max_n}] (step {step})")
    return best


def compute_elastic_config(ds_config: dict, world_size: int = 0,
                           return_microbatch: bool = False):
    """Reference-parity entry (``elasticity.py:287``).

    Returns ``(final_batch_size, valid_world_sizes[, micro_batch])``; with
    ``world_size > 0`` also validates it and derives that size's
    micro-batch/GAS pair.
    """
    ecfg = ElasticityConfig(**ds_config.get("elasticity", {}))
    ecfg.validate()
    if not ecfg.enabled:
        raise ElasticityConfigError("elasticity is not enabled in the config")

    step = (ecfg.num_gpus_per_node * ecfg.model_parallel_size
            if ecfg.version >= 0.2 else 1)
    batch, valid = get_compatible_accelerator_counts(
        ecfg.max_train_batch_size, sorted(ecfg.micro_batch_sizes),
        ecfg.min_gpus, ecfg.max_gpus, prefer_larger=ecfg.prefer_larger_batch,
        step=step)

    micro = None
    if world_size > 0:
        if world_size not in valid:
            raise ElasticityIncompatibleWorldSize(
                f"world size {world_size} is not in the elastic set {valid} "
                f"for global batch {batch}")
        dp = world_size // ecfg.model_parallel_size \
            if ecfg.version >= 0.2 else world_size
        # largest micro batch that divides the per-dp share (prefer fewer
        # accumulation steps)
        for m in sorted(ecfg.micro_batch_sizes, reverse=True):
            if batch % (m * dp) == 0:
                micro = m
                break
        assert micro is not None
    if return_microbatch:
        return batch, valid, micro
    return batch, valid


def elasticity_enabled(ds_config: dict) -> bool:
    return bool(ds_config.get("elasticity", {}).get("enabled", False))


def resume_notes() -> str:
    """Operational recipe for preemption resume on TPU (the DSElasticAgent
    analog — reference ``elastic_agent.py:25`` restarts torch workers; on
    TPU the platform restarts the slice and training resumes by reloading
    under the new mesh)."""
    return (
        "1. run under a restarting controller (GKE Job/JobSet or gcloud "
        "queued-resources) so preempted slices are re-created;\n"
        "2. save checkpoints at a cadence >= elasticity.min_time via "
        "engine.save_checkpoint (orbax writes are multi-host);\n"
        "3. on restart, build the mesh from the surviving slice size, pick "
        "the micro-batch from compute_elastic_config(world_size=N), and "
        "engine.load_checkpoint — universal-checkpoint resharding restores "
        "params/optimizer under the new (dp, tp, pp) layout;\n"
        "4. the global batch is unchanged by construction, so schedules and "
        "convergence are unaffected.")
