"""Elasticity config schema (reference ``elasticity/config.py``)."""

from __future__ import annotations

from typing import List, Optional

from ..runtime.config_utils import DeepSpeedConfigModel


class ElasticityError(Exception):
    """Base error for elastic training (reference ``elasticity/constants``)."""


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


LATEST_ELASTICITY_VERSION = 0.2


class ElasticityConfig(DeepSpeedConfigModel):
    """Keys mirror the reference's ``elasticity`` block."""

    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = [2, 4, 6]
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    version: float = 0.2
    prefer_larger_batch: bool = True
    ignore_non_elastic_batch_info: bool = False
    #: v0.2: accelerator counts must be multiples of this (chips per host x
    #: model-parallel degree)
    model_parallel_size: int = 1
    num_gpus_per_node: int = 1

    def validate(self) -> None:
        if not self.micro_batch_sizes:
            raise ElasticityConfigError("micro_batch_sizes must be non-empty")
        if any(m <= 0 for m in self.micro_batch_sizes):
            raise ElasticityConfigError(
                f"micro_batch_sizes must be positive: {self.micro_batch_sizes}")
        if self.max_train_batch_size < min(self.micro_batch_sizes):
            raise ElasticityConfigError(
                f"max_train_batch_size {self.max_train_batch_size} is smaller "
                f"than the smallest micro batch {min(self.micro_batch_sizes)}")
        if self.min_gpus < 1 or self.max_gpus < self.min_gpus:
            raise ElasticityConfigError(
                f"invalid accelerator range [{self.min_gpus}, {self.max_gpus}]")
        if self.version > LATEST_ELASTICITY_VERSION:
            raise ElasticityConfigError(
                f"elasticity version {self.version} > latest supported "
                f"{LATEST_ELASTICITY_VERSION}")
