"""Elastic training: batch-size math + preemption resume.

Reference: ``deepspeed/elasticity`` — ``compute_elastic_config``
(``elasticity/elasticity.py:287``) derives, from a target batch-size range
and allowed micro-batch sizes, the set of accelerator counts that keep the
*global* batch size constant, so a job can lose or gain nodes and resume
without changing training semantics; ``DSElasticAgent``
(``elastic_agent.py:25``) restarts workers on membership changes.

TPU realisation: the math is framework-neutral and lives in
:mod:`elasticity`.  The agent role is played by the platform (GKE job
controller / ``gcloud`` queued resources restart preempted slices); resume =
``load_checkpoint`` under the new mesh, which the universal-checkpoint
resharding already handles (``tests/unit/test_universal_checkpoint.py``) —
see :func:`resume_notes` for the operational recipe.
"""

from .config import ElasticityConfig, ElasticityConfigError, ElasticityError
from .elastic_agent import ElasticAgent
from .elasticity import (compute_elastic_config, elasticity_enabled,
                         get_compatible_accelerator_counts, resume_notes)

__all__ = ["ElasticAgent", "ElasticityConfig", "ElasticityConfigError",
           "ElasticityError", "compute_elastic_config", "elasticity_enabled",
           "get_compatible_accelerator_counts", "resume_notes"]
