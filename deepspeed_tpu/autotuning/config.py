"""Autotuning config (reference ``autotuning/config.py``; same key names
under the ``autotuning`` block)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..runtime.config_utils import DeepSpeedConfigModel


class AutotuningConfig(DeepSpeedConfigModel):
    enabled: bool = False
    fast: bool = True                       # tune zero stage + micro batch
    results_dir: str = "autotuning_results"
    exps_dir: str = "autotuning_exps"
    overwrite: bool = True
    metric: str = "throughput"              # throughput | latency
    start_profile_step: int = 3             # warmup steps before measuring
    end_profile_step: int = 5
    max_train_batch_size: Optional[int] = None
    min_train_batch_size: int = 1
    max_train_micro_batch_size_per_gpu: int = 1024
    min_train_micro_batch_size_per_gpu: int = 1
    num_tuning_micro_batch_sizes: int = 3
    tuner_type: str = "staged"              # staged | gridsearch | model_based
    tuner_early_stopping: int = 5
    tuner_num_trials: int = 50
    arg_mappings: Dict[str, Any] = {}
    zero_stages: Optional[List[int]] = None  # restrict the searched stages
    #: staged mode: which knob groups to tune, in order.  "batch" = zero
    #: stage x micro batch; "remat" = remat_policy x scan_layers; "gas" =
    #: gradient accumulation; "flash" = flash kernel block sizes.  These are
    #: the knobs that actually set TPU throughput (PROFILE.md) — the
    #: reference's fast mode only covers the first group.
    stages: List[str] = ["batch", "remat", "gas", "flash"]
    gas_candidates: List[int] = [1, 2, 4, 8, 16]
    remat_policies: List[str] = ["full", "dots", "dots_flash"]
    flash_blocks: List[List[int]] = [[256, 1024], [512, 1024],
                                     [1024, 1024], [512, 512]]
