"""Replayable serving traces: the workload half of the serving autotuner.

A tuner is only as honest as its workload.  The reference framework
replays *training* steps (one batch looks like the next); serving has no
such luxury — throughput depends on the *trace*: prompt lengths, decode
budgets, arrival order, and above all the session/prefix structure that
decides what the prefix cache and the host tier can reuse.  This module
makes that workload a first-class, replayable artifact:

 - :class:`ServingTrace`: a JSON-able trace whose prompts are
   **deterministic functions of seeds** — a session's shared prefix is
   drawn from ``rng([seed, _SESSION_SALT, session])`` and each request's
   unique tail from ``rng([seed, _TAIL_SALT, index])`` — so a trace file
   is a few hundred bytes yet materializes the exact same token arrays on
   every machine, forever.  Entries keep arrival order, per-request
   decode budgets, ``slo_class``/``priority``, and the session id that
   encodes the prefix-sharing structure.  Recorded (non-synthetic)
   entries may instead carry their literal tokens.
 - :class:`TraceRecorder`: attaches to a live :class:`~deepspeed_tpu
   .inference.serving.ServingEngine` or :class:`~deepspeed_tpu.serving
   .ReplicaRouter` via the ``_submit_observer`` hook and captures every
   ``submit()`` (verbatim tokens, budgets, SLO class, arrival order) into
   a replayable trace — record production traffic once, tune against it
   offline.
 - :func:`fit_trace`: builds a *synthetic* trace from a telemetry
   snapshot (``engine.metrics.snapshot()`` — the PR 8 registry): mean
   prompt/decode lengths from the token counters, the SLO-class mix from
   ``serving_slo_requests_total``, and the session structure
   (``sessions``, ``prefix_len``) fitted against the observed
   prefix-cache hit rate — for fleets where recording raw tokens is not
   an option, the scrape you already have is enough to tune against.

``ServingTrace.slice(n)`` is the successive-halving budget unit: the
first ``n`` entries in arrival order, session structure intact.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["TraceEntry", "ServingTrace", "TraceRecorder", "fit_trace",
           "sessions_trace"]

#: Format v2 (PR 20) adds per-request sampling params (``temperature``,
#: ``top_k``, ``top_p``, ``seed``).  Absent fields default to greedy
#: (``temperature=0``), so every committed v1 trace loads unchanged and
#: replays byte-identically — the defaults ARE the v1 semantics.
TRACE_VERSION = 2

# rng stream salts: sessions and tails must never collide even when a
# session id equals an entry index
_SESSION_SALT = 7919
_TAIL_SALT = 104729


@dataclasses.dataclass
class TraceEntry:
    """One request in the trace (arrival order = list order).

    Synthetic entries describe their prompt (``session``/``tail_len`` or
    a sessionless ``prompt_len``); recorded entries carry ``tokens``
    verbatim and ignore the synthetic fields.
    """
    uid: Any
    max_new_tokens: int
    session: Optional[int] = None     # shared-prefix group; None = no prefix
    tail_len: int = 0                 # unique tokens after the prefix
    prompt_len: int = 0               # sessionless synthetic prompt length
    slo_class: Optional[str] = None
    priority: int = 0
    eos_token_id: Optional[int] = None   # submit-time eos (early stop)
    tokens: Optional[List[int]] = None   # recorded verbatim prompt
    # v2 sampling params — defaults are exactly the greedy v1 semantics
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0                        # per-request sampling seed

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        for k in ("session", "slo_class", "eos_token_id",
                  "tokens"):                           # None = default
            if d[k] is None:
                del d[k]
        for k in ("tail_len", "prompt_len", "priority",
                  "temperature", "top_k", "seed"):         # 0 = default
            if not d[k]:
                del d[k]
        if d["top_p"] == 1.0:                          # 1.0 = default
            del d["top_p"]
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TraceEntry":
        return cls(**{f.name: d[f.name] for f in dataclasses.fields(cls)
                      if f.name in d})


class ServingTrace:
    """A replayable request trace (module docstring).

    Parameters
    ----------
    vocab:      token-id range for synthetic prompts (``[0, vocab)``).
    seed:       root seed every synthetic prompt derives from.
    prefix_len: shared-prefix length of every session (tokens).
    entries:    arrival-ordered :class:`TraceEntry` list.
    meta:       free-form provenance (recorded-from, fitted-from, ...).
    """

    def __init__(self, *, vocab: int, seed: int = 0, prefix_len: int = 0,
                 entries: Optional[Sequence[TraceEntry]] = None,
                 meta: Optional[Dict[str, Any]] = None):
        self.vocab = int(vocab)
        if self.vocab < 1:
            raise ValueError(f"vocab must be >= 1, got {vocab}")
        self.seed = int(seed)
        self.prefix_len = int(prefix_len)
        self.entries: List[TraceEntry] = list(entries or [])
        self.meta: Dict[str, Any] = dict(meta or {})
        uids = [e.uid for e in self.entries]
        if len(set(uids)) != len(uids):
            raise ValueError("duplicate uids in trace entries")

    # ------------------------------------------------------------ shape
    def __len__(self) -> int:
        return len(self.entries)

    @property
    def sessions(self) -> int:
        """Distinct session (shared-prefix) groups referenced."""
        return len({e.session for e in self.entries
                    if e.session is not None})

    def slice(self, n: int) -> "ServingTrace":
        """The replay-budget unit: the first ``n`` entries in arrival
        order (session structure intact — request ``i`` still returns to
        its session)."""
        return ServingTrace(vocab=self.vocab, seed=self.seed,
                            prefix_len=self.prefix_len,
                            entries=self.entries[: int(n)],
                            meta=self.meta)

    def max_total_len(self) -> int:
        """Largest prompt + completion over the trace — what
        ``max_seq_len`` must cover."""
        longest = 0
        for e in self.entries:
            if e.tokens is not None:
                plen = len(e.tokens)
            elif e.session is not None:
                plen = self.prefix_len + e.tail_len
            else:
                plen = e.prompt_len
            longest = max(longest, plen + int(e.max_new_tokens))
        return longest

    def working_set_tokens(self) -> int:
        """Unique KV tokens the whole trace touches — each session
        prefix counted ONCE, plus every unique tail and completion
        (recorded entries count their full prompt; shared structure is
        not recoverable from verbatim tokens without re-hashing).  This
        is the BENCH_r09 pool-pressure sizing unit: a device pool at a
        fraction of it forces eviction/preemption/tiering."""
        toks = self.sessions * self.prefix_len
        for e in self.entries:
            if e.tokens is not None:
                toks += len(e.tokens) + int(e.max_new_tokens)
            elif e.session is not None:
                toks += int(e.tail_len) + int(e.max_new_tokens)
            else:
                toks += int(e.prompt_len) + int(e.max_new_tokens)
        return toks

    # ------------------------------------------------------- materialize
    def _prefix_tokens(self, session: int) -> np.ndarray:
        rng = np.random.default_rng([self.seed, _SESSION_SALT, int(session)])
        return rng.integers(0, self.vocab, self.prefix_len, dtype=np.int64)

    def prompt_for(self, index: int) -> np.ndarray:
        """Deterministic int32 prompt for entry ``index`` (same tokens on
        every call, every process — the replay-determinism contract)."""
        e = self.entries[index]
        if e.tokens is not None:
            return np.asarray(e.tokens, np.int32)
        rng = np.random.default_rng([self.seed, _TAIL_SALT, int(index)])
        if e.session is not None:
            tail = rng.integers(0, self.vocab, int(e.tail_len),
                                dtype=np.int64)
            return np.concatenate(
                [self._prefix_tokens(e.session), tail]).astype(np.int32)
        return rng.integers(0, self.vocab, int(e.prompt_len),
                            dtype=np.int64).astype(np.int32)

    def requests(self):
        """Materialize ``[(Request, TraceEntry), ...]`` in arrival order
        (import is local: trace files must load in jax-free tooling)."""
        from ..inference.serving import Request

        return [(Request(uid=e.uid, prompt=self.prompt_for(i),
                         max_new_tokens=e.max_new_tokens,
                         temperature=e.temperature, top_k=e.top_k,
                         top_p=e.top_p, seed=e.seed), e)
                for i, e in enumerate(self.entries)]

    def submit_all(self, target, eos_token_id=None) -> list:
        """Replay the arrival order into ``target`` (engine or router)
        ``submit()``; returns the handles.  A recorded per-entry
        ``eos_token_id`` wins over the call-level default — replay must
        stop early exactly where the recorded traffic did."""
        return [target.submit(req, priority=e.priority,
                              slo_class=e.slo_class,
                              eos_token_id=e.eos_token_id
                              if e.eos_token_id is not None
                              else eos_token_id)
                for req, e in self.requests()]

    # ---------------------------------------------------------- persist
    def to_dict(self) -> Dict[str, Any]:
        return {"version": TRACE_VERSION, "vocab": self.vocab,
                "seed": self.seed, "prefix_len": self.prefix_len,
                "meta": self.meta,
                "entries": [e.to_dict() for e in self.entries]}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ServingTrace":
        if int(d.get("version", 1)) > TRACE_VERSION:
            raise ValueError(
                f"trace version {d['version']} is newer than this "
                f"reader ({TRACE_VERSION})")
        return cls(vocab=d["vocab"], seed=d.get("seed", 0),
                   prefix_len=d.get("prefix_len", 0),
                   entries=[TraceEntry.from_dict(e)
                            for e in d.get("entries", [])],
                   meta=d.get("meta"))

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2, default=str)
        return path

    @classmethod
    def load(cls, path: str) -> "ServingTrace":
        with open(path) as f:
            return cls.from_dict(json.load(f))


def sessions_trace(n_requests: int, *, vocab: int, seed: int = 0,
                   sessions: int = 0, prefix_len: int = 0,
                   tail_range: Tuple[int, int] = (16, 64),
                   new_range: Tuple[int, int] = (8, 32),
                   slo_classes: Optional[Sequence[Optional[str]]] = None,
                   temperature: float = 0.0, top_k: int = 0,
                   top_p: float = 1.0) -> ServingTrace:
    """The BENCH_r09 returning-session workload as a :class:`ServingTrace`:
    ``sessions`` distinct shared prefixes dealt round-robin (request ``i``
    returns to session ``i % sessions`` with a fresh tail), per-request
    tail/decode budgets drawn deterministically from ``seed``.
    ``sessions=0`` produces a sessionless mixed trace with prompt lengths
    in ``tail_range``.  ``temperature > 0`` makes every request sampled
    with the given params and a per-request sampling seed drawn from the
    SAME deterministic stream — the trace file fully determines the
    sampled token streams (the engine's counter-based PRNG is keyed only
    by request seed + emission position)."""
    rng = np.random.default_rng([int(seed), 39916801])
    classes = list(slo_classes or [None])
    entries = []
    for i in range(int(n_requests)):
        tail = int(rng.integers(tail_range[0], tail_range[1] + 1))
        mnew = int(rng.integers(new_range[0], new_range[1] + 1))
        samp_seed = int(rng.integers(1, 2 ** 31 - 1)) \
            if float(temperature) > 0 else 0
        entries.append(TraceEntry(
            uid=i, max_new_tokens=mnew,
            session=(i % sessions) if sessions else None,
            tail_len=tail if sessions else 0,
            prompt_len=0 if sessions else tail,
            slo_class=classes[i % len(classes)],
            temperature=float(temperature), top_k=int(top_k),
            top_p=float(top_p), seed=samp_seed))
    return ServingTrace(vocab=vocab, seed=seed,
                        prefix_len=prefix_len if sessions else 0,
                        entries=entries,
                        meta={"generator": "sessions_trace",
                              "sessions": int(sessions),
                              "tail_range": list(tail_range),
                              "new_range": list(new_range),
                              "temperature": float(temperature)})


class TraceRecorder:
    """Capture a replayable trace from a live engine or router.

    ``attach(target)`` installs this recorder as the target's
    ``_submit_observer``; every subsequent ``submit()`` appends one
    verbatim-token entry in arrival order.  ``trace()`` snapshots the
    recording; ``detach()`` removes the hook.  One recorder per target
    (attaching over a foreign observer raises — silently dropping
    someone else's recording would be worse than failing) — unless
    ``chain=True``, which wraps the prior observer instead: the
    incumbent keeps seeing every submit FIRST, this recorder second,
    and ``detach()`` restores the incumbent (the incident recorder's
    always-on capture must not evict a user's own recording)."""

    def __init__(self, vocab: int):
        self.vocab = int(vocab)
        self.entries: List[TraceEntry] = []
        #: (target, previous observer, installed observer) per attach
        self._targets: list = []

    def attach(self, target, chain: bool = False) -> "TraceRecorder":
        current = getattr(target, "_submit_observer", "missing")
        if current == "missing":
            raise TypeError(
                f"{type(target).__name__} has no _submit_observer hook — "
                "expected a ServingEngine or ReplicaRouter")
        if current is not None and current != self._observe:
            if not chain:
                raise RuntimeError(
                    f"{type(target).__name__} already has a submit "
                    "observer attached — detach it first (or attach "
                    "with chain=True)")
            prev = current

            def chained(request, *, priority=0, slo_class=None,
                        eos_token_id=None):
                prev(request, priority=priority, slo_class=slo_class,
                     eos_token_id=eos_token_id)
                self._observe(request, priority=priority,
                              slo_class=slo_class,
                              eos_token_id=eos_token_id)

            target._submit_observer = chained
            self._targets.append((target, prev, chained))
            return self
        target._submit_observer = self._observe
        self._targets.append((target, None, self._observe))
        return self

    def detach(self) -> None:
        for t, prev, installed in self._targets:
            if getattr(t, "_submit_observer", None) == installed:
                t._submit_observer = prev
        self._targets = []

    def _observe(self, request, *, priority=0, slo_class=None,
                 eos_token_id=None) -> None:
        self.entries.append(TraceEntry(
            uid=request.uid,
            max_new_tokens=int(request.max_new_tokens),
            slo_class=slo_class, priority=int(priority),
            eos_token_id=None if eos_token_id is None else int(eos_token_id),
            tokens=[int(t) for t in np.asarray(request.prompt).reshape(-1)],
            temperature=float(getattr(request, "temperature", 0.0)),
            top_k=int(getattr(request, "top_k", 0)),
            top_p=float(getattr(request, "top_p", 1.0)),
            seed=int(getattr(request, "seed", 0))))

    def __len__(self) -> int:
        return len(self.entries)

    def trace(self, meta: Optional[Dict[str, Any]] = None) -> ServingTrace:
        return ServingTrace(vocab=self.vocab, entries=list(self.entries),
                            meta={"recorded": True, **(meta or {})})


# --------------------------------------------------------------- fitting
def _counter_total(snapshot: Dict[str, Any], name: str) -> float:
    """Sum a counter family over all its labeled series (0.0 if the
    family never registered)."""
    fam = snapshot.get(name)
    if not fam:
        return 0.0
    return float(sum(s.get("value", 0.0) for s in fam.get("series", [])))


def _slo_mix(snapshot: Dict[str, Any]) -> Dict[str, float]:
    """Observed ``slo_class`` request mix (empty when untracked)."""
    fam = snapshot.get("serving_slo_requests_total")
    out: Dict[str, float] = {}
    for s in (fam or {}).get("series", []):
        cls = s.get("labels", {}).get("slo_class")
        if cls:
            out[cls] = out.get(cls, 0.0) + float(s.get("value", 0.0))
    total = sum(out.values())
    return {c: v / total for c, v in out.items()} if total else {}


def fit_trace(snapshot: Dict[str, Any], *, vocab: int, n_requests: int = 64,
              seed: int = 0, block_size: int = 32,
              spread: float = 0.25) -> ServingTrace:
    """Fit a synthetic :class:`ServingTrace` to a telemetry snapshot
    (``engine.metrics.snapshot()`` / the ``/stats`` scrape's
    ``registry`` section).

    The registry carries exact totals, so the first moments are exact:
    mean prompt length = ``serving_prompt_tokens_total / admitted`` and
    mean decode budget = ``serving_generated_tokens_total / finished``.
    The session structure is fitted: in the steady state of an
    ``S``-session round-robin workload, roughly every request past each
    session's first admission hits its block-aligned shared prefix, so
    the expected hit rate is ``(1 - S/N) * prefix_blocks*B / mean_prompt``
    — :func:`fit_trace` grid-searches ``(S, prefix_blocks)`` for the
    closest match to the observed ``serving_prefix_hit_tokens_total /
    serving_prompt_tokens_total`` (deterministic tie-break: smaller
    error, then longer prefix, then fewer sessions).  Per-request
    tail/decode lengths spread ``±spread`` uniformly around the fitted
    means; the ``slo_class`` mix replays the observed
    ``serving_slo_requests_total`` proportions round-robin."""
    admitted = _counter_total(snapshot, "serving_requests_admitted_total")
    finished = _counter_total(snapshot, "serving_requests_finished_total")
    prompt_tokens = _counter_total(snapshot, "serving_prompt_tokens_total")
    hit_tokens = _counter_total(snapshot, "serving_prefix_hit_tokens_total")
    gen_tokens = _counter_total(snapshot, "serving_generated_tokens_total")
    if admitted < 1 or prompt_tokens < 1:
        raise ValueError(
            "snapshot records no admitted traffic "
            "(serving_requests_admitted_total / serving_prompt_tokens_total"
            " empty) — nothing to fit a trace to")
    mean_prompt = prompt_tokens / admitted
    mean_new = max(1.0, gen_tokens / finished) if finished else 16.0
    observed_hit = hit_tokens / prompt_tokens

    n = int(n_requests)
    best = None  # (err, -prefix_len, sessions)
    if observed_hit > 0:
        max_pb = max(1, int((mean_prompt - 1) // block_size))
        for s in range(1, n + 1):
            for pb in range(1, max_pb + 1):
                pl = pb * block_size
                predicted = max(0.0, 1.0 - s / n) * pl / mean_prompt
                key = (abs(predicted - observed_hit), -pl, s)
                if best is None or key < best[0]:
                    best = (key, s, pl)
    if best is not None:
        _, sessions, prefix_len = best
    else:
        sessions, prefix_len = 0, 0

    mean_tail = max(1.0, mean_prompt - prefix_len)
    mix = _slo_mix(snapshot)
    classes: List[Optional[str]] = []
    if mix:
        # integer class counts by largest remainder...
        counts = {c: int(n * f) for c, f in mix.items()}
        rem = sorted(mix, key=lambda c: -(n * mix[c] - counts[c]))
        for c in rem:
            if sum(counts.values()) >= n:
                break
            counts[c] += 1
        # ...then INTERLEAVED proportionally (always pick the most
        # under-served class) — a budgeted trace.slice(b) replay must
        # see the same mix as the full trace, not one class per block
        filled = {c: 0 for c in counts if counts[c]}
        for _ in range(n):
            c = min(filled, key=lambda c: (filled[c] / counts[c], c))
            filled[c] += 1
            classes.append(c)

    rng = np.random.default_rng([int(seed), 2147483629])
    lo, hi = 1.0 - spread, 1.0 + spread
    entries = []
    for i in range(n):
        tail = max(1, int(round(mean_tail * rng.uniform(lo, hi))))
        mnew = max(1, int(round(mean_new * rng.uniform(lo, hi))))
        entries.append(TraceEntry(
            uid=i, max_new_tokens=mnew,
            session=(i % sessions) if sessions else None,
            tail_len=tail if sessions else 0,
            prompt_len=0 if sessions else tail,
            slo_class=classes[i % len(classes)] if classes else None))
    return ServingTrace(
        vocab=vocab, seed=seed, prefix_len=prefix_len, entries=entries,
        meta={"fitted": True, "observed_hit_rate": observed_hit,
              "mean_prompt": mean_prompt, "mean_new": mean_new,
              "fitted_sessions": sessions, "fitted_prefix_len": prefix_len,
              "slo_mix": mix})
