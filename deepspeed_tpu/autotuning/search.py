"""Search cores shared by the training and serving autotuners.

Two searchers live here:

 - :func:`run_candidates` — the measured sequential loop (best-feasible
   tracking + early stopping) both the training ``Autotuner``'s
   gridsearch mode and its staged coordinate descent drive.  It used to
   exist twice inside ``autotuner.py``; this is the shared copy.
 - :class:`SuccessiveHalving` — the serving tuner's search: run EVERY
   admissible candidate at a short replay budget, keep the top ``1/eta``
   by score, multiply the budget by ``eta``, repeat until the full
   budget (or one survivor).  Serving trials are expensive (an engine
   build + a trace replay each) and the knob space is wide but shallow —
   halving spends the trial budget where cheap short replays already
   rank candidates, and only the finalists earn full-length runs.

The halving searcher is **deterministic**: given the same candidate
list and the same per-trial records it visits the same (config, budget)
pairs in the same order, survivors tie-break by candidate order, and the
whole run is resumable — every completed trial is appended to
``<results_dir>/exps.json`` *as it finishes*, and a re-run with
``resume=True`` replays completed trials from that file instead of
re-measuring them (mid-rung interruptions included).  ``max_trials``
bounds *executed* (non-resumed) objective calls; budget accounting in
the returned summary counts replayed requests per rung.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["config_key", "rank_results", "run_candidates",
           "SuccessiveHalving"]


def config_key(config: Dict[str, Any]) -> str:
    """Canonical identity of a candidate (dict-order independent)."""
    return json.dumps(config, sort_keys=True, default=str)


def rank_results(results: Sequence[Dict[str, Any]],
                 metric: str = "throughput") -> List[Dict[str, Any]]:
    """Feasible records, best metric first (the report table order).
    Ties keep input (arrival) order — ranking stays deterministic."""
    return sorted((r for r in results if r.get("feasible")),
                  key=lambda r: -float(r[metric]))


def run_candidates(cands: Sequence[Any], run_fn: Callable[[Any], dict], *,
                   metric: str = "throughput", early_stopping: int = 0,
                   skip: Optional[Callable[[Any], bool]] = None
                   ) -> Optional[Dict[str, Any]]:
    """Measure candidates in order, tracking the best feasible record.

    ``run_fn(cand)`` returns a record with ``feasible`` and ``metric``;
    recording/logging side effects belong inside it.  ``skip(cand)``
    prunes before measuring.  After ``early_stopping`` consecutive
    measured-feasible records that fail to improve the incumbent, the
    loop ends (0 = never).  Returns the best feasible record or None.
    """
    best: Optional[Dict[str, Any]] = None
    stale = 0
    for cand in cands:
        if skip is not None and skip(cand):
            continue
        rec = run_fn(cand)
        if not rec.get("feasible"):
            continue
        if best is None or rec[metric] > best[metric]:
            best, stale = rec, 0
        else:
            stale += 1
            if early_stopping and stale >= early_stopping:
                break
    return best


class SuccessiveHalving:
    """Constraint-aware successive halving over explicit candidates.

    Parameters
    ----------
    eta:         keep ``ceil(n/eta)`` survivors per rung; budgets also
                 multiply by ``eta``.
    min_budget:  rung-0 per-trial budget (trace entries replayed).
    max_budget:  final-rung budget (the full trace length).
    max_trials:  bound on *executed* objective calls (resumed trials are
                 free); exhausting it ends the search with the best
                 record measured so far and ``exhausted=True``.
    results_dir: where ``exps.json`` persists (None = no persistence,
                 no resume).
    metric:      record key to rank on (higher is better).
    """

    def __init__(self, *, eta: int = 2, min_budget: int, max_budget: int,
                 max_trials: Optional[int] = None,
                 results_dir: Optional[str] = None,
                 metric: str = "throughput"):
        if eta < 2:
            raise ValueError(f"eta must be >= 2, got {eta}")
        if not 1 <= min_budget <= max_budget:
            raise ValueError(
                f"need 1 <= min_budget ({min_budget}) <= max_budget "
                f"({max_budget})")
        self.eta = int(eta)
        self.min_budget = int(min_budget)
        self.max_budget = int(max_budget)
        self.max_trials = max_trials if max_trials is None \
            else int(max_trials)
        self.results_dir = results_dir
        self.metric = metric
        self.results: List[Dict[str, Any]] = []

    # ------------------------------------------------------ persistence
    def _exps_path(self) -> Optional[str]:
        if self.results_dir is None:
            return None
        return os.path.join(self.results_dir, "exps.json")

    def _load_cache(self) -> Dict[Tuple[str, int], Dict[str, Any]]:
        path = self._exps_path()
        if path is None or not os.path.exists(path):
            return {}
        with open(path) as f:
            prior = json.load(f)
        return {(config_key(r["config"]), int(r["budget"])): r
                for r in prior if "budget" in r}

    def _persist(self) -> None:
        path = self._exps_path()
        if path is None:
            return
        os.makedirs(self.results_dir, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.results, f, indent=2, default=str)

    # ------------------------------------------------------------- run
    def run(self, candidates: Sequence[Dict[str, Any]],
            objective: Callable[[Dict[str, Any], int], Dict[str, Any]],
            *, resume: bool = False) -> Dict[str, Any]:
        """Search.  ``objective(config, budget)`` returns a record with
        at least ``feasible`` (bool) and, when feasible, ``metric``;
        the searcher stamps ``rung``/``budget`` onto it.  Returns::

            {"best": record | None, "results": [records...],
             "rungs": [{rung, budget, candidates, feasible, resumed}...],
             "trials_executed": int, "trials_total": int,
             "budget_spent": int, "exhausted": bool}
        """
        pool = []
        seen = set()
        for cfg in candidates:
            k = config_key(cfg)
            if k not in seen:
                seen.add(k)
                pool.append(dict(cfg))
        if not pool:
            raise ValueError("successive halving needs >= 1 candidate")
        cache = self._load_cache() if resume else {}
        self.results = []
        rungs: List[Dict[str, Any]] = []
        executed = spent = 0
        rung, budget = 0, self.min_budget
        exhausted = False
        best: Optional[Dict[str, Any]] = None
        while True:
            ranked: List[Tuple[float, int, Dict[str, Any]]] = []
            resumed_here = 0
            for idx, cfg in enumerate(pool):
                key = (config_key(cfg), budget)
                rec = cache.get(key)
                if rec is not None:
                    resumed_here += 1
                    rec = dict(rec)
                else:
                    if self.max_trials is not None and \
                            executed >= self.max_trials:
                        exhausted = True
                        break
                    rec = dict(objective(cfg, budget))
                    executed += 1
                    spent += budget
                rec.update(config=cfg, rung=rung, budget=budget,
                           stage=f"rung{rung}")
                self.results.append(rec)
                self._persist()
                if rec.get("feasible"):
                    ranked.append((float(rec[self.metric]), idx, rec))
            ranked.sort(key=lambda t: (-t[0], t[1]))
            if ranked:
                # the deepest rung with any feasible record names the
                # winner — scores at different budgets are not comparable
                best = ranked[0][2]
            rungs.append({"rung": rung, "budget": budget,
                          "candidates": len(pool),
                          "feasible": len(ranked),
                          "resumed": resumed_here})
            if exhausted or budget >= self.max_budget or len(ranked) <= 1:
                break
            keep = max(1, math.ceil(len(ranked) / self.eta))
            pool = [rec["config"] for _, _, rec in ranked[:keep]]
            budget = min(budget * self.eta, self.max_budget)
            rung += 1
        return {"best": best, "results": self.results, "rungs": rungs,
                "trials_executed": executed,
                "trials_total": len(self.results),
                "budget_spent": spent, "exhausted": exhausted}
