"""Shared autotuning artifact writers (training AND serving).

Both tuners emit the same three artifacts into their ``results_dir`` —
the reference's ``autotuning/`` layout, kept schema-identical across the
two subsystems so dashboards and CI read one format:

 - ``exps.json``: every trial record, arrival order.  Common keys:
   ``config`` (the overrides/kwargs measured), ``feasible``,
   ``throughput`` (tok/s — the ranking metric), ``stage`` (coordinate-
   descent stage name or ``rungN``), plus per-subsystem extras
   (``step_s``/``loss`` for training; ``budget``/``rung``/``wall_s``/
   ``slo_attainment``/``parity`` for serving; ``error`` when
   infeasible).
 - ``best_config.json``: a ready-to-use config dict — merged DeepSpeed
   JSON config for training, ``init_serving`` kwargs for serving
   (``init_serving(model, **json.load(...))`` must just work).
 - ``report.md``: the ranked markdown table (:func:`render_table`) plus
   any subsystem sections (infeasible summary, constraint-pruning
   counts, the serving tuner's predicted-vs-measured block).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

from .search import rank_results

__all__ = ["write_json", "write_exps", "write_best_config",
           "render_table", "write_report_md", "write_results"]


def write_json(path: str, obj: Any) -> str:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(obj, f, indent=2, default=str)
    return path


def write_exps(results_dir: str, results: Sequence[Dict[str, Any]]) -> str:
    return write_json(os.path.join(results_dir, "exps.json"), list(results))


def write_best_config(results_dir: str, config: Dict[str, Any]) -> str:
    return write_json(os.path.join(results_dir, "best_config.json"), config)


def _detail(rec: Dict[str, Any]) -> str:
    """The per-record detail cell: step latency for training trials,
    rung/budget for serving trials."""
    if rec.get("step_s") is not None:
        return f"{1e3 * rec['step_s']:.1f} ms/step"
    if rec.get("budget") is not None:
        return f"budget {rec['budget']}"
    return "-"


def render_table(results: Sequence[Dict[str, Any]],
                 metric: str = "throughput") -> str:
    """Ranked feasible-trial table (shared columns, module docstring)."""
    lines = ["| rank | stage | config | tok/s | detail |",
             "|---|---|---|---|---|"]
    for i, r in enumerate(rank_results(results, metric), 1):
        lines.append(
            f"| {i} | {r.get('stage', '-')} | "
            f"`{json.dumps(r['config'], default=str)}` | "
            f"{float(r[metric]):.0f} | {_detail(r)} |")
    return "\n".join(lines) + "\n"


def write_report_md(results_dir: str, results: Sequence[Dict[str, Any]], *,
                    metric: str = "throughput",
                    title: str = "Autotuning report",
                    extra_sections: Optional[Sequence[str]] = None) -> str:
    body = [f"# {title}", "", render_table(results, metric)]
    infeasible = [r for r in results if not r.get("feasible")]
    if infeasible:
        body.append(f"\n{len(infeasible)} infeasible experiment(s) "
                    "(OOM/invalid/constraint) — see exps.json.\n")
    for section in extra_sections or ():
        body.append("\n" + section.rstrip() + "\n")
    path = os.path.join(results_dir, "report.md")
    os.makedirs(results_dir, exist_ok=True)
    with open(path, "w") as f:
        f.write("\n".join(body))
    return path


def write_results(results_dir: str, results: Sequence[Dict[str, Any]],
                  best_config: Dict[str, Any], *,
                  metric: str = "throughput",
                  title: str = "Autotuning report",
                  extra_sections: Optional[Sequence[str]] = None
                  ) -> Dict[str, str]:
    """Write the full artifact trio; returns their paths."""
    return {
        "exps": write_exps(results_dir, results),
        "best_config": write_best_config(results_dir, best_config),
        "report": write_report_md(results_dir, results, metric=metric,
                                  title=title,
                                  extra_sections=extra_sections),
    }
