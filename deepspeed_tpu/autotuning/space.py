"""The serving knob space: per-knob domains + constraint predicates.

``init_serving`` has grown ~10 interacting knobs (ROADMAP item 5's "knob
explosion"); most combinations are either invalid (speculative decoding
in bucketed-prefill mode), physically impossible (a KV pool past the HBM
ceiling), or violate a checked contract (compile budget, HKV
divisibility).  Searching them naively wastes most of the trial budget
discovering what static reasoning already knows, so this module encodes
the space *with* its constraints:

 - :class:`ModelGeom`: the model geometry the KV block formulae need
   (layers, KV heads, head dim, KV dtype bytes) — from a live engine or
   a model config.
 - :func:`kv_pool_bytes` / :func:`compile_budget`: closed-form mirrors
   of the engine's own accounting (``ServingEngine._kv_footprint`` /
   the ctor's budget arithmetic) over a *candidate dict*, evaluated
   before anything is built.
 - :class:`ServingKnobSpace`: base config + per-knob domains →
   cartesian candidates, each screened by the ``CONSTRAINTS`` predicates;
   ``prune()`` reports how many candidates each constraint removed (the
   searcher's "pruned before any trial ran" accounting).  The special
   ``num_blocks`` value ``"mem"`` resolves to the largest pool that fits
   ``mem_ceiling_bytes`` at the candidate's own ``block_size``/
   ``quantize`` — candidates trade block granularity against pool depth
   under one fixed memory envelope, the way a real chip does.

Every constraint here has a matching *loud* ctor validation in
``ServingEngine``/``init_serving`` (audited by
``tests/unit/test_serving_autotune.py``): pruning is an optimization,
not the safety net — a config that somehow slips through still fails
with a diagnosis naming the knob, never a mid-trial crash.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["ModelGeom", "ServingKnobSpace", "kv_pool_bytes",
           "compile_budget", "workload_space", "DEFAULT_DOMAINS",
           "CONSTRAINTS", "BASE_SERVING_CONFIG", "DECODE_STEPS_MAX"]

#: the verify kernel's widest speculative window (K+1 <= this);
#: mirrored from ops/decode_attention.py without importing jax
VERIFY_T_MAX = 16

#: ``init_serving`` serving-level defaults — the hand-picked config every
#: search starts from (and the yardstick the winner must beat)
BASE_SERVING_CONFIG: Dict[str, Any] = {
    "slots": 8,
    "max_seq_len": None,
    "block_size": 32,
    "num_blocks": None,
    "chunked_prefill": True,
    "prefill_chunk": 128,
    "prompt_buckets": None,
    "prefill_batch": 4,
    "prefix_caching": True,
    "spec_tokens": 0,
    "quantize": None,
    "host_blocks": 0,
    "swap_batch": 8,
    "role": "both",
    "nvme_blocks": 0,
    "nvme_high_watermark": 0.9,
    "replicas": 1,
    "prefill_workers": 0,
    "shard_kv": None,
    "topology": 1,
    "decode_steps": 1,
    "engine_mode": "replicas",
    "sp": 1,
    "resident_window_blocks": 0,
    "sampling": True,
    "spec_verifier": "rejection",
    "logit_masks": False,
    "trace_capacity": 16384,
}

#: conservative default domains — callers override per workload (the
#: bench lane, for one, adds trace-sized ``host_blocks`` choices)
DEFAULT_DOMAINS: Dict[str, Tuple[Any, ...]] = {
    "block_size": (16, 32, 64),
    "prefill_chunk": (64, 128, 256),
    "prefill_batch": (2, 4, 8),
    "spec_tokens": (0, 4),
    "decode_steps": (1, 4, 8),
}

#: widest fused-decode window the space searches; a larger K only adds
#: host-fence latency variance past the point where the Python loop is
#: already off the critical path (the fused while_loop program is ONE
#: compile for any K — the budget does not scale with it)
DECODE_STEPS_MAX = 64


@dataclasses.dataclass(frozen=True)
class ModelGeom:
    """KV-geometry inputs to the block formulae."""
    layers: int
    kv_heads: int
    head_dim: int
    dtype_bytes: int = 4          # KV pool element size (fp32=4, bf16=2)

    @classmethod
    def from_model_config(cls, mc, dtype_bytes: int = 4) -> "ModelGeom":
        heads = int(getattr(mc, "num_heads"))
        kv_heads = int(getattr(mc, "num_kv_heads", heads))
        head_dim = int(getattr(mc, "head_dim",
                               getattr(mc, "hidden_size") // heads))
        return cls(layers=int(mc.num_layers), kv_heads=kv_heads,
                   head_dim=head_dim, dtype_bytes=int(dtype_bytes))

    @classmethod
    def from_engine(cls, engine) -> "ModelGeom":
        """From a live ``init_inference`` engine (reads the engine's KV
        dtype — the serving pool is built in it)."""
        import jax.numpy as jnp

        return cls.from_model_config(
            engine.module.model_config,
            dtype_bytes=jnp.dtype(engine._config.jnp_dtype).itemsize)


def _blocks_per_seq(config: Dict[str, Any]) -> int:
    return int(math.ceil(int(config["max_seq_len"]) /
                         int(config["block_size"])))


def resolved_num_blocks(config: Dict[str, Any]) -> int:
    """``num_blocks`` with the engine's own default applied (``None`` =
    scratch + full residency for every slot)."""
    nb = config.get("num_blocks")
    if nb is None:
        return 1 + int(config["slots"]) * _blocks_per_seq(config)
    return int(nb)


def block_bytes(config: Dict[str, Any], geom: ModelGeom) -> int:
    """Bytes of ONE physical KV block under the candidate's knobs —
    the k + v payload leaves (``[L, NB, HKV, bs, hd]`` per leaf), plus
    the per-block bf16 scale rows (``[L, NB, HKV, bs]``) when the pool
    quantizes to int8 codes (``quantize`` includes ``kv8``)."""
    bs = int(config["block_size"])
    elems = geom.layers * geom.kv_heads * bs * geom.head_dim
    quant = str(config.get("quantize") or "")
    if "kv8" in quant:
        return 2 * (elems * 1 + geom.layers * geom.kv_heads * bs * 2)
    return 2 * elems * geom.dtype_bytes


def kv_pool_bytes(config: Dict[str, Any], geom: ModelGeom) -> int:
    """Device KV pool footprint of a candidate (block formula x pool
    depth) — the quantity ``mem_ceiling_bytes`` caps.  Host-tier blocks
    live in host DRAM and do not count against the device ceiling."""
    return resolved_num_blocks(config) * block_bytes(config, geom)


def compile_budget(config: Dict[str, Any]) -> int:
    """Mirror of the ctor's compiled-program budget: 2 chunked (prefill +
    decode / n-gram verify), buckets + 2 bucketed, + 2 swap programs with
    a host tier.  (A draft model would add 1; the space searches the
    zero-extra-programs n-gram proposer.)

    ``decode_steps > 1`` does NOT add a program: the fused multi-step
    while_loop REPLACES the per-token decode program (same sentry name,
    same budget slot), so the count is K-invariant.  ``engine_mode=
    "dp_tp"`` likewise compiles the same two programs — one dp-sharded
    decode instead of N per-replica copies.  ``nvme_blocks`` and ``role``
    add NOTHING: the NVMe tier spills/promotes through the host arena's
    existing two swap programs (the file I/O is host-side ``ops/aio``),
    and a role only gates which host-side scheduler phases run.
    ``sp > 1`` and ``resident_window_blocks > 0`` are likewise +0: the
    sp prefill reshapes the SAME chunked prefill program through
    shard_map, and the windowed decode/prefill bodies REPLACE the plain
    ones one-for-one (one extra traced operand, same sentry names).
    ``sampling`` / ``spec_verifier`` / ``logit_masks`` are +0 too: the
    per-slot sampling params (and the optional ``[slots, vocab]`` mask)
    ride as extra fixed-shape operands of the SAME programs, and the
    rejection verifier replaces the greedy matcher inside the one verify
    program."""
    if config.get("spec_tokens"):
        budget = 2
    elif config.get("chunked_prefill", True):
        budget = 2
    else:
        budget = len(config.get("prompt_buckets") or ()) + 2
    if config.get("host_blocks"):
        budget += 2
    return budget


# ---------------------------------------------------------- constraints
def _c_memory(config, space) -> Optional[str]:
    if space.mem_ceiling_bytes is None:
        return None
    got = kv_pool_bytes(config, space.geom)
    if got > space.mem_ceiling_bytes:
        return (f"kv pool {got} bytes exceeds the ceiling "
                f"{space.mem_ceiling_bytes} (num_blocks="
                f"{resolved_num_blocks(config)}, block_size="
                f"{config['block_size']}, quantize="
                f"{config.get('quantize')})")
    return None


def _c_compile(config, space) -> Optional[str]:
    got = compile_budget(config)
    if got > space.max_programs:
        return (f"compile budget {got} exceeds max_programs="
                f"{space.max_programs}")
    return None


def _c_shard_kv(config, space) -> Optional[str]:
    tp = int(config.get("topology") or 1)
    if config.get("shard_kv") and tp > 1 and space.geom.kv_heads % tp:
        return (f"shard_kv=True but kv_heads={space.geom.kv_heads} does "
                f"not divide tp={tp}")
    return None


def _c_spec_bucketed(config, space) -> Optional[str]:
    if config.get("spec_tokens") and not config.get("chunked_prefill",
                                                    True):
        return "spec_tokens > 0 requires chunked-prefill mode"
    return None


def _c_spec_window(config, space) -> Optional[str]:
    k = int(config.get("spec_tokens") or 0)
    if k and k + 1 > VERIFY_T_MAX:
        return (f"spec_tokens={k} verify window exceeds the kernel max "
                f"{VERIFY_T_MAX}")
    return None


def _c_tiered_prefix(config, space) -> Optional[str]:
    if config.get("host_blocks") and not (
            config.get("chunked_prefill", True)
            and config.get("prefix_caching", True)):
        return ("host_blocks > 0 requires chunked prefill with "
                "prefix_caching=True")
    return None


def _c_swap_batch(config, space) -> Optional[str]:
    hb = int(config.get("host_blocks") or 0)
    sb = int(config.get("swap_batch") or 0)
    if hb and (sb < 1 or sb > hb):
        return f"swap_batch={sb} outside [1, host_blocks={hb}]"
    return None


def _c_pool_min(config, space) -> Optional[str]:
    if int(config.get("block_size") or 0) < 1:
        return None                    # positive_knobs owns this failure
    need = 1 + _blocks_per_seq(config)
    win = int(config.get("resident_window_blocks") or 0)
    if win:
        # a windowed slot never holds more than landmark + window +
        # one in-flight prefill chunk on device — the pool floor drops
        # accordingly (the ctor applies the same min())
        chunk_blocks = int(math.ceil(int(config.get("prefill_chunk") or 1)
                                     / int(config["block_size"])))
        need = min(need, 1 + 1 + win + chunk_blocks)
    if resolved_num_blocks(config) < need:
        return (f"num_blocks={resolved_num_blocks(config)} cannot hold "
                f"one {'resident window' if win else 'full sequence'} "
                f"({need} blocks incl. scratch)")
    return None


def _c_positive(config, space) -> Optional[str]:
    for k in ("slots", "prefill_batch", "block_size"):
        if int(config.get(k) or 0) < 1:
            return f"{k} must be >= 1, got {config.get(k)}"
    return None


def _c_decode_steps(config, space) -> Optional[str]:
    k = int(config.get("decode_steps") or 1)
    if k < 1 or k > DECODE_STEPS_MAX:
        return (f"decode_steps={k} outside [1, {DECODE_STEPS_MAX}]")
    if k > 1 and int(config.get("spec_tokens") or 0):
        # not invalid at the ctor (spec dispatch wins; K is inert) but a
        # duplicate of the K=1 candidate — prune so the trial budget
        # never pays for the same config twice
        return (f"decode_steps={k} is inert under spec_tokens="
                f"{config['spec_tokens']} (speculative dispatch wins) — "
                "duplicate of the decode_steps=1 candidate")
    return None


def _c_role_tiered(config, space) -> Optional[str]:
    role = config.get("role") or "both"
    if role not in ("prefill", "decode", "both"):
        return (f"role={role!r} — expected 'prefill', 'decode' or 'both'")
    if role != "both" and not int(config.get("host_blocks") or 0):
        return (f"role={role!r} needs the tiered KV cache "
                "(host_blocks > 0): the prefill→decode handoff travels "
                "as a host-tier chain export/import")
    return None


def _c_prefill_ratio(config, space) -> Optional[str]:
    pw = int(config.get("prefill_workers") or 0)
    reps = int(config.get("replicas") or 1)
    if pw < 0:
        return f"prefill_workers must be >= 0, got {pw}"
    if pw and pw >= reps:
        return (f"prefill_workers={pw} with replicas={reps}: the "
                "prefill_workers:decode_workers ratio must keep at least "
                "one worker on each side")
    if pw and not int(config.get("host_blocks") or 0):
        return ("a disaggregated fleet (prefill_workers > 0) needs "
                "host_blocks > 0 on every replica — the handoff is a "
                "host-tier chain pull")
    return None


def _c_nvme_tier(config, space) -> Optional[str]:
    nb = int(config.get("nvme_blocks") or 0)
    if nb < 0:
        return f"nvme_blocks must be >= 0, got {nb}"
    if nb and not int(config.get("host_blocks") or 0):
        return (f"nvme_blocks={nb} needs the host tier above it "
                "(host_blocks > 0)")
    return None


def _c_nvme_watermark(config, space) -> Optional[str]:
    wm = float(config.get("nvme_high_watermark") or 0.9)
    if not (0.0 < wm <= 1.0):
        return f"nvme_high_watermark={wm} outside (0, 1]"
    hb = int(config.get("host_blocks") or 0)
    sb = int(config.get("swap_batch") or 0)
    if int(config.get("nvme_blocks") or 0) and hb and sb > int(wm * hb):
        return (f"swap_batch={sb} exceeds the host-arena watermark budget "
                f"int({wm} * {hb}) — one promotion batch would "
                "immediately re-spill its own head")
    return None


def _c_engine_mode(config, space) -> Optional[str]:
    mode = config.get("engine_mode") or "replicas"
    if mode not in ("replicas", "dp_tp"):
        return (f"engine_mode={mode!r} — expected 'replicas' or 'dp_tp'")
    if mode != "dp_tp":
        return None
    if not config.get("chunked_prefill", True):
        return "engine_mode='dp_tp' requires chunked-prefill mode"
    for knob in ("spec_tokens", "host_blocks"):
        if int(config.get(knob) or 0):
            return (f"engine_mode='dp_tp' does not compose with "
                    f"{knob}={config[knob]} (v1: dp groups would need "
                    "cross-group scheduling)")
    if config.get("quantize"):
        return ("engine_mode='dp_tp' does not compose with quantize="
                f"{config['quantize']!r}")
    if config.get("prefix_caching", True):
        return ("engine_mode='dp_tp' requires prefix_caching=False "
                "(shared trie blocks cannot cross dp pool groups)")
    return None


def _c_sp(config, space) -> Optional[str]:
    sp = int(config.get("sp") or 1)
    if sp < 1:
        return f"sp must be >= 1, got {sp}"
    if sp == 1:
        return None
    if not config.get("chunked_prefill", True):
        return "sp > 1 requires chunked-prefill mode"
    chunk = int(config.get("prefill_chunk") or 0)
    if chunk % sp:
        return (f"prefill_chunk={chunk} must divide by sp={sp} — every "
                "rank owns an equal sequence shard of the chunk")
    if int(config.get("spec_tokens") or 0):
        return (f"sp={sp} does not compose with spec_tokens="
                f"{config['spec_tokens']} (v1: the verify window is not "
                "sequence-sharded)")
    if (config.get("engine_mode") or "replicas") == "dp_tp":
        return (f"sp={sp} does not compose with engine_mode='dp_tp' "
                "(v1: the mesh carries either dp or sp, not both)")
    return None


def _c_resident_window(config, space) -> Optional[str]:
    win = int(config.get("resident_window_blocks") or 0)
    if win < 0:
        return f"resident_window_blocks must be >= 0, got {win}"
    if not win:
        return None
    if not (config.get("chunked_prefill", True)
            and config.get("prefix_caching", True)):
        return ("resident_window_blocks > 0 requires chunked prefill "
                "with prefix_caching=True (slid blocks demote through "
                "the chain-keyed host tier)")
    if not int(config.get("host_blocks") or 0):
        return ("resident_window_blocks > 0 needs the host tier "
                "(host_blocks > 0) to hold demoted cold context")
    for knob in ("spec_tokens",):
        if int(config.get(knob) or 0):
            return (f"resident_window_blocks={win} does not compose "
                    f"with {knob}={config[knob]} (v1: the verify span "
                    "assumes a contiguous block table)")
    if int(config.get("decode_steps") or 1) > 1:
        return (f"resident_window_blocks={win} does not compose with "
                f"decode_steps={config['decode_steps']} (v1: the fused "
                "loop cannot slide the window mid-program)")
    if (config.get("engine_mode") or "replicas") == "dp_tp":
        return (f"resident_window_blocks={win} does not compose with "
                "engine_mode='dp_tp'")
    if int(config.get("sp") or 1) > 1:
        return (f"resident_window_blocks={win} does not compose with "
                f"sp={config['sp']} (v1: windowing is decode-side, sp "
                "is prefill-side — composition is untested)")
    if int(config.get("block_size") or 0) >= 1:
        chunk_blocks = int(math.ceil(int(config.get("prefill_chunk")
                                         or 1)
                                     / int(config["block_size"])))
        if win < chunk_blocks + 1:
            return (f"resident_window_blocks={win} smaller than one "
                    f"prefill chunk + 1 ({chunk_blocks + 1}): the "
                    "window would slide past its own in-flight chunk")
    return None


def _c_spec_sampling(config, space) -> Optional[str]:
    verifier = config.get("spec_verifier") or "rejection"
    if verifier not in ("rejection", "greedy"):
        return (f"spec_verifier={verifier!r} — expected 'rejection' or "
                "'greedy'")
    if (int(config.get("spec_tokens") or 0)
            and config.get("sampling", True) and verifier == "greedy"):
        return ("speculative decoding on a sampling engine requires the "
                "rejection verifier (spec_verifier='rejection') — the "
                "greedy prefix-matcher would silently reshape sampled "
                "output distributions")
    return None


def _c_logit_masks(config, space) -> Optional[str]:
    if not config.get("logit_masks"):
        return None
    if not config.get("sampling", True):
        return ("logit_masks=True needs the sampling stack — constrained "
                "decoding applies the mask inside the sampler programs")
    if (config.get("engine_mode") or "replicas") == "dp_tp":
        return ("engine_mode='dp_tp' v1 excludes logit_masks — the "
                "dp-sharded decode program does not carry the "
                "[slots, vocab] mask operand")
    return None


#: ``(name, predicate)`` — predicate returns a violation message or None.
#: Each has a loud ctor-validation twin (module docstring).
CONSTRAINTS: Tuple[Tuple[str, Callable], ...] = (
    ("positive_knobs", _c_positive),
    ("kv_pool_memory", _c_memory),
    ("compile_budget", _c_compile),
    ("shard_kv_divisibility", _c_shard_kv),
    ("spec_bucketed_exclusive", _c_spec_bucketed),
    ("spec_window", _c_spec_window),
    ("tiered_needs_prefix_cache", _c_tiered_prefix),
    ("swap_batch_bounds", _c_swap_batch),
    ("role_needs_tiered_kv", _c_role_tiered),
    ("prefill_decode_ratio", _c_prefill_ratio),
    ("nvme_needs_host_tier", _c_nvme_tier),
    ("nvme_watermark_window", _c_nvme_watermark),
    ("pool_min_blocks", _c_pool_min),
    ("decode_steps_window", _c_decode_steps),
    ("engine_mode_exclusive", _c_engine_mode),
    ("sp_prefill_exclusive", _c_sp),
    ("resident_window_span", _c_resident_window),
    ("spec_sampling_needs_rejection", _c_spec_sampling),
    ("logit_masks_excludes_dp_tp", _c_logit_masks),
)


class ServingKnobSpace:
    """Base config + domains -> constraint-screened candidates.

    Parameters
    ----------
    geom:             :class:`ModelGeom` for the block formulae.
    max_seq_len:      the trace's required sequence budget (every
                      candidate carries it; a knob only via ``domains``).
    base:             overrides onto :data:`BASE_SERVING_CONFIG` — the
                      "hand-picked default" candidate 0 of every search.
    domains:          ``knob -> tuple of values`` (unlisted knobs stay at
                      the base value).  ``num_blocks`` accepts the
                      special value ``"mem"`` (module docstring);
                      ``host_blocks`` accepts ``"ws"`` — the trace's
                      unique working set in the candidate's OWN block
                      size, plus one sequence of slack (needs
                      ``ws_tokens``).
    mem_ceiling_bytes: device KV-pool byte ceiling (None = uncapped).
    max_programs:     compile-budget ceiling (sentry-aligned).
    """

    def __init__(self, geom: ModelGeom, *, max_seq_len: int,
                 base: Optional[Dict[str, Any]] = None,
                 domains: Optional[Dict[str, Sequence[Any]]] = None,
                 mem_ceiling_bytes: Optional[int] = None,
                 max_programs: int = 8,
                 ws_tokens: Optional[int] = None):
        self.geom = geom
        self.base = dict(BASE_SERVING_CONFIG)
        self.base["max_seq_len"] = int(max_seq_len)
        self.base.update(base or {})
        self.domains: Dict[str, Tuple[Any, ...]] = {
            k: tuple(v) for k, v in (domains if domains is not None
                                     else DEFAULT_DOMAINS).items()}
        unknown = set(self.domains) - set(self.base)
        if unknown:
            raise ValueError(
                f"domain(s) over unknown knob(s) {sorted(unknown)} — "
                f"knobs: {sorted(self.base)}")
        self.mem_ceiling_bytes = mem_ceiling_bytes
        self.max_programs = int(max_programs)
        self.ws_tokens = None if ws_tokens is None else int(ws_tokens)

    # ------------------------------------------------------- candidates
    def size(self) -> int:
        out = 1
        for v in self.domains.values():
            out *= len(v)
        return out

    def _resolve(self, config: Dict[str, Any]) -> Dict[str, Any]:
        if config.get("num_blocks") == "mem":
            if self.mem_ceiling_bytes is None:
                raise ValueError(
                    'num_blocks="mem" needs mem_ceiling_bytes')
            config["num_blocks"] = max(
                1, self.mem_ceiling_bytes // block_bytes(config, self.geom))
        if config.get("host_blocks") == "ws":
            if self.ws_tokens is None:
                raise ValueError('host_blocks="ws" needs ws_tokens')
            config["host_blocks"] = int(
                math.ceil(self.ws_tokens / int(config["block_size"]))
                + _blocks_per_seq(config))
        return config

    def default_config(self) -> Dict[str, Any]:
        return self._resolve(dict(self.base))

    def candidates(self) -> List[Dict[str, Any]]:
        """Cartesian product of the domains overlaid on the base,
        deduplicated, default config first — UNSCREENED (``prune()``
        applies the constraints and reports per-constraint counts)."""
        keys = sorted(self.domains)
        seen = set()
        out: List[Dict[str, Any]] = []
        default = self.default_config()
        for cfg in [default] + [
                self._resolve({**self.base,
                               **dict(zip(keys, combo))})
                for combo in itertools.product(
                    *(self.domains[k] for k in keys))]:
            key = tuple(sorted((k, repr(v)) for k, v in cfg.items()))
            if key not in seen:
                seen.add(key)
                out.append(cfg)
        return out

    # ------------------------------------------------------ constraints
    def check(self, config: Dict[str, Any]) -> List[Tuple[str, str]]:
        """``(constraint name, violation message)`` for every violated
        predicate (empty = admissible)."""
        out = []
        for name, pred in CONSTRAINTS:
            msg = pred(config, self)
            if msg:
                out.append((name, msg))
        return out

    def prune(self, candidates: Optional[List[Dict[str, Any]]] = None
              ) -> Tuple[List[Dict[str, Any]], Dict[str, int]]:
        """Screen candidates; returns ``(kept, pruned_by_constraint)``
        where the counts attribute each rejection to the FIRST violated
        constraint (a candidate is pruned once)."""
        if candidates is None:
            candidates = self.candidates()
        kept: List[Dict[str, Any]] = []
        pruned: Dict[str, int] = {name: 0 for name, _ in CONSTRAINTS}
        for cfg in candidates:
            bad = self.check(cfg)
            if bad:
                pruned[bad[0][0]] += 1
            else:
                kept.append(cfg)
        return kept, {k: v for k, v in pruned.items() if v}


def workload_space(geom: ModelGeom, trace, *, pool_frac: float = 0.0,
                   mem_ceiling_bytes: Optional[int] = None,
                   base: Optional[Dict[str, Any]] = None,
                   domains: Optional[Dict[str, Sequence[Any]]] = None
                   ) -> ServingKnobSpace:
    """A :class:`ServingKnobSpace` sized to a :class:`~deepspeed_tpu
    .autotuning.trace.ServingTrace` workload.

    ``pool_frac > 0`` applies the BENCH_r09 pool-pressure protocol: the
    device memory ceiling is set to the bytes of a default-``block_size``
    pool holding ``pool_frac`` of the trace's unique working set, the
    base ``num_blocks`` becomes ``"mem"`` (every candidate fills its own
    block geometry to the SAME byte envelope), and the ``host_blocks``
    domain offers the tiered escape hatch (0, or the full working set
    plus one sequence of slack — host DRAM is not under the device
    ceiling).  An explicit ``mem_ceiling_bytes`` overrides the
    ``pool_frac`` sizing.  With neither, the space is unpressured: the
    engine's default full-residency pool and no memory constraint."""
    base = dict(base or {})
    base.setdefault("max_seq_len", trace.max_total_len())
    bs = int(base.get("block_size", BASE_SERVING_CONFIG["block_size"]))
    ws_blocks = max(1, math.ceil(trace.working_set_tokens() / bs))
    probe = {**BASE_SERVING_CONFIG, **base}
    if mem_ceiling_bytes is None and pool_frac > 0:
        bps = _blocks_per_seq(probe)
        pressured = max(1 + bps, int(pool_frac * ws_blocks) + 1)
        mem_ceiling_bytes = pressured * block_bytes(probe, geom)
    if mem_ceiling_bytes is not None:
        base.setdefault("num_blocks", "mem")
        if domains is None:
            domains = dict(DEFAULT_DOMAINS)
            domains["host_blocks"] = (0, "ws")
    return ServingKnobSpace(geom, max_seq_len=base.pop("max_seq_len"),
                            base=base, domains=domains,
                            mem_ceiling_bytes=mem_ceiling_bytes,
                            ws_tokens=trace.working_set_tokens())
