"""Serving-autotuner trial execution: build → replay → gate → score.

One trial = one candidate config at one replay budget:

 1. **Build** a :class:`~deepspeed_tpu.inference.serving.ServingEngine`
    over the SHARED ``init_inference`` engine (weights are built once for
    the whole search) with ``debug_checks=True`` — the recompile sentry
    runs *strict*, so a candidate that would compile past its declared
    budget raises at trace time and the trial records an infeasible
    ``compile_budget`` constraint instead of silently burning programs.
 2. **Replay** the trace slice twice — a cold pass (compiles included)
    and a warm pass (the steady state: compile-warm, prefix-warm, host
    tier populated).  The warm pass's aggregate tok/s is the score; both
    walls are recorded.
 3. **Parity-gate** BOTH passes against the reference outputs (the
    default config run once per budget and cached): exact token equality
    for full-precision candidates, completion-token match rate >=
    ``min_token_match`` for quantized ones (int8 rounding may flip
    near-tie argmaxes — the PR 7 bounded-divergence contract).  A trial
    that fails parity is infeasible, never ranked.
 4. **SLO**: the per-class attainment report rides every record;
    ``min_slo_attainment`` turns it into a hard constraint and
    ``slo_penalty`` into a score multiplier
    (``score *= 1 - penalty * (1 - attainment)``).

:func:`tune_serving` wires it together: knob space → constraint pruning
→ successive halving → full-budget re-run of the winner AND the default
(the predicted-vs-measured block) → ``exps.json`` / ``best_config.json``
/ ``report.md`` artifacts.
"""

from __future__ import annotations

import gc
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..analysis.sentry import RetraceError
from ..utils.logging import log_dist
from . import report as report_mod
from .search import SuccessiveHalving
from .space import ModelGeom, ServingKnobSpace
from .trace import ServingTrace

__all__ = ["ParityError", "TrialRunner", "tune_serving"]

#: config keys forwarded to the ServingEngine ctor (``topology`` is an
#: ``init_serving``-level knob — the shared engine already has its mesh)
_SERVING_KEYS = (
    "slots", "max_seq_len", "prompt_buckets", "prefill_batch",
    "block_size", "num_blocks", "chunked_prefill", "prefill_chunk",
    "prefix_caching", "spec_tokens", "quantize", "host_blocks",
    "swap_batch", "ngram_max", "ngram_min", "shard_kv", "trace_capacity",
    "slo_targets", "peak_flops",
)


class ParityError(AssertionError):
    """A trial's replayed tokens diverged past the gate."""


def _completion_match(outs, ref, requests) -> float:
    """Per-token match rate over the COMPLETION region (prompts always
    agree — counting them would flatter the rate)."""
    match = total = 0
    for req, _ in requests:
        a = np.asarray(outs[req.uid]).reshape(-1)[len(req.prompt):]
        b = np.asarray(ref[req.uid]).reshape(-1)[len(req.prompt):]
        n = min(a.size, b.size)
        match += int(np.sum(a[:n] == b[:n]))
        total += n
    return match / total if total else 1.0


class TrialRunner:
    """The successive-halving objective (module docstring).

    Parameters
    ----------
    engine:          shared ``init_inference`` engine (one weight pytree
                     for the whole search).
    trace:           the :class:`ServingTrace` to replay; ``budget`` =
                     entries replayed (``trace.slice``).
    base_config:     the reference/default config trials are
                     parity-gated against.
    min_token_match: completion-token match-rate floor for quantized
                     candidates (full-precision candidates require 1.0).
    slo_penalty:     score multiplier weight on missed attainment.
    min_slo_attainment: hard attainment floor (None = off).
    """

    def __init__(self, engine, trace: ServingTrace, *,
                 base_config: Dict[str, Any],
                 min_token_match: float = 0.90,
                 slo_penalty: float = 0.0,
                 min_slo_attainment: Optional[float] = None):
        self.engine = engine
        self.trace = trace
        self.base_config = dict(base_config)
        self.min_token_match = float(min_token_match)
        self.slo_penalty = float(slo_penalty)
        self.min_slo_attainment = min_slo_attainment
        self._ref_outputs: Dict[int, Dict[Any, np.ndarray]] = {}
        self._ref_engine = None

    # ------------------------------------------------------------ build
    def build(self, config: Dict[str, Any]):
        from ..inference.serving import ServingEngine
        from ..parallel.topology import TP_AXIS

        # topology is an init_serving-level knob and trials share ONE
        # engine: a candidate asking for a different tp than the engine
        # carries would silently measure at the wrong parallelism (and
        # best_config.json would ship an unmeasured deployment) — fail
        # the trial with the diagnosis instead
        want_tp = int(config.get("topology") or 1)
        have_tp = int(dict(self.engine.mesh.shape).get(TP_AXIS, 1))
        if want_tp != have_tp:
            raise ValueError(
                f"candidate topology={want_tp} does not match the shared "
                f"search engine's tp={have_tp} — trials share one "
                "init_inference engine; build it at the topology you "
                "want to search (space base {'topology': ...})")
        kwargs = {k: config[k] for k in _SERVING_KEYS if k in config}
        return ServingEngine(self.engine, debug_checks=True, **kwargs)

    # ----------------------------------------------------------- replay
    def _replay(self, srv, budget: int):
        """One full pass over the trace slice through the incremental
        API (per-entry ``slo_class``/``priority`` ride along); returns
        ``(outputs, wall_s, generated_tokens)``."""
        sliced = self.trace.slice(budget)
        requests = sliced.requests()
        t0 = time.perf_counter()
        handles = [srv.submit(req, priority=e.priority,
                              slo_class=e.slo_class,
                              eos_token_id=e.eos_token_id)
                   for req, e in requests]
        while srv.step():
            pass
        wall = time.perf_counter() - t0
        outs = {h.uid: h.result(timeout=0) for h in handles}
        gen = sum(req.max_new_tokens for req, _ in requests)
        return outs, wall, gen, requests

    def reference(self, budget: int) -> Dict[Any, np.ndarray]:
        """Default-config outputs for this budget (cached; the ref
        engine lives across budgets so its compiles amortize)."""
        if budget not in self._ref_outputs:
            if self._ref_engine is None:
                self._ref_engine = self.build(self.base_config)
            outs, _, _, _ = self._replay(self._ref_engine, budget)
            self._ref_outputs[budget] = outs
        return self._ref_outputs[budget]

    def _gate_parity(self, config, outs, requests, budget) -> float:
        ref = self.reference(budget)
        rate = _completion_match(outs, ref, requests)
        exact = not config.get("quantize")
        floor = 1.0 if exact else self.min_token_match
        if rate < floor:
            gate = "the exact-parity gate" if exact else \
                f"min_token_match={self.min_token_match}"
            raise ParityError(
                f"completion token match {rate:.4f} below {gate}")
        return rate

    # -------------------------------------------------------- objective
    def __call__(self, config: Dict[str, Any], budget: int
                 ) -> Dict[str, Any]:
        rec: Dict[str, Any] = {"config": dict(config)}
        srv = None
        try:
            srv = self.build(config)
            outs_cold, wall_cold, gen, requests = self._replay(srv, budget)
            match_cold = self._gate_parity(config, outs_cold, requests,
                                           budget)
            outs_warm, wall_warm, _, _ = self._replay(srv, budget)
            match_warm = self._gate_parity(config, outs_warm, requests,
                                           budget)
            slo = srv.slo_report()
            atts = [min(c["ttft_attainment"], c["tpot_attainment"])
                    for c in slo.values() if c["requests"]]
            attainment = min(atts) if atts else 1.0
            if self.min_slo_attainment is not None and \
                    attainment < self.min_slo_attainment:
                rec.update(feasible=False, constraint="slo",
                           slo_attainment=attainment,
                           error=f"attainment {attainment:.3f} < "
                                 f"{self.min_slo_attainment}")
                return rec
            score = (gen / wall_warm) * (
                1.0 - self.slo_penalty * (1.0 - attainment))
            st = srv.stats()
            rec.update(
                feasible=True, throughput=score,
                tok_s_cold=gen / wall_cold, tok_s_warm=gen / wall_warm,
                wall_s=wall_cold, wall_warm_s=wall_warm,
                generated_tokens=gen,
                token_match=min(match_cold, match_warm),
                slo_attainment=attainment,
                compiled_programs=st["compile_count"],
                prefix_cache_hit_rate=st["prefix_cache_hit_rate"],
                preemptions=st["evicted"], swap_in=st["swap_in"],
                resolved_config=st["config"])
            return rec
        except ParityError as e:
            rec.update(feasible=False, constraint="parity", error=str(e))
            return rec
        except RetraceError as e:
            rec.update(feasible=False, constraint="compile_budget",
                       error=str(e)[:300])
            return rec
        except ValueError as e:
            rec.update(feasible=False, constraint="validation",
                       error=str(e)[:300])
            return rec
        except Exception as e:   # OOM and friends: infeasible, not fatal
            rec.update(feasible=False, constraint=type(e).__name__,
                       error=str(e)[:300])
            return rec
        finally:
            del srv
            gc.collect()


def tune_serving(engine, trace: ServingTrace, *,
                 space: Optional[ServingKnobSpace] = None,
                 domains: Optional[Dict[str, Any]] = None,
                 base: Optional[Dict[str, Any]] = None,
                 mem_ceiling_bytes: Optional[int] = None,
                 eta: int = 2, min_budget: Optional[int] = None,
                 max_budget: Optional[int] = None,
                 max_trials: Optional[int] = None,
                 results_dir: str = "autotuning_results_serving",
                 resume: bool = False,
                 min_token_match: float = 0.90,
                 slo_penalty: float = 0.0,
                 min_slo_attainment: Optional[float] = None
                 ) -> Dict[str, Any]:
    """Closed-loop serving autotune (module docstring): returns the
    summary dict and writes the ``results_dir`` artifact trio.

    ``space`` defaults to :class:`ServingKnobSpace` over the engine's
    geometry and the trace's required ``max_seq_len`` (pass ``domains``/
    ``base``/``mem_ceiling_bytes`` to shape it).  ``min_budget``/
    ``max_budget`` default to a quarter of / the whole trace."""
    if space is None:
        from ..parallel.topology import TP_AXIS

        base = dict(base or {})
        # the candidates describe THIS engine: pin the space's topology
        # to its mesh so every trial (and best_config.json) matches the
        # parallelism that was actually measured
        base.setdefault("topology",
                        int(dict(engine.mesh.shape).get(TP_AXIS, 1)))
        space = ServingKnobSpace(
            ModelGeom.from_engine(engine),
            max_seq_len=trace.max_total_len(),
            base=base, domains=domains,
            mem_ceiling_bytes=mem_ceiling_bytes)
    n = len(trace)
    max_budget = n if max_budget is None else min(int(max_budget), n)
    if min_budget is None:
        min_budget = max(2, max_budget // 4)
    candidates = space.candidates()
    kept, pruned = space.prune(candidates)
    if not kept:
        raise RuntimeError(
            f"every candidate was pruned by constraints: {pruned}")
    log_dist(
        f"autotune[serving]: {len(candidates)} candidates, "
        f"{len(kept)} admissible (pruned {pruned}), budgets "
        f"{min_budget}..{max_budget} x eta={eta}", ranks=[0])
    runner = TrialRunner(engine, trace, base_config=space.default_config(),
                         min_token_match=min_token_match,
                         slo_penalty=slo_penalty,
                         min_slo_attainment=min_slo_attainment)
    sh = SuccessiveHalving(eta=eta, min_budget=min_budget,
                           max_budget=max_budget, max_trials=max_trials,
                           results_dir=results_dir)
    out = sh.run(kept, runner, resume=resume)
    if out["best"] is None:
        raise RuntimeError(
            "autotuning found no feasible serving configuration; "
            f"see {results_dir}/exps.json")
    winner_cfg = out["best"]["config"]
    predicted = float(out["best"]["throughput"])

    # predicted-vs-measured: fresh full-budget re-runs of the winner and
    # the hand-picked default (same gates as every trial)
    rerun_w = runner(winner_cfg, max_budget)
    rerun_w.update(stage="rerun_winner", budget=max_budget)
    default_cfg = space.default_config()
    rerun_d = runner(default_cfg, max_budget)
    rerun_d.update(stage="rerun_default", budget=max_budget)
    results = out["results"] + [rerun_w, rerun_d]
    measured = float(rerun_w.get("throughput") or 0.0)
    default_tok_s = float(rerun_d.get("throughput") or 0.0)
    speedup = measured / default_tok_s if default_tok_s else None

    pvm = [
        "## Predicted vs measured",
        "",
        "| config | predicted tok/s | measured tok/s (full budget) |",
        "|---|---|---|",
        f"| winner | {predicted:.0f} | {measured:.0f} |",
        f"| default | - | {default_tok_s:.0f} |",
        "",
        f"Winner / default speedup: "
        f"**{speedup:.2f}x**" if speedup else "Default re-run infeasible.",
    ]
    prune_md = ["## Constraint pruning", ""] + (
        [f"- `{k}`: {v} candidate(s)" for k, v in sorted(pruned.items())]
        or ["- nothing pruned"])
    sched = ["## Search schedule", ""] + [
        f"- rung {r['rung']}: {r['candidates']} candidate(s) at budget "
        f"{r['budget']} ({r['feasible']} feasible, {r['resumed']} resumed)"
        for r in out["rungs"]]
    report_mod.write_results(
        results_dir, results, winner_cfg,
        title="Serving autotuning report",
        extra_sections=["\n".join(prune_md), "\n".join(sched),
                        "\n".join(pvm)])
    summary = {
        "results_dir": results_dir,
        "candidates": len(candidates),
        "admissible": len(kept),
        "pruned_by_constraint": pruned,
        "trials_executed": out["trials_executed"],
        "trials_total": out["trials_total"],
        "budget_spent_requests": out["budget_spent"],
        "rungs": out["rungs"],
        "exhausted": out["exhausted"],
        "best_config": winner_cfg,
        "winner": {"predicted_tok_s": predicted,
                   "measured_tok_s": measured,
                   "record": rerun_w},
        "default": {"measured_tok_s": default_tok_s,
                    "record": rerun_d},
        "speedup": speedup,
    }
    log_dist(
        f"autotune[serving]: winner {measured:.1f} tok/s vs default "
        f"{default_tok_s:.1f} tok/s "
        f"({speedup:.2f}x) -> {results_dir}/best_config.json"
        if speedup else
        f"autotune[serving]: winner {measured:.1f} tok/s -> "
        f"{results_dir}/best_config.json", ranks=[0])
    return summary
