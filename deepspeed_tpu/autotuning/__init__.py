"""Autotuning (reference ``deepspeed/autotuning``): search ZeRO stage /
micro-batch / remat configurations by measuring short training runs."""

from .autotuner import Autotuner, autotune
from .config import AutotuningConfig

__all__ = ["Autotuner", "autotune", "AutotuningConfig"]
