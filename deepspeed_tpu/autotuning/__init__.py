"""Autotuning (reference ``deepspeed/autotuning``): measured search over
configuration spaces.

Training side (:class:`Autotuner` / :func:`autotune`): ZeRO stage /
micro-batch / remat / gas / flash-block coordinate descent over short
``train_batch`` runs.  Serving side (:func:`tune_serving`): replayable
traces (:class:`ServingTrace` — record, load, or fit one from a
telemetry snapshot), a constraint-screened knob space
(:class:`ServingKnobSpace`), and deterministic successive halving
(:class:`SuccessiveHalving`) with parity-gated, sentry-enforced trials.
Both emit the same ``exps.json`` / ``best_config.json`` / ``report.md``
artifact trio (``report.py``).  See ``docs/autotuning.md``.
"""

from .autotuner import Autotuner, autotune
from .config import AutotuningConfig
from .runner import ParityError, TrialRunner, tune_serving
from .search import SuccessiveHalving, config_key, rank_results
from .space import ModelGeom, ServingKnobSpace
from .trace import ServingTrace, TraceEntry, TraceRecorder, fit_trace, \
    sessions_trace

__all__ = [
    "Autotuner", "autotune", "AutotuningConfig",
    "ServingTrace", "TraceEntry", "TraceRecorder", "fit_trace",
    "sessions_trace",
    "ModelGeom", "ServingKnobSpace",
    "SuccessiveHalving", "config_key", "rank_results",
    "ParityError", "TrialRunner", "tune_serving",
]
