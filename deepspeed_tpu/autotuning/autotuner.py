"""Autotuner — measured search over ZeRO stage x micro-batch (x remat).

Reference ``autotuning/autotuner.py:423 Autotuner.tune``: builds an
experiment space from the user config ("fast" mode: ZeRO stage and
micro-batch size), launches short training runs per experiment, records
throughput, prunes infeasible points, and emits the best config.  The
reference spawns cluster jobs per experiment; here each experiment is an
in-process engine build + a few measured ``train_batch`` steps (XLA compile
cache makes repeats cheap), with HBM OOM treated as infeasible-and-prune
(larger micro batches of the same stage are skipped — the reference's
memory-based pruning).
"""

from __future__ import annotations

import gc
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..utils.logging import log_dist, logger
from .config import AutotuningConfig

OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Allocation", "exceed", "out of memory")


def _merge_overrides(base: dict, overrides: dict) -> dict:
    """One-level deep merge: dict-valued overrides update the base sub-dict
    instead of replacing it (zero_optimization.stage must not drop the
    user's offload/bucket options)."""
    out = dict(base)
    for k, v in overrides.items():
        if isinstance(v, dict):
            merged = dict(out.get(k, {}))
            merged.update(v)
            out[k] = merged
        else:
            out[k] = v
    return out


class Autotuner:

    def __init__(self, model_factory: Callable[[], Any], base_config: dict,
                 batch_factory: Callable[[int, int], dict],
                 seq_len: int = 128):
        """``model_factory()`` -> fresh ModelSpec per experiment;
        ``batch_factory(global_batch, seq_len)`` -> host batch dict."""
        self.model_factory = model_factory
        self.base_config = dict(base_config)
        self.cfg = AutotuningConfig(**self.base_config.get("autotuning", {}))
        self.batch_factory = batch_factory
        self.seq_len = seq_len
        self.results: List[Dict[str, Any]] = []

    # ----------------------------------------------------------- exp space
    def experiment_space(self) -> List[dict]:
        """Fast-mode space (reference ``_generate_experiments``): ZeRO
        stages x micro-batch powers of two."""
        base_micro = int(self.base_config.get(
            "train_micro_batch_size_per_gpu", 1))
        micros = []
        m = max(self.cfg.min_train_micro_batch_size_per_gpu, base_micro)
        for _ in range(self.cfg.num_tuning_micro_batch_sizes):
            if m > self.cfg.max_train_micro_batch_size_per_gpu:
                break
            micros.append(m)
            m *= 2
        stages = self.base_config.get("autotuning", {}).get(
            "zero_stages", [0, 1, 2, 3])
        exps = []
        for stage in stages:
            for micro in micros:
                overrides = {
                    "train_micro_batch_size_per_gpu": micro,
                    "zero_optimization": {"stage": stage},
                }
                exps.append(overrides)
        return exps[: self.cfg.tuner_num_trials]

    # ------------------------------------------------------------ measure
    def _run_experiment(self, overrides: dict) -> Dict[str, Any]:
        import jax

        import deepspeed_tpu

        config = dict(self.base_config)
        config.pop("autotuning", None)
        config = _merge_overrides(config, overrides)
        rec: Dict[str, Any] = {"config": overrides}
        deepspeed_tpu.comm.reset_topology()
        engine = None
        try:
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=self.model_factory(), config=config)
            warm = self.cfg.start_profile_step
            steps = max(self.cfg.end_profile_step - warm, 1)
            for _ in range(warm):
                engine.train_batch(self.batch_factory(
                    engine.train_batch_size(), self.seq_len))
            t0 = time.perf_counter()
            for _ in range(steps):
                _, m = engine.train_batch(self.batch_factory(
                    engine.train_batch_size(), self.seq_len))
            jax.device_get(jax.tree_util.tree_leaves(
                engine.state["params"])[0].sum())
            dt = (time.perf_counter() - t0) / steps
            toks = engine.train_batch_size() * self.seq_len
            rec.update(feasible=True, step_s=dt,
                       throughput=toks / dt, loss=float(m["loss"]))
        except Exception as e:  # infeasible (OOM / invalid combo)
            msg = str(e)
            rec.update(feasible=False,
                       oom=any(s in msg for s in OOM_MARKERS),
                       error=msg[:300])
        finally:
            del engine
            gc.collect()
        return rec

    # ---------------------------------------------------------------- tune
    def tune(self) -> Dict[str, Any]:
        """Run the space; returns the best record (reference ``tune``:423).

        Pruning: an OOM at micro batch m skips larger micros for the same
        stage; ``tuner_early_stopping`` consecutive non-improving trials end
        the search."""
        best: Optional[Dict[str, Any]] = None
        stale = 0
        pruned_stage_micro: Dict[int, int] = {}
        for overrides in self.experiment_space():
            stage = overrides["zero_optimization"]["stage"]
            micro = overrides["train_micro_batch_size_per_gpu"]
            if stage in pruned_stage_micro and \
                    micro >= pruned_stage_micro[stage]:
                continue
            rec = self._run_experiment(overrides)
            self.results.append(rec)
            log_dist(f"autotuning exp {overrides}: "
                     f"{'%.1f tok/s' % rec['throughput'] if rec.get('feasible') else 'infeasible'}",
                     ranks=[0])
            if not rec.get("feasible"):
                if rec.get("oom"):
                    pruned_stage_micro[stage] = micro
                continue
            if best is None or rec["throughput"] > best["throughput"]:
                best, stale = rec, 0
            else:
                stale += 1
                if stale >= self.cfg.tuner_early_stopping:
                    break
        if best is None:
            raise RuntimeError(
                "autotuning found no feasible configuration; "
                f"records: {self.results}")
        self._write_results(best)
        return best

    def _write_results(self, best) -> None:
        os.makedirs(self.cfg.results_dir, exist_ok=True)
        with open(os.path.join(self.cfg.results_dir, "exps.json"), "w") as f:
            json.dump(self.results, f, indent=2, default=str)
        with open(os.path.join(self.cfg.results_dir,
                               "best_config.json"), "w") as f:
            cfg = dict(self.base_config)
            cfg.pop("autotuning", None)
            json.dump(_merge_overrides(cfg, best["config"]), f, indent=2)
        log_dist(f"autotuning: best {best['config']} at "
                 f"{best['throughput']:.1f} tok/s -> "
                 f"{self.cfg.results_dir}/best_config.json", ranks=[0])


def autotune(model_factory, base_config, batch_factory, seq_len=128):
    """One-call entry (the ``deepspeed --autotuning run`` analog)."""
    return Autotuner(model_factory, base_config, batch_factory,
                     seq_len).tune()
