"""Autotuner — measured search over ZeRO stage x micro-batch (x remat).

Reference ``autotuning/autotuner.py:423 Autotuner.tune``: builds an
experiment space from the user config ("fast" mode: ZeRO stage and
micro-batch size), launches short training runs per experiment, records
throughput, prunes infeasible points, and emits the best config.  The
reference spawns cluster jobs per experiment; here each experiment is an
in-process engine build + a few measured ``train_batch`` steps (XLA compile
cache makes repeats cheap), with HBM OOM treated as infeasible-and-prune
(larger micro batches of the same stage are skipped — the reference's
memory-based pruning).
"""

from __future__ import annotations

import gc
import time
from typing import Any, Callable, Dict, List, Optional

from ..utils.logging import log_dist
from . import report
from .config import AutotuningConfig
from .search import run_candidates

OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Allocation", "exceed", "out of memory")


def _merge_overrides(base: dict, overrides: dict) -> dict:
    """One-level deep merge: dict-valued overrides update the base sub-dict
    instead of replacing it (zero_optimization.stage must not drop the
    user's offload/bucket options)."""
    out = dict(base)
    for k, v in overrides.items():
        if isinstance(v, dict):
            merged = dict(out.get(k, {}))
            merged.update(v)
            out[k] = merged
        else:
            out[k] = v
    return out


class Autotuner:

    def __init__(self, model_factory: Callable[[], Any], base_config: dict,
                 batch_factory: Callable[[int, int], dict],
                 seq_len: int = 128):
        """``model_factory()`` -> fresh ModelSpec per experiment;
        ``batch_factory(global_batch, seq_len)`` -> host batch dict."""
        self.model_factory = model_factory
        self.base_config = dict(base_config)
        self.cfg = AutotuningConfig(**self.base_config.get("autotuning", {}))
        self.batch_factory = batch_factory
        self.seq_len = seq_len
        self.results: List[Dict[str, Any]] = []

    # ----------------------------------------------------------- exp space
    def experiment_space(self) -> List[dict]:
        """Fast-mode space (reference ``_generate_experiments``): ZeRO
        stages x micro-batch powers of two."""
        base_micro = int(self.base_config.get(
            "train_micro_batch_size_per_gpu", 1))
        micros = []
        m = max(self.cfg.min_train_micro_batch_size_per_gpu, base_micro)
        for _ in range(self.cfg.num_tuning_micro_batch_sizes):
            if m > self.cfg.max_train_micro_batch_size_per_gpu:
                break
            micros.append(m)
            m *= 2
        stages = self.base_config.get("autotuning", {}).get(
            "zero_stages", [0, 1, 2, 3])
        exps = []
        for stage in stages:
            for micro in micros:
                overrides = {
                    "train_micro_batch_size_per_gpu": micro,
                    "zero_optimization": {"stage": stage},
                }
                exps.append(overrides)
        return exps[: self.cfg.tuner_num_trials]

    # ------------------------------------------------------------ measure
    def _run_experiment(self, overrides: dict) -> Dict[str, Any]:
        import jax

        import deepspeed_tpu

        config = dict(self.base_config)
        config.pop("autotuning", None)
        model_overrides = dict(overrides.get("_model", {}))
        config = _merge_overrides(
            config, {k: v for k, v in overrides.items() if k != "_model"})
        rec: Dict[str, Any] = {"config": overrides}
        deepspeed_tpu.comm.reset_topology()
        engine = None
        try:
            model = self.model_factory()
            mc = getattr(model, "model_config", None)
            for k, v in model_overrides.items():
                if mc is None or not hasattr(mc, k):
                    raise ValueError(f"model does not expose knob {k!r}")
                setattr(mc, k, v)
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=model, config=config)
            warm = self.cfg.start_profile_step
            steps = max(self.cfg.end_profile_step - warm, 1)
            for _ in range(warm):
                engine.train_batch(self.batch_factory(
                    engine.train_batch_size(), self.seq_len))
            # drain the async warm-step backlog BEFORE starting the clock
            # (dispatch returns at enqueue; without this the measured
            # window absorbs the warm steps' device time)
            jax.device_get(jax.tree_util.tree_leaves(
                engine.state["params"])[0].sum())
            t0 = time.perf_counter()
            for _ in range(steps):
                _, m = engine.train_batch(self.batch_factory(
                    engine.train_batch_size(), self.seq_len))
            jax.device_get(jax.tree_util.tree_leaves(
                engine.state["params"])[0].sum())
            dt = (time.perf_counter() - t0) / steps
            toks = engine.train_batch_size() * self.seq_len
            rec.update(feasible=True, step_s=dt,
                       throughput=toks / dt, loss=float(m["loss"]))
        except Exception as e:  # infeasible (OOM / invalid combo)
            msg = str(e)
            rec.update(feasible=False,
                       oom=any(s in msg for s in OOM_MARKERS),
                       error=msg[:300])
        finally:
            del engine
            gc.collect()
        return rec

    # -------------------------------------------------- staged search (v2)
    def _model_knob_space(self, stage_name: str) -> List[dict]:
        """Candidate ``_model`` overrides for one staged knob group.  These
        are the knobs that actually set TPU throughput (PROFILE.md's
        measured winners: remat policy, layer-loop unrolling, gas, flash
        block sizes) — the reference's fast mode never touches them."""
        probe = self.model_factory()
        mc = getattr(probe, "model_config", None)

        def has(k):
            return mc is not None and hasattr(mc, k)

        if stage_name == "remat":
            cands = []
            for pol in (self.cfg.remat_policies if has("remat_policy")
                        else [None]):
                for scan in ([True, False] if has("scan_layers") else [None]):
                    m = {}
                    if pol is not None:
                        m["remat"] = pol != "off"
                        m["remat_policy"] = pol
                    if scan is not None:
                        m["scan_layers"] = scan
                    if m:
                        cands.append({"_model": m})
            return cands
        if stage_name == "gas":
            return [{"gradient_accumulation_steps": g}
                    for g in self.cfg.gas_candidates]
        if stage_name == "flash":
            if not (has("flash_block_q") and has("flash_block_k")):
                return []
            return [{"_model": {"flash_block_q": bq, "flash_block_k": bk}}
                    for bq, bk in self.cfg.flash_blocks]
        raise ValueError(stage_name)

    def _predict(self, features: List[float],
                 measured: List[tuple]) -> Optional[float]:
        """Model-based trial ordering (reference
        ``tuner/model_based_tuner.py``): inverse-distance-weighted
        prediction of throughput from the experiments measured so far."""
        if len(measured) < 2:
            return None
        num = den = 0.0
        for f, y in measured:
            d = sum((a - b) ** 2 for a, b in zip(features, f)) ** 0.5
            w = 1.0 / (d + 1e-3)
            num += w * y
            den += w
        return num / den

    def _features(self, overrides: dict) -> List[float]:
        m = overrides.get("_model", {})
        return [
            float(overrides.get("train_micro_batch_size_per_gpu", 0)),
            float(overrides.get("zero_optimization", {}).get("stage", -1)),
            float(overrides.get("gradient_accumulation_steps", 0)),
            {"full": 0, "dots": 1, "dots_flash": 2}.get(
                m.get("remat_policy"), -1),
            1.0 if m.get("scan_layers") else 0.0,
            float(m.get("flash_block_q", 0)),
            float(m.get("flash_block_k", 0)),
        ]

    def _measure(self, overrides: dict, stage_name: Optional[str],
                 measured: Optional[List[tuple]] = None) -> Dict[str, Any]:
        """Run + record + log ONE experiment (the shared per-candidate
        body both search modes hand to ``search.run_candidates``)."""
        rec = self._run_experiment(overrides)
        if stage_name is not None:
            rec["stage"] = stage_name
        self.results.append(rec)
        log_dist(
            f"autotuning{'[' + stage_name + ']' if stage_name else ''} "
            f"{overrides}: "
            f"{'%.1f tok/s' % rec['throughput'] if rec.get('feasible') else 'infeasible'}",
            ranks=[0])
        if measured is not None and rec.get("feasible"):
            measured.append((self._features(overrides), rec["throughput"]))
        return rec

    def _tune_staged(self) -> Dict[str, Any]:
        """Greedy coordinate descent over knob groups: tune batch geometry
        first (memory-dominant), then remat policy, then gas, then flash
        blocks — each stage keeps the winners of the previous ones.  A
        model-based tuner orders within-stage candidates and early-stops
        when its prediction falls far behind the incumbent."""
        best_over: Dict[str, Any] = {}
        best_rec: Optional[Dict[str, Any]] = None
        measured: List[tuple] = []
        for stage_name in self.cfg.stages:
            if stage_name == "batch":
                cands = self.experiment_space()
            else:
                cands = self._model_knob_space(stage_name)
            cands = [c for c in cands if c]
            if not cands:
                continue
            # model-based ordering: try predicted-best first
            if self.cfg.tuner_type == "model_based" and len(measured) >= 2:
                cands.sort(key=lambda c: -(self._predict(
                    self._features(_merge_overrides(best_over, c)), measured)
                    or 0.0))
            stage_best = run_candidates(
                cands,
                lambda cand: self._measure(
                    _merge_overrides(best_over, cand), stage_name,
                    measured),
                early_stopping=self.cfg.tuner_early_stopping)
            if stage_best is not None and (
                    best_rec is None or
                    stage_best["throughput"] >= best_rec["throughput"]):
                best_rec = stage_best
                best_over = stage_best["config"]
        if best_rec is None:
            raise RuntimeError(
                "autotuning found no feasible configuration; "
                f"records: {self.results}")
        self._write_results(best_rec)
        return best_rec

    # ---------------------------------------------------------------- tune
    def tune(self) -> Dict[str, Any]:
        """Run the space; returns the best record (reference ``tune``:423).

        ``tuner_type``: "staged"/"model_based" run the v2 coordinate
        search over batch -> remat -> gas -> flash blocks; "gridsearch"
        keeps the reference-style stage x micro-batch grid.  Pruning: an
        OOM at micro batch m skips larger micros for the same stage;
        ``tuner_early_stopping`` consecutive non-improving trials end the
        search."""
        if self.cfg.tuner_type in ("staged", "model_based"):
            return self._tune_staged()
        pruned_stage_micro: Dict[int, int] = {}

        def _skip(overrides):
            stage = overrides["zero_optimization"]["stage"]
            micro = overrides["train_micro_batch_size_per_gpu"]
            return stage in pruned_stage_micro and \
                micro >= pruned_stage_micro[stage]

        def _run(overrides):
            rec = self._measure(overrides, None)
            if not rec.get("feasible") and rec.get("oom"):
                pruned_stage_micro[
                    overrides["zero_optimization"]["stage"]] = \
                    overrides["train_micro_batch_size_per_gpu"]
            return rec

        best = run_candidates(
            self.experiment_space(), _run,
            early_stopping=self.cfg.tuner_early_stopping, skip=_skip)
        if best is None:
            raise RuntimeError(
                "autotuning found no feasible configuration; "
                f"records: {self.results}")
        self._write_results(best)
        return best

    def _write_results(self, best) -> None:
        """Emit the shared artifact trio (``autotuning/report.py`` — the
        ranked table and exps schema are identical to the serving
        tuner's).  ``best_config.json`` stays a full merged DeepSpeed
        config, ``_model`` builder knobs alongside."""
        cfg = dict(self.base_config)
        cfg.pop("autotuning", None)
        model_over = best["config"].get("_model")
        cfg = _merge_overrides(
            cfg, {k: v for k, v in best["config"].items()
                  if k != "_model"})
        if model_over:
            cfg["_model"] = model_over  # builder knobs (GPT2Config etc.)
        report.write_results(self.cfg.results_dir, self.results, cfg)
        log_dist(f"autotuning: best {best['config']} at "
                 f"{best['throughput']:.1f} tok/s -> "
                 f"{self.cfg.results_dir}/best_config.json", ranks=[0])


def autotune(model_factory, base_config, batch_factory, seq_len=128):
    """One-call entry (the ``deepspeed --autotuning run`` analog)."""
    return Autotuner(model_factory, base_config, batch_factory,
                     seq_len).tune()
