"""Compression config schema — same key names as the reference
(``compression/config.py`` / ``compression/constants.py``)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..runtime.config_utils import DeepSpeedConfigModel


class QuantSharedParams(DeepSpeedConfigModel):
    enabled: bool = False
    quantizer_kernel: bool = False
    schedule_offset: int = 0
    quantize_groups: int = 1
    quantize_verbose: bool = False
    quantization_type: str = "symmetric"       # symmetric | asymmetric
    rounding: str = "nearest"                  # nearest | stochastic
    quantize_weight_in_forward: bool = True
    fp16_mixed_quantize: Dict[str, Any] = {}


class QuantGroup(DeepSpeedConfigModel):
    params: Dict[str, Any] = {}
    modules: List[str] = ["*"]
    related_modules: Optional[List[str]] = None

    @property
    def target_bits(self) -> int:
        return int(self.params.get("target_bits", 8))

    @property
    def start_bits(self) -> int:
        return int(self.params.get("start_bits", self.target_bits))


class WeightQuantConfig(DeepSpeedConfigModel):
    shared_parameters: QuantSharedParams = QuantSharedParams()
    different_groups: Dict[str, QuantGroup] = {}


class ActQuantSharedParams(DeepSpeedConfigModel):
    enabled: bool = False
    quantization_type: str = "symmetric"
    range_calibration: str = "dynamic"
    schedule_offset: int = 0


class ActQuantConfig(DeepSpeedConfigModel):
    shared_parameters: ActQuantSharedParams = ActQuantSharedParams()
    different_groups: Dict[str, QuantGroup] = {}


class PruneSharedParams(DeepSpeedConfigModel):
    enabled: bool = False
    schedule_offset: int = 0
    method: str = "l1"  # l1 | topk
    dense_ratio: float = 1.0


class PruneGroup(DeepSpeedConfigModel):
    params: Dict[str, Any] = {}
    modules: List[str] = ["*"]
    related_modules: Optional[List[str]] = None

    @property
    def dense_ratio(self) -> float:
        return float(self.params.get("dense_ratio", 0.5))


class PruneConfig(DeepSpeedConfigModel):
    shared_parameters: PruneSharedParams = PruneSharedParams()
    different_groups: Dict[str, PruneGroup] = {}


class LayerReductionConfig(DeepSpeedConfigModel):
    enabled: bool = False
    keep_number_layer: int = 0
    module_name_prefix: str = ""
    teacher_layer: List[int] = []
    other_module_name: List[str] = []


class CompressionConfig(DeepSpeedConfigModel):
    weight_quantization: WeightQuantConfig = WeightQuantConfig()
    activation_quantization: ActQuantConfig = ActQuantConfig()
    sparse_pruning: PruneConfig = PruneConfig()
    row_pruning: PruneConfig = PruneConfig()
    head_pruning: PruneConfig = PruneConfig()
    channel_pruning: PruneConfig = PruneConfig()
    layer_reduction: LayerReductionConfig = LayerReductionConfig()


def get_compression_config(param_dict: dict) -> CompressionConfig:
    block = param_dict.get("compression_training", {})
    return CompressionConfig(**block)
