"""Compression suite (reference ``deepspeed/compression``): quantization-
aware training, activation quantization, sparse/row/head pruning, layer
reduction for distillation — all config-driven via ``init_compression``."""

from .compress import init_compression, redundancy_clean
from .config import get_compression_config
from .distill import (distillation_loss, init_distillation,
                      student_initialization)
from .ops import (channel_pruning_mask, fake_quantize, head_pruning_mask,
                  quantize_activation, row_pruning_mask, sparse_pruning_mask)

__all__ = ["init_compression", "redundancy_clean", "get_compression_config",
           "fake_quantize", "quantize_activation", "sparse_pruning_mask",
           "row_pruning_mask", "head_pruning_mask", "channel_pruning_mask",
           "distillation_loss", "init_distillation", "student_initialization"]
