"""Compression primitives: fake-quant with straight-through gradients,
pruning masks.

Reference kernels: ``csrc/quantization/{quantize,fake_quantizer}.cu`` (group
symmetric/asymmetric/stochastic quantization) and the mask construction in
``compression/basic_layer.py`` (``LinearLayer_Compress.fix_sparse_pruning``
etc.).  On TPU the quantization arithmetic fuses into the surrounding ops via
XLA; the straight-through estimator is a ``custom_vjp`` identity backward.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def fake_quantize(w, bits: int = 8, groups: int = 1,
                  quant_type: str = "symmetric", stochastic: bool = False):
    """Quantize-dequantize ``w`` to ``bits`` with per-group scaling; gradient
    is straight-through (identity)."""
    return _fake_quantize_fwd_value(w, bits, groups, quant_type, stochastic,
                                    None)


def _group_view(w, groups: int):
    flat = w.reshape(-1)
    pad = (-flat.size) % groups
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(groups, -1), pad, w.shape


def _fake_quantize_fwd_value(w, bits, groups, quant_type, stochastic, rng):
    q_max = 2.0 ** (bits - 1) - 1
    g, pad, shape = _group_view(w.astype(jnp.float32), groups)
    if quant_type == "asymmetric":
        lo = jnp.min(g, axis=-1, keepdims=True)
        hi = jnp.max(g, axis=-1, keepdims=True)
        scale = (hi - lo) / (2.0 ** bits - 1)
        scale = jnp.where(scale == 0, 1.0, scale)
        q = (g - lo) / scale
        q = _round(q, stochastic, rng)
        deq = q * scale + lo
    else:  # symmetric
        amax = jnp.max(jnp.abs(g), axis=-1, keepdims=True)
        scale = jnp.where(amax == 0, 1.0, amax / q_max)
        q = jnp.clip(_round(g / scale, stochastic, rng), -q_max - 1, q_max)
        deq = q * scale
    out = deq.reshape(-1)
    if pad:
        out = out[:-pad]
    return out.reshape(shape).astype(w.dtype)


def _round(x, stochastic: bool, rng):
    if stochastic and rng is not None:
        return jnp.floor(x + jax.random.uniform(rng, x.shape))
    return jnp.round(x)


def _fq_fwd(w, bits, groups, quant_type, stochastic):
    return fake_quantize(w, bits, groups, quant_type, stochastic), None


def _fq_bwd(bits, groups, quant_type, stochastic, res, g):
    return (g,)  # straight-through estimator


fake_quantize.defvjp(_fq_fwd, _fq_bwd)


def quantize_activation(x, bits: int = 8, quant_type: str = "symmetric"):
    """Dynamic-range activation fake-quant (per-tensor); STE gradient."""
    return fake_quantize(x, bits, 1, quant_type, False)


# ------------------------------------------------------------------ pruning
def sparse_pruning_mask(w, dense_ratio: float, method: str = "l1"):
    """Unstructured magnitude mask keeping ``dense_ratio`` of elements
    (reference ``fix_sparse_pruning``)."""
    flat = jnp.abs(w.reshape(-1))
    k = max(1, int(flat.size * dense_ratio))
    thresh = jnp.sort(flat)[-k]
    return (jnp.abs(w) >= thresh).astype(w.dtype)


def row_pruning_mask(w, dense_ratio: float):
    """Structured row mask by l1 row norm (reference ``fix_row_pruning``);
    w: [..., rows, cols] — masks rows."""
    norms = jnp.sum(jnp.abs(w), axis=-1)
    k = max(1, int(norms.shape[-1] * dense_ratio))
    thresh = jnp.sort(norms, axis=-1)[..., -k][..., None]
    return (norms >= thresh)[..., None].astype(w.dtype) * jnp.ones_like(w)


def channel_pruning_mask(w, dense_ratio: float):
    """Structured output-channel mask for conv kernels by per-channel l1
    norm (reference ``Conv2dLayer_Compress.fix_channel_pruning``);
    w: [kh, kw, cin, cout] (our VAE/UNet layout) — masks the cout dim."""
    if w.ndim < 3:
        return jnp.ones_like(w)
    norms = jnp.sum(jnp.abs(w), axis=tuple(range(w.ndim - 1)))  # [cout]
    k = max(1, int(norms.shape[-1] * dense_ratio))
    thresh = jnp.sort(norms)[-k]
    return (norms >= thresh).astype(w.dtype) * jnp.ones_like(w)


def head_pruning_mask(w, dense_ratio: float, num_heads: int):
    """Attention-head mask by per-head l1 norm on an output-projection-shaped
    weight [in(=H*hd), out] (reference ``fix_head_pruning``)."""
    in_dim = w.shape[-2]
    hd = in_dim // num_heads
    heads = w.reshape(w.shape[:-2] + (num_heads, hd, w.shape[-1]))
    norms = jnp.sum(jnp.abs(heads), axis=(-1, -2))          # [..., H]
    k = max(1, int(num_heads * dense_ratio))
    thresh = jnp.sort(norms, axis=-1)[..., -k][..., None]
    mask = (norms >= thresh).astype(w.dtype)                # [..., H]
    mask = jnp.repeat(mask[..., None], hd, axis=-1).reshape(
        w.shape[:-2] + (in_dim, 1))
    return mask * jnp.ones_like(w)
