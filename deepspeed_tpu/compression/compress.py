"""``init_compression`` — config-driven model compression.

Reference ``compression/compress.py:99 init_compression`` walks the torch
module tree replacing Linear/Embedding with ``*_Compress`` variants per the
config's ``different_groups`` module patterns.  The TPU analog wraps a
:class:`ModelSpec`: a *param transform* applies scheduled fake-quantization
and pruning masks to the leaves whose path matches a group's ``modules``
patterns, just before the loss/apply functions run — same QAT semantics
(compression in forward, straight-through gradients), no module tree needed.

``redundancy_clean`` (reference ``compress.py:148``) bakes the masks/quant
into the weights permanently for deployment.
"""

from __future__ import annotations

import fnmatch
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..runtime.model import ModelSpec
from ..utils.logging import log_dist
from .config import CompressionConfig, get_compression_config
from .ops import (channel_pruning_mask, fake_quantize, head_pruning_mask,
                  row_pruning_mask, sparse_pruning_mask)

PyTree = Any


def _leaf_path_names(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    names = ["/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                      for k in path) for path, _ in flat]
    return names, [leaf for _, leaf in flat], treedef


def _matches(name: str, patterns: List[str]) -> bool:
    return any(fnmatch.fnmatch(name, p) or p in name for p in patterns)


def _build_transform(cfg: CompressionConfig, num_heads: Optional[int]):
    """Compile the config into a per-leaf transform list.

    Each rule carries its mechanism's ``schedule_offset`` so mechanisms
    activate independently (reference: per-method offsets)."""
    rules = []  # (kind, patterns, fn(leaf) -> leaf, schedule_offset)

    wq = cfg.weight_quantization
    if wq.shared_parameters.enabled:
        if wq.shared_parameters.rounding == "stochastic":
            raise NotImplementedError(
                "rounding='stochastic' is not implemented on TPU yet "
                "(needs an rng threaded through the weight transform); use "
                "'nearest'")
        off = wq.shared_parameters.schedule_offset
        for gname, grp in wq.different_groups.items():
            bits = grp.target_bits
            qt = wq.shared_parameters.quantization_type
            groups = wq.shared_parameters.quantize_groups
            rules.append(("quant", grp.modules,
                          lambda w, b=bits, q=qt, g=groups:
                          fake_quantize(w, b, g, q, False), off))

    sp = cfg.sparse_pruning
    if sp.shared_parameters.enabled:
        off = sp.shared_parameters.schedule_offset
        for gname, grp in sp.different_groups.items():
            ratio = grp.dense_ratio
            rules.append(("sparse", grp.modules,
                          lambda w, r=ratio: w * sparse_pruning_mask(w, r),
                          off))

    rp = cfg.row_pruning
    if rp.shared_parameters.enabled:
        off = rp.shared_parameters.schedule_offset
        for gname, grp in rp.different_groups.items():
            ratio = grp.dense_ratio
            rules.append(("row", grp.modules,
                          lambda w, r=ratio: w * row_pruning_mask(w, r),
                          off))

    hp = cfg.head_pruning
    if hp.shared_parameters.enabled:
        assert num_heads, "head_pruning needs num_heads (pass via model cfg)"
        off = hp.shared_parameters.schedule_offset
        for gname, grp in hp.different_groups.items():
            ratio = grp.dense_ratio
            rules.append(("head", grp.modules,
                          lambda w, r=ratio: w * head_pruning_mask(
                              w, r, num_heads), off))

    cp = cfg.channel_pruning
    if cp.shared_parameters.enabled:
        off = cp.shared_parameters.schedule_offset
        for gname, grp in cp.different_groups.items():
            ratio = grp.dense_ratio
            rules.append(("channel", grp.modules,
                          lambda w, r=ratio: w * channel_pruning_mask(w, r),
                          off))
    return rules


def compress_params(params: PyTree, rules, step: Optional[int] = None
                    ) -> PyTree:
    """Apply rules whose schedule_offset has passed (``step=None`` applies
    all — standalone/deployment use)."""
    names, leaves, treedef = _leaf_path_names(params)
    out = []
    for name, leaf in zip(names, leaves):
        for kind, patterns, fn, offset in rules:
            if step is not None and step < offset:
                continue
            if getattr(leaf, "ndim", 0) >= 2 and _matches(name, patterns):
                leaf = fn(leaf)
        out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def init_compression(model: ModelSpec, deepspeed_config,
                     num_heads: Optional[int] = None,
                     mpu=None) -> ModelSpec:
    """Wrap ``model`` so its forward sees compressed weights
    (reference ``init_compression``, ``compress.py:99``).

    ``deepspeed_config``: dict (or parsed config) containing
    ``compression_training``.  Scheduling: ``schedule_offset`` is honored by
    the engine which flips the transform on at that step; standalone use
    applies it immediately.
    """
    pd = deepspeed_config if isinstance(deepspeed_config, dict) else \
        getattr(deepspeed_config, "_param_dict", {})
    cfg = get_compression_config(pd)
    rules = _build_transform(cfg, num_heads)

    # activation quantization: there is no module tree to wrap, so models
    # expose an ``act_quant_bits`` knob (matmul inputs fake-quantize when
    # set — models/gpt2._block); the wrapper flips it at trace time so
    # schedule_offset composes with the engine's retrace-at-offset
    aq = cfg.activation_quantization
    act_bits = act_off = None
    mc = getattr(model, "model_config", None)
    if aq.shared_parameters.enabled:
        if mc is None or not hasattr(mc, "act_quant_bits"):
            from ..runtime import constants as C
            from ..runtime.config_utils import get_scalar_param

            msg = ("activation_quantization is enabled but the model "
                   "exposes no act_quant_bits knob — the setting would be "
                   "silently ignored. Use a model that supports it, or set "
                   '"strict": false to proceed without activation '
                   "quantization.")
            if get_scalar_param(pd, C.STRICT, C.STRICT_DEFAULT):
                raise ValueError(msg)
            log_dist(msg + " (strict=false: ignoring)", ranks=[0])
        else:
            grp = next(iter(aq.different_groups.values()), None)
            act_bits = grp.target_bits if grp is not None else 8
            act_off = aq.shared_parameters.schedule_offset
            if hasattr(mc, "act_quant_type"):
                mc.act_quant_type = aq.shared_parameters.quantization_type

    if not rules and act_bits is None:
        log_dist("init_compression: no compression groups enabled", ranks=[0])
        return model

    import dataclasses

    orig_loss, orig_apply = model.loss_fn, model.apply_fn
    offsets = sorted({off for _, _, _, off in rules} |
                     ({act_off} if act_bits is not None else set()))

    class _Toggle:
        """Trace-time step marker: the engine advances ``step`` as offsets
        are crossed and rebuilds its jitted step (one retrace per distinct
        offset); mechanisms with offset 0 are active from the start."""
        step = 0

        @classmethod
        def active(cls):
            return any(off <= cls.step for off in offsets)

    def _sync_act_quant():
        # runs at TRACE time, before the model traces: the knob the model
        # reads reflects this trace's step marker
        if act_bits is not None:
            mc.act_quant_bits = act_bits if _Toggle.step >= act_off else None

    def loss_fn(params, batch, rng=None, train=True):
        _sync_act_quant()
        p = compress_params(params, rules, step=_Toggle.step)
        return orig_loss(p, batch, rng, train)

    def apply_fn(params, batch, rng=None):
        _sync_act_quant()
        p = compress_params(params, rules, step=_Toggle.step)
        return orig_apply(p, batch, rng)

    wrapped = dataclasses.replace(
        model, loss_fn=loss_fn,
        apply_fn=apply_fn if orig_apply else None,
        name=model.name + "+compressed")
    wrapped._compression_rules = rules
    wrapped._compression_toggle = _Toggle
    wrapped._compression_offsets = offsets
    return wrapped


def redundancy_clean(model_or_params, deepspeed_config,
                     num_heads: Optional[int] = None) -> PyTree:
    """Bake compression into the weights for deployment
    (reference ``compress.py:148``).  Accepts a param pytree; returns the
    quantize-dequantized / masked copy."""
    pd = deepspeed_config if isinstance(deepspeed_config, dict) else \
        getattr(deepspeed_config, "_param_dict", {})
    cfg = get_compression_config(pd)
    rules = _build_transform(cfg, num_heads)
    return compress_params(model_or_params, rules)


def apply_layer_reduction(params: PyTree, blocks_key, keep_layers: List[int]
                          ) -> PyTree:
    """Student init from selected teacher layers (reference
    ``compression/helper.py student_initialization``): slice the scan-stacked
    blocks to ``keep_layers``."""
    import copy

    path = (blocks_key,) if isinstance(blocks_key, str) else tuple(blocks_key)
    out = copy.copy(params) if isinstance(params, dict) else dict(params)
    node = out
    for k in path[:-1]:
        node[k] = dict(node[k])
        node = node[k]
    idx = np.asarray(keep_layers)
    node[path[-1]] = jax.tree_util.tree_map(lambda b: b[idx],
                                            node[path[-1]])
    return out
