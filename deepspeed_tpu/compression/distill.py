"""Knowledge-distillation glue for compression-aware training.

Reference: ``compression/helper.py student_initialization`` initializes a
reduced student from teacher layers, and the compression examples combine
the task loss with a temperature-scaled KL to the teacher's logits.  Here:

 - :func:`student_initialization` — student params from selected teacher
   layers (wraps ``apply_layer_reduction``; non-block leaves copy over).
 - :func:`distillation_loss` — (1-alpha)*hard + alpha*T^2*KL(teacher||student).
 - :func:`init_distillation` — wrap a student :class:`ModelSpec` so its
   ``loss_fn`` trains against a FROZEN teacher (teacher params are closed
   over and stop_gradient'd; the teacher forward shares the step's jit).
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp

from ..runtime.model import ModelSpec
from .compress import apply_layer_reduction

PyTree = Any


def student_initialization(teacher_params: PyTree, blocks_key,
                           teacher_layers: List[int]) -> PyTree:
    """Student params whose stacked blocks are the selected teacher layers
    (reference ``student_initialization``: layer_reduction.teacher_layer)."""
    return apply_layer_reduction(teacher_params, blocks_key, teacher_layers)


def distillation_loss(student_logits, teacher_logits, hard_loss,
                      alpha: float = 0.5, temperature: float = 1.0,
                      valid=None):
    """Soft-target KD: ``(1-alpha) * hard + alpha * T^2 * KL(t || s)``.

    The ``T^2`` factor keeps soft-gradient magnitudes comparable across
    temperatures (Hinton et al. 2015 — the convention the reference's
    example configs assume).  ``valid`` (bool, logits' leading dims) masks
    positions out of the KL mean — pass the same mask the hard CE uses so
    padding positions don't dilute/skew the soft term."""
    t = jnp.asarray(temperature, jnp.float32)
    s = jax.nn.log_softmax(student_logits.astype(jnp.float32) / t, axis=-1)
    p = jax.nn.softmax(
        jax.lax.stop_gradient(teacher_logits).astype(jnp.float32) / t,
        axis=-1)
    kl = jnp.sum(p * (jnp.log(jnp.maximum(p, 1e-20)) - s), axis=-1)
    if valid is not None:
        kl = jnp.where(valid, kl, 0.0).sum() / jnp.maximum(valid.sum(), 1)
    else:
        kl = kl.mean()
    return (1.0 - alpha) * hard_loss + alpha * t * t * kl


def init_distillation(student: ModelSpec, teacher_params: PyTree,
                      alpha: float = 0.5, temperature: float = 2.0,
                      teacher_apply=None) -> ModelSpec:
    """Wrap ``student`` so training distills from a frozen teacher.

    ``teacher_apply(params, batch, rng) -> logits`` defaults to the
    student's own ``apply_fn`` (reduced-layer student of the same family —
    the layer_reduction workflow).  The teacher's params ride as a closure
    constant: XLA folds them in as weights, no optimizer state grows.

    The student runs ONE forward per step: the hard CE derives from the
    same logits the KD term uses (next-token shift or explicit ``labels``,
    the GPT-2-family convention).  ``apply_fn`` runs eval-mode, so KD
    training here is dropout-free — both loss terms see the same network.
    """
    import dataclasses

    if teacher_apply is None:
        teacher_apply = student.apply_fn
    assert teacher_apply is not None, "teacher needs an apply_fn"
    assert student.apply_fn is not None, "student needs an apply_fn"
    student_apply = student.apply_fn
    frozen_teacher = jax.tree_util.tree_map(jax.lax.stop_gradient,
                                            teacher_params)

    def _targets_of(batch):
        if isinstance(batch, (tuple, list)):
            ids, labels = batch
        else:
            ids = batch["input_ids"]
            labels = batch.get("labels")
        return labels  # None = shift convention

    def _ce(logits, batch):
        labels = _targets_of(batch)
        if labels is None:
            ids = batch["input_ids"] if isinstance(batch, dict) else batch[0]
            logits, labels = logits[:, :-1], ids[:, 1:]
        valid = labels >= 0
        safe = jnp.where(valid, labels, 0)
        lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
        picked = jnp.take_along_axis(
            logits, safe[..., None], axis=-1)[..., 0].astype(jnp.float32)
        nll = jnp.where(valid, lse - picked, 0.0)
        return nll.sum() / jnp.maximum(valid.sum(), 1), logits, valid

    def loss_fn(params, batch, rng=None, train=True):
        s_logits_full = student_apply(params, batch, rng)
        t_logits_full = teacher_apply(frozen_teacher, batch, None)
        hard, s_logits, valid = _ce(s_logits_full, batch)
        if _targets_of(batch) is None:
            t_logits_full = t_logits_full[:, :-1]
        # the KL shares the CE's position mask: padding never trains
        return distillation_loss(s_logits, t_logits_full, hard,
                                 alpha=alpha, temperature=temperature,
                                 valid=valid)

    return dataclasses.replace(student, loss_fn=loss_fn,
                               name=student.name + "+distill")
