"""Monitoring backends.

Analog of reference ``deepspeed/monitor/monitor.py`` (``MonitorMaster`` :25):
fans out ``(name, value, step)`` events to TensorBoard / W&B / CSV.  Only process
0 writes (reference checks rank 0 the same way).
"""

from __future__ import annotations

import os
from abc import ABC, abstractmethod
from typing import List, Tuple

from ..utils.logging import logger


class Monitor(ABC):

    def __init__(self, monitor_config):
        self.monitor_config = monitor_config

    @abstractmethod
    def write_events(self, event_list: List[Tuple]) -> None:
        ...


class TensorBoardMonitor(Monitor):

    def __init__(self, tensorboard_config):
        super().__init__(tensorboard_config)
        self.summary_writer = None
        try:
            from torch.utils.tensorboard import SummaryWriter

            log_dir = os.path.join(tensorboard_config.output_path or ".",
                                   tensorboard_config.job_name)
            self.summary_writer = SummaryWriter(log_dir=log_dir)
        except Exception as e:  # tensorboard optional
            logger.warning(f"tensorboard unavailable: {e}")

    def write_events(self, event_list, flush: bool = True) -> None:
        if self.summary_writer is None:
            return
        for name, value, step in event_list:
            self.summary_writer.add_scalar(name, value, step)
        if flush:
            self.summary_writer.flush()


class WandbMonitor(Monitor):

    def __init__(self, wandb_config):
        super().__init__(wandb_config)
        self._wandb = None
        try:
            import wandb

            wandb.init(project=wandb_config.project, group=wandb_config.group,
                       entity=wandb_config.team)
            self._wandb = wandb
        except Exception as e:
            logger.warning(f"wandb unavailable: {e}")

    def write_events(self, event_list) -> None:
        if self._wandb is None:
            return
        for name, value, step in event_list:
            self._wandb.log({name: value}, step=step)


class csvMonitor(Monitor):

    def __init__(self, csv_config):
        super().__init__(csv_config)
        self.filenames = {}
        self.log_dir = os.path.join(csv_config.output_path or ".",
                                    csv_config.job_name)
        os.makedirs(self.log_dir, exist_ok=True)

    def write_events(self, event_list) -> None:
        import csv

        for name, value, step in event_list:
            fname = os.path.join(self.log_dir,
                                 name.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            with open(fname, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", name])
                w.writerow([step, float(value)])


class MonitorMaster(Monitor):

    def __init__(self, monitor_config):
        super().__init__(monitor_config)
        import jax

        self.monitors: List[Monitor] = []
        self.enabled = monitor_config.enabled
        if jax.process_index() == 0:
            if monitor_config.tensorboard.enabled:
                self.monitors.append(TensorBoardMonitor(monitor_config.tensorboard))
            if monitor_config.wandb.enabled:
                self.monitors.append(WandbMonitor(monitor_config.wandb))
            if monitor_config.csv_monitor.enabled:
                self.monitors.append(csvMonitor(monitor_config.csv_monitor))

    def write_events(self, event_list) -> None:
        for m in self.monitors:
            m.write_events(event_list)

    def write_registry(self, registry, step: int) -> None:
        """Fan a telemetry :class:`~deepspeed_tpu.telemetry.
        MetricsRegistry` snapshot out to every backend: counters/gauges
        emit one ``(name, value, step)`` event each (under their
        ``monitor_name`` when set — the training engine keeps its
        historical ``Train/Samples/...`` names this way), histograms
        emit ``_p50``/``_p95``/``_count`` scalars.  This is how the
        training engine's registry-backed loss / lr / throughput /
        wall-clock-breakdown metrics land in the CSV files on disk."""
        self.write_events(registry.to_events(step))
