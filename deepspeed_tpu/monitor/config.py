"""Monitor config (reference ``deepspeed/monitor/config.py:22``)."""

from __future__ import annotations

from typing import Optional

from pydantic import model_validator

from ..runtime.config_utils import DeepSpeedConfigModel


class TensorBoardConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(DeepSpeedConfigModel):
    enabled: bool = False
    # Optional, not bare str: pydantic v2 validates assigned values against
    # the annotation, so `group: str = None` accepted the default but
    # rejected an explicit group=None (and round-tripping a dumped config
    # re-assigns every field) — reference config has the same typing bug
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed"


class CSVConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class DeepSpeedMonitorConfig(DeepSpeedConfigModel):
    tensorboard: TensorBoardConfig = TensorBoardConfig()
    wandb: WandbConfig = WandbConfig()
    csv_monitor: CSVConfig = CSVConfig()

    @property
    def enabled(self) -> bool:
        return (self.tensorboard.enabled or self.wandb.enabled
                or self.csv_monitor.enabled)


def get_monitor_config(param_dict: dict) -> DeepSpeedMonitorConfig:
    monitor_dict = {
        key: param_dict.get(key, {})
        for key in ("tensorboard", "wandb", "csv_monitor")
    }
    return DeepSpeedMonitorConfig(**monitor_dict)
