"""Monitor config (reference ``deepspeed/monitor/config.py:22``)."""

from __future__ import annotations

from pydantic import model_validator

from ..runtime.config_utils import DeepSpeedConfigModel


class TensorBoardConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(DeepSpeedConfigModel):
    enabled: bool = False
    group: str = None
    team: str = None
    project: str = "deepspeed"


class CSVConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class DeepSpeedMonitorConfig(DeepSpeedConfigModel):
    tensorboard: TensorBoardConfig = TensorBoardConfig()
    wandb: WandbConfig = WandbConfig()
    csv_monitor: CSVConfig = CSVConfig()

    @property
    def enabled(self) -> bool:
        return (self.tensorboard.enabled or self.wandb.enabled
                or self.csv_monitor.enabled)


def get_monitor_config(param_dict: dict) -> DeepSpeedMonitorConfig:
    monitor_dict = {
        key: param_dict.get(key, {})
        for key in ("tensorboard", "wandb", "csv_monitor")
    }
    return DeepSpeedMonitorConfig(**monitor_dict)
