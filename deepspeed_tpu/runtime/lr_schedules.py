"""Learning-rate schedules.

Analog of reference ``deepspeed/runtime/lr_schedules.py`` (LRRangeTest :308,
OneCycle :415, WarmupLR :704, WarmupDecayLR :800), with the same schedule names
and parameter keys.  TPU-native form: each schedule is a pure ``step -> lr``
callable (optax schedule), which the engine closes over inside the jitted train
step — no mutable scheduler object needs checkpointing beyond the step counter.

A thin ``LRScheduler`` wrapper preserves the reference's object API
(``step()``/``get_lr()``/``state_dict()``) for user code that expects it.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Union

LR_SCHEDULE = "lr_schedule"
LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR]

LR_RANGE_TEST_MIN_LR = "lr_range_test_min_lr"
LR_RANGE_TEST_STEP_RATE = "lr_range_test_step_rate"
LR_RANGE_TEST_STEP_SIZE = "lr_range_test_step_size"
LR_RANGE_TEST_STAIRCASE = "lr_range_test_staircase"

CYCLE_MIN_LR = "cycle_min_lr"
CYCLE_MAX_LR = "cycle_max_lr"
DECAY_LR_RATE = "decay_lr_rate"
CYCLE_FIRST_STEP_SIZE = "cycle_first_step_size"
CYCLE_SECOND_STEP_SIZE = "cycle_second_step_size"
CYCLE_FIRST_STAIR_COUNT = "cycle_first_stair_count"
CYCLE_SECOND_STAIR_COUNT = "cycle_second_stair_count"
DECAY_STEP_SIZE = "decay_step_size"

WARMUP_MIN_LR = "warmup_min_lr"
WARMUP_MAX_LR = "warmup_max_lr"
WARMUP_NUM_STEPS = "warmup_num_steps"
WARMUP_TYPE = "warmup_type"
WARMUP_LOG_RATE = "log"
WARMUP_LINEAR_RATE = "linear"

TOTAL_NUM_STEPS = "total_num_steps"

Schedule = Callable[[Any], Any]


def lr_range_test(lr_range_test_min_lr: float = 1e-3,
                  lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0,
                  lr_range_test_staircase: bool = False, **_) -> Schedule:
    """Linearly/stepwise increasing LR probe (reference :308)."""
    import jax.numpy as jnp

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        interval = (jnp.floor(step / lr_range_test_step_size)
                    if lr_range_test_staircase else step / lr_range_test_step_size)
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)

    return schedule


def one_cycle(cycle_min_lr: float = 0.0, cycle_max_lr: float = 1e-3,
              decay_lr_rate: float = 0.0, cycle_first_step_size: int = 2000,
              cycle_second_step_size: Optional[int] = None,
              cycle_first_stair_count: int = 0,
              cycle_second_stair_count: Optional[int] = None,
              decay_step_size: int = 0, **_) -> Schedule:
    """1-cycle policy (reference :415); momentum cycling handled by optimizer hyperparams."""
    import jax.numpy as jnp

    second = (cycle_second_step_size
              if cycle_second_step_size is not None else cycle_first_step_size)
    total_cycle = cycle_first_step_size + second

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        up_frac = jnp.clip(step / cycle_first_step_size, 0.0, 1.0)
        down_frac = jnp.clip((step - cycle_first_step_size) / second, 0.0, 1.0)
        in_cycle_lr = jnp.where(
            step <= cycle_first_step_size,
            cycle_min_lr + (cycle_max_lr - cycle_min_lr) * up_frac,
            cycle_max_lr - (cycle_max_lr - cycle_min_lr) * down_frac)
        decay_steps = jnp.maximum(step - total_cycle, 0.0)
        if decay_step_size > 0:
            decay_epochs = jnp.floor(decay_steps / decay_step_size)
        else:
            decay_epochs = decay_steps
        decayed = cycle_min_lr / (1.0 + decay_lr_rate * decay_epochs)
        return jnp.where(step <= total_cycle, in_cycle_lr, decayed)

    return schedule


def warmup_lr(warmup_min_lr: float = 0.0, warmup_max_lr: float = 1e-3,
              warmup_num_steps: int = 1000, warmup_type: str = WARMUP_LOG_RATE,
              **_) -> Schedule:
    """Warm up then hold (reference :704)."""
    import jax.numpy as jnp

    warmup_num_steps = max(2, warmup_num_steps)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        frac = jnp.clip(step / warmup_num_steps, 0.0, 1.0)
        if warmup_type == WARMUP_LOG_RATE:
            gamma = jnp.log(jnp.maximum(step, 1.0)) / math.log(warmup_num_steps)
            gamma = jnp.clip(gamma, 0.0, 1.0)
        else:
            gamma = frac
        warm = warmup_min_lr + (warmup_max_lr - warmup_min_lr) * gamma
        return jnp.where(step < warmup_num_steps, warm, warmup_max_lr)

    return schedule


def warmup_decay_lr(total_num_steps: int, warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 1e-3, warmup_num_steps: int = 1000,
                    warmup_type: str = WARMUP_LOG_RATE, **_) -> Schedule:
    """Warm up then linear decay to 0 (reference :800)."""
    import jax.numpy as jnp

    warm = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)
    warmup_num_steps_c = max(2, warmup_num_steps)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        decay_frac = jnp.clip(
            (total_num_steps - step) /
            jnp.maximum(float(total_num_steps - warmup_num_steps_c), 1.0), 0.0, 1.0)
        return jnp.where(step < warmup_num_steps_c, warm(step),
                         warmup_max_lr * decay_frac)

    return schedule


_SCHEDULE_BUILDERS = {
    LR_RANGE_TEST: lr_range_test,
    ONE_CYCLE: one_cycle,
    WARMUP_LR: warmup_lr,
    WARMUP_DECAY_LR: warmup_decay_lr,
}


def get_lr_schedule(name: str, params: Dict[str, Any],
                    base_lr: Optional[float] = None) -> Schedule:
    if name not in _SCHEDULE_BUILDERS:
        raise ValueError(f"unknown lr schedule {name!r}; valid: {VALID_LR_SCHEDULES}")
    return _SCHEDULE_BUILDERS[name](**params)


class LRScheduler:
    """Object-style wrapper matching the reference scheduler API."""

    def __init__(self, schedule: Schedule, last_batch_iteration: int = -1):
        self.schedule = schedule
        self.last_batch_iteration = last_batch_iteration

    def step(self, last_batch_iteration: Optional[int] = None) -> None:
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration

    def get_lr(self) -> List[float]:
        return [float(self.schedule(max(self.last_batch_iteration, 0)))]

    def get_last_lr(self) -> List[float]:
        return self.get_lr()

    def state_dict(self) -> dict:
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd: dict) -> None:
        self.last_batch_iteration = sd["last_batch_iteration"]


def add_tuning_arguments(parser):
    """Reference ``lr_schedules.py:add_tuning_arguments`` (exported top-level)."""
    group = parser.add_argument_group("Convergence Tuning",
                                      "Convergence tuning configurations")
    group.add_argument("--lr_schedule", type=str, default=None,
                       help="LR schedule for training.")
    group.add_argument("--lr_range_test_min_lr", type=float, default=0.001)
    group.add_argument("--lr_range_test_step_rate", type=float, default=1.0)
    group.add_argument("--lr_range_test_step_size", type=int, default=1000)
    group.add_argument("--lr_range_test_staircase", type=bool, default=False)
    group.add_argument("--cycle_first_step_size", type=int, default=1000)
    group.add_argument("--cycle_first_stair_count", type=int, default=-1)
    group.add_argument("--cycle_second_step_size", type=int, default=-1)
    group.add_argument("--cycle_second_stair_count", type=int, default=-1)
    group.add_argument("--decay_step_size", type=int, default=1000)
    group.add_argument("--cycle_min_lr", type=float, default=0.01)
    group.add_argument("--cycle_max_lr", type=float, default=0.1)
    group.add_argument("--decay_lr_rate", type=float, default=0.0)
    group.add_argument("--warmup_min_lr", type=float, default=0)
    group.add_argument("--warmup_max_lr", type=float, default=0.001)
    group.add_argument("--warmup_num_steps", type=int, default=1000)
    group.add_argument("--warmup_type", type=str, default=WARMUP_LOG_RATE)
    return parser
