"""MoQ — Mixed-precision quantization-during-training.

Reference ``runtime/quantize.py:11 Quantizer``: bits anneal from
``start_bits`` to ``target_bits``, halving the value range every
``quantize_period`` steps; with eigenvalue guidance each layer's period is
scaled by its (normalized) leading Hessian eigenvalue so sensitive layers
quantize later.  The quantization itself is the group fake-quant from
``compression/ops.py`` (kernel analog: ``csrc/quantization/fake_quantizer.cu``).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax

from ..compression.ops import fake_quantize


class Quantizer:

    def __init__(self, q_target_bits: int = 8, q_start_bits: int = 16,
                 q_period: int = 100, q_offset: int = 0, q_groups: int = 1,
                 q_type: str = "symmetric", q_rounding: str = "nearest",
                 use_quantizer_kernel: bool = False, layer_num: int = 0):
        self.q_target_bits = q_target_bits
        self.q_start_bits = q_start_bits
        self.q_period = max(int(q_period), 1)
        self.q_offset = q_offset
        self.q_groups = q_groups
        self.q_type = q_type
        self.q_rounding = q_rounding
        self.layer_num = layer_num

    def current_bits(self, step: int,
                     eigenvalue_ratio: Optional[float] = None) -> int:
        """Bits at ``step``: one bit dropped per (scaled) period after the
        offset, floored at target_bits."""
        if step < self.q_offset:
            return self.q_start_bits
        period = self.q_period
        if eigenvalue_ratio is not None:
            # sensitive layers (ratio ~1) quantize slower (longer period)
            period = max(1, int(period * (1.0 + eigenvalue_ratio)))
        drops = (step - self.q_offset) // period
        return max(self.q_target_bits, self.q_start_bits - int(drops))

    def quantize(self, params, step: int,
                 eigenvalue_ratios: Optional[Dict[str, float]] = None):
        """Fake-quantize every >=2D leaf at its current bit width."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        out = []
        for path, leaf in flat:
            name = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                            for k in path)
            if getattr(leaf, "ndim", 0) < 2:
                out.append(leaf)
                continue
            ratio = (eigenvalue_ratios or {}).get(name)
            bits = self.current_bits(step, ratio)
            if bits >= 16:
                out.append(leaf)
            else:
                out.append(fake_quantize(leaf, bits, self.q_groups,
                                         self.q_type, False))
        return jax.tree_util.tree_unflatten(treedef, out)
