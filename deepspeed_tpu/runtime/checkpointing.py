"""Checkpoint save/load.

Analog of reference ``runtime/engine.py:3068 save_checkpoint`` /
``:2708 load_checkpoint`` + the pluggable ``CheckpointEngine``
(``runtime/checkpoint_engine/checkpoint_engine.py``).  TPU-native storage is
orbax: sharded arrays are written by all hosts cooperatively (the analog of each
rank writing its ``zero_pp_rank_*`` partition file) and restored with *current*
shardings — which gives elastic / universal-checkpoint resharding (reference
``checkpoint/deepspeed_checkpoint.py``) for free: save under one mesh, load under
another, orbax + XLA redistribute.

Layout (per the reference's tag-directory protocol)::

    <save_dir>/
      latest                # text file containing the newest tag (engine.py:3105)
      <tag>/
        state/              # orbax pytree: params, opt_state, scaler, step
        ds_meta.json        # counters, config snapshot, client_state
"""

from __future__ import annotations

import json
import os
from abc import ABC, abstractmethod
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..utils.logging import log_dist, logger


class CheckpointEngine(ABC):
    """Pluggable storage backend (reference ``checkpoint_engine.py``)."""

    def __init__(self, config_params=None):
        pass

    @abstractmethod
    def save(self, state_tree, path: str) -> None:
        ...

    @abstractmethod
    def load(self, path: str, abstract_target=None):
        ...

    def makedirs(self, path: str, exist_ok: bool = True) -> None:
        os.makedirs(path, exist_ok=exist_ok)

    def commit(self, tag: str) -> bool:
        return True


class OrbaxCheckpointEngine(CheckpointEngine):
    """Async-capable sharded-array storage via orbax (the Nebula-engine analog —
    reference ``nebula_checkpoint_engine.py`` — is subsumed: orbax is already
    async + multi-host)."""

    def __init__(self, config_params=None, use_async: bool = False):
        super().__init__(config_params)
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self._ckptr = ocp.StandardCheckpointer()

    def save(self, state_tree, path: str) -> None:
        path = os.path.abspath(path)
        self._ckptr.save(path, state_tree, force=True)
        self._ckptr.wait_until_finished()

    def load(self, path: str, abstract_target=None):
        path = os.path.abspath(path)
        if abstract_target is not None:
            return self._ckptr.restore(path, target=abstract_target)
        return self._ckptr.restore(path)


class CheckpointManager:
    """Engine-facing checkpoint orchestration with the reference's tag protocol."""

    def __init__(self, engine, checkpoint_engine: Optional[CheckpointEngine] = None):
        self.engine = engine
        self.checkpoint_engine = checkpoint_engine or OrbaxCheckpointEngine()

    # -- tag handling (reference engine.py:3050 _checkpoint_tag_validation) ----
    def _validate_tag(self, tag: str) -> None:
        mode = self.engine._config.checkpoint_config.tag_validation.lower()
        if mode == "ignore" or jax.process_count() == 1:
            return
        from .. import comm as dist

        import hashlib

        digest = hashlib.sha256(str(tag).encode()).digest()[:8]
        h = np.frombuffer(digest, dtype=np.int64)
        gathered = dist.all_gather_host(jax.numpy.asarray(h))
        valid = bool(np.all(np.asarray(gathered) == np.asarray(gathered)[0]))
        if not valid:
            msg = f"checkpoint tag '{tag}' is not consistent across processes"
            if mode == "fail":
                raise RuntimeError(msg)
            logger.warning(msg)

    def _default_dir(self, save_dir, for_load: bool = False):
        """``nebula.persistent_storage_path`` is the default checkpoint
        root when no directory is passed (reference nebula tier); loads
        prefer ``nebula.load_path`` when set (the reference's warm-start
        redirection, gated on ``enable_nebula_load``)."""
        if save_dir is not None:
            return save_dir
        neb = getattr(self.engine._config, "nebula_config", None)
        # a disabled nebula block carrying stale paths must not silently
        # redirect the default roots (the reference gates all nebula
        # behavior on enabled=true)
        if neb is not None and neb.enabled:
            if for_load and neb.enable_nebula_load and neb.load_path:
                return neb.load_path
            if neb.persistent_storage_path:
                return neb.persistent_storage_path
        raise ValueError(
            "save_checkpoint/load_checkpoint need a directory (or set "
            "nebula.persistent_storage_path as the default root)")

    def save(self, save_dir: str, tag: Optional[str] = None,
             client_state: Optional[Dict[str, Any]] = None,
             save_latest: bool = True) -> str:
        engine = self.engine
        save_dir = self._default_dir(save_dir)
        if tag is None:
            tag = f"global_step{engine.global_steps}"
        self._validate_tag(tag)
        ckpt_dir = os.path.join(save_dir, str(tag))
        self.checkpoint_engine.makedirs(ckpt_dir)

        self.checkpoint_engine.save(engine.state, os.path.join(ckpt_dir, "state"))
        if getattr(engine, "_offload_opt", None) is not None:
            # host-side optimizer partition (ZeRO-Offload/Infinity tier):
            # every process saves ITS ZeRO partition (reference writes
            # per-rank ``zero_pp_rank_*_optim_states.pt`` the same way)
            np.savez(os.path.join(
                ckpt_dir, f"offload_optimizer.p{jax.process_index()}.npz"),
                **engine._offload_opt.state_dict())
        if getattr(engine, "_block_opt", None) is not None and \
                jax.process_index() == 0:
            # streamed block params: fp32 master + moments (param-stream tier;
            # single-controller, so process 0 owns the whole store)
            np.savez(os.path.join(ckpt_dir, "offload_blocks.npz"),
                     **engine._block_opt.state_dict())
        meta = {
            "tag": str(tag),
            "global_steps": engine.global_steps,
            "global_samples": engine.global_samples,
            "micro_steps": engine.micro_steps,
            "skipped_steps": engine.skipped_steps,
            "dp_world_size": engine.topology.data_parallel_size,
            "mesh": engine.topology.axis_sizes,
            "zero_stage": engine.zero_stage,
            "dtype": engine._config.precision_dtype,
            "lr_scheduler": (engine.lr_scheduler.state_dict()
                             if engine.lr_scheduler else None),
            "client_state": client_state or {},
        }
        if jax.process_index() == 0:
            with open(os.path.join(ckpt_dir, "ds_meta.json"), "w") as f:
                json.dump(meta, f, indent=2)
            if save_latest:
                with open(os.path.join(save_dir, "latest"), "w") as f:
                    f.write(str(tag))
        from .. import comm as dist

        dist.barrier("checkpoint_save")
        log_dist(f"saved checkpoint {ckpt_dir}", ranks=[0])
        return ckpt_dir

    def load(self, load_dir: str, tag: Optional[str] = None,
             load_optimizer_states: bool = True, load_module_only: bool = False):
        engine = self.engine
        load_dir = self._default_dir(load_dir, for_load=True)
        if tag is None:
            latest_path = os.path.join(load_dir, "latest")
            if not os.path.isfile(latest_path):
                logger.warning(f"no 'latest' file at {load_dir}; nothing loaded")
                return None, {}
            with open(latest_path) as f:
                tag = f.read().strip()
        ckpt_dir = os.path.join(load_dir, str(tag))
        meta_path = os.path.join(ckpt_dir, "ds_meta.json")
        meta: Dict[str, Any] = {}
        if os.path.isfile(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)

        # abstract target carries *current* shardings -> orbax reshards on read
        abstract = jax.tree_util.tree_map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            engine.state, engine.state_shardings)
        if load_module_only or not load_optimizer_states:
            loaded = self.checkpoint_engine.load(os.path.join(ckpt_dir, "state"),
                                                 abstract_target=abstract)
            engine.state["params"] = loaded["params"]
            if not load_module_only:
                engine.state["step"] = loaded["step"]
                engine.state["scaler"] = loaded["scaler"]
        else:
            engine.state = self.checkpoint_engine.load(
                os.path.join(ckpt_dir, "state"), abstract_target=abstract)

        if getattr(engine, "_offload_opt", None) is not None:
            # re-sync this process's host master partition with the restored
            # params (grad/ZeRO-partition layout), then overlay saved
            # moments/master when present
            partitioned = engine.to_grad_layout(engine.state["params"])
            pieces = engine._offload_pieces_of(partitioned)
            for piece, off, size in zip(pieces,
                                        engine._offload_opt.offsets[:-1],
                                        engine._offload_opt.sizes):
                engine._offload_opt.master[off:off + size] = \
                    np.asarray(piece, np.float32).reshape(-1)
            off_path = os.path.join(
                ckpt_dir, f"offload_optimizer.p{jax.process_index()}.npz")
            if not os.path.isfile(off_path) and jax.process_count() == 1:
                # round-1 checkpoints used the unsuffixed name
                off_path = os.path.join(ckpt_dir, "offload_optimizer.npz")
            if load_optimizer_states and not load_module_only and \
                    os.path.isfile(off_path):
                with np.load(off_path) as z:
                    engine._offload_opt.load_state_dict(dict(z))

        if getattr(engine, "_block_opt", None) is not None:
            blk_path = os.path.join(ckpt_dir, "offload_blocks.npz")
            if os.path.isfile(blk_path):
                with np.load(blk_path) as z:
                    sd = dict(z)
                if load_optimizer_states and not load_module_only:
                    engine._block_opt.load_state_dict(sd)
                else:
                    # module-only load: restore the master weights but keep
                    # fresh moments/step counts (matches the resident gating)
                    engine._block_opt.master[:] = sd["master"]
                engine._param_store.master = engine._block_opt.param_leaves()
                engine._param_store.refresh_compute()

        engine.global_steps = int(meta.get("global_steps", 0))
        engine.global_samples = int(meta.get("global_samples", 0))
        engine.micro_steps = int(meta.get("micro_steps", 0))
        engine.skipped_steps = int(meta.get("skipped_steps", 0))
        if engine.lr_scheduler is not None and meta.get("lr_scheduler"):
            engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])
        log_dist(f"loaded checkpoint {ckpt_dir} at step {engine.global_steps}",
                 ranks=[0])
        return ckpt_dir, meta.get("client_state", {})
