"""Progressive layer drop (reference ``runtime/progressive_layer_drop.py:7``):
stochastic-depth schedule — the keep probability theta anneals from 1 toward
``theta`` as ``(1 - theta) * exp(-gamma * t) + theta``, and layer ``l`` of
``L`` keeps with probability ``1 - (l / L) * (1 - theta_t)`` (deeper layers
drop more).  Models consume ``layer_keep_prob`` inside their block scan
(multiply the residual branch by a Bernoulli draw / keep_prob)."""

from __future__ import annotations


class ProgressiveLayerDrop:

    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def get_theta(self, global_step: int) -> float:
        import math

        t = (1.0 - self.theta) * math.exp(-self.gamma * global_step) + \
            self.theta
        return t

    def update_state(self, global_step: int) -> float:
        self.current_theta = self.get_theta(global_step)
        return self.current_theta

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta}

    def layer_keep_prob(self, layer_idx: int, num_layers: int,
                        global_step: int) -> float:
        theta_t = self.get_theta(global_step)
        return 1.0 - (layer_idx + 1) / max(num_layers, 1) * (1.0 - theta_t)
