"""The training engine.

TPU-native analog of reference ``deepspeed/runtime/engine.py`` (``DeepSpeedEngine``
:189).  The user contract is preserved — ``initialize(model, config) -> engine``,
then either the reference-style micro-step loop::

    loss = engine(batch)        # forward (engine.py:1766)
    engine.backward(loss)       # engine.py:1915
    engine.step()               # engine.py:2126

or the fused TPU-native path, one compiled XLA program per *global* step::

    state, metrics = engine.train_batch(batch)   # fwd+bwd+GAS+update, one jit

Where the reference orchestrates fwd/bwd/allreduce/step imperatively with hooks
and NCCL calls, here the whole training step — gradient accumulation loop
(lax.scan), mixed-precision casting, loss scaling, ZeRO-sharded gradient
reduction, clipping, optimizer update — is a single jitted function whose
communication schedule is derived by XLA SPMD from the sharding specs in
``runtime/zero/sharding.py``.  Grad allreduce (engine.py:1895), ZeRO
reduce-scatter (stage_1_and_2.py:952) and post-step allgather
(stage_1_and_2.py:1772) all fall out of those specs.

Master weights are always fp32 (the engine casts to the compute dtype inside the
loss closure), which subsumes the reference's separate FP16_Optimizer /
BF16_Optimizer / fused-master-weight machinery (fp16/fused_optimizer.py:20,
bf16_optimizer.py:38).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterator, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import comm as dist
from ..analysis.sentry import RecompileSentry
from ..ops.optimizers import get_optimizer
from ..parallel.topology import (DATA_AXES, SP_AXIS, MeshTopology,
                                 topology_from_config)
from ..telemetry import MetricsRegistry
from ..utils.logging import log_dist, logger
from ..utils.timer import (BACKWARD_GLOBAL_TIMER, FORWARD_GLOBAL_TIMER,
                           STEP_GLOBAL_TIMER, TRAIN_BATCH_TIMER,
                           SynchronizedWallClockTimer, ThroughputTimer)
from .checkpointing import CheckpointManager
from .config import DeepSpeedConfig
from .dataloader import DeepSpeedDataLoader, RepeatingLoader
from .fp16.loss_scaler import LossScaleState, has_overflow, update_scale
from .lr_schedules import LRScheduler, get_lr_schedule
from .model import ModelSpec
from .zero.sharding import ZeroShardingPlan, constrain

PyTree = Any

MEMORY_OPT_ALLREDUCE_SIZE = 500000000


class _LazyMetrics:
    """Mapping over device-side metrics that defers the host transfer until
    first read.  Keeps the train loop free of per-step device_get round
    trips (which serialize the pipeline; very costly on remote backends)."""

    __slots__ = ("_dev", "_host")

    def __init__(self, device_metrics):
        self._dev = dict(device_metrics)
        self._host = None

    def _force(self):
        if self._host is None:
            got = jax.device_get(self._dev)
            self._host = {k: np.asarray(v).item() for k, v in got.items()}
        return self._host

    def __getitem__(self, k):
        return self._force()[k]

    def get(self, k, default=None):
        if k not in self._dev:       # don't force a transfer for a miss
            return default
        return self._force().get(k, default)

    def __contains__(self, k):
        return k in self._dev

    def __iter__(self):
        return iter(self._dev)

    def __len__(self):
        return len(self._dev)

    def keys(self):
        return self._dev.keys()

    def items(self):
        return self._force().items()

    def values(self):
        return self._force().values()

    def __repr__(self):
        return repr(self._force())


def _cast_floating(tree: PyTree, dtype) -> PyTree:
    def cast(x):
        if jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(cast, tree)


class DeepSpeedEngine:
    """Holds the sharded train state and the compiled step functions."""

    def __init__(self,
                 args=None,
                 model: Optional[ModelSpec] = None,
                 optimizer: Optional[Union[optax.GradientTransformation,
                                           Callable]] = None,
                 model_parameters=None,
                 training_data=None,
                 lr_scheduler=None,
                 mpu=None,
                 dist_init_required: Optional[bool] = None,
                 collate_fn=None,
                 config: Optional[Union[str, dict]] = None,
                 config_class: Optional[DeepSpeedConfig] = None,
                 dont_change_device: bool = False):
        assert model is not None, "deepspeed_tpu.initialize requires a model"
        assert isinstance(model, ModelSpec), (
            "model must be a deepspeed_tpu ModelSpec (see runtime/model.py); "
            "wrap flax modules with deepspeed_tpu.runtime.model.from_flax")
        dist.init_distributed()

        raw_config = config if config is not None else {}
        if isinstance(raw_config, str):
            import json

            with open(raw_config) as f:
                raw_dict = json.load(f)
        else:
            raw_dict = dict(raw_config)
        self.topology: MeshTopology = topology_from_config(raw_dict.get("mesh"))
        dist.configure(topology=self.topology)
        self.mesh = self.topology.mesh

        self._config = config_class or DeepSpeedConfig(
            raw_dict, mesh_topology=self.topology)
        dist.comms_logger.configure(self._config.comms_config)

        self.module = model  # reference name for the wrapped model
        self.model_spec = model
        self.client_optimizer = optimizer
        self.client_lr_scheduler = lr_scheduler
        self.training_data = training_data
        self.collate_fn = collate_fn

        self.global_steps = 0
        self.global_samples = 0
        self.micro_steps = 0
        self.skipped_steps = 0
        self._cached_metrics: Dict[str, Any] = {}

        # precision
        self.fp16_enabled = self._config.fp16_enabled
        self.bfloat16_enabled = self._config.bfloat16_enabled
        self.compute_dtype = {
            "float16": jnp.float16,
            "bfloat16": jnp.bfloat16,
            "float32": jnp.float32,
        }[self._config.precision_dtype]
        self.dynamic_loss_scale = self.fp16_enabled and self._config.loss_scale == 0

        # ZeRO plan
        self.zero_stage = self._config.zero_optimization_stage
        self.zero_plan = ZeroShardingPlan(
            self.zero_stage, self.mesh,
            param_persistence_threshold=(
                self._config.zero_config.param_persistence_threshold
                if self.zero_stage >= 3 else 0))

        # ZeRO-Offload / ZeRO-Infinity: optimizer state lives on host
        # (DRAM or NVMe) and steps through the C++ CPU optimizer
        off = self._config.zero_config.offload_optimizer
        self.offload_enabled = bool(off is not None and
                                    off.device.value != "none")
        self._offload_opt = None
        if self.offload_enabled and optimizer is not None:
            raise ValueError(
                "offload_optimizer requires a config-specified optimizer "
                "(adam/adamw/adagrad) — client optax transformations cannot "
                "run on host (reference: offload needs DeepSpeedCPUAdam)")

        # ZeRO-Infinity parameter streaming: block params stay host-resident
        # and stream through io_callback per scan step (zero/param_stream.py)
        offp = self._config.zero_config.offload_param
        self.param_stream_enabled = bool(offp is not None and
                                         offp.device.value != "none")
        self._param_store = None
        self._block_opt = None
        if self.param_stream_enabled:
            if not self.offload_enabled:
                raise ValueError(
                    "offload_param requires offload_optimizer too: streamed "
                    "block gradients are accumulated on host and must be "
                    "stepped by the host optimizer (reference ZeRO-Infinity "
                    "couples param+optimizer NVMe tiers, zero/stage3.py:486)")
            if model.pipeline_hooks is None:
                raise ValueError(
                    "offload_param needs a block-structured model "
                    "(ModelSpec.pipeline_hooks) so layers can stream "
                    "one scan step at a time")
            # multi-controller validated (round 3): callbacks pin to the
            # GLOBAL first device, so process 0's store serves loads and
            # receives the full psum'd grad push; _host_apply's
            # host_all_reduce_sum distributes it.  2-process x 2-device
            # loss parity vs the single-process run is asserted by
            # tests/unit/test_multiprocess.py::test_two_process_param_streaming_matches_single_process.
            if self.topology.pipe_parallel_size > 1:
                raise ValueError(
                    "offload_param with pp>1 is unsupported: the pipeline "
                    "engine shards the block params the streaming tier "
                    "removes from device state")

        # curriculum learning (reference engine consumes curriculum seqlen
        # at :1806-1812)
        self.curriculum_scheduler = None
        if self._config.curriculum_enabled:
            from .data_pipeline.curriculum_scheduler import CurriculumScheduler

            self.curriculum_scheduler = CurriculumScheduler(
                self._config.curriculum_params)

        # activation_checkpointing config -> model remat selection
        # (reference activation_checkpointing/checkpointing.py:749) — must
        # run before _build_state so the first trace sees the new knobs
        from .remat import apply_config_to_model as _apply_ac

        _apply_ac(self._config.activation_checkpointing_config,
                  self.model_spec,
                  log=lambda m: log_dist(m, ranks=[0]),
                  n_devices=self.mesh.size if self.mesh is not None else 1)

        # sparse_gradients -> row-sparse embedding-grad exchange (reference
        # engine sparse allreduce, runtime/engine.py:2461-2476)
        if self._config.sparse_gradients_enabled:
            mc = getattr(self.model_spec, "model_config", None)
            if mc is not None and hasattr(mc, "sparse_embedding_grad"):
                mc.sparse_embedding_grad = True
                log_dist("sparse_gradients: embedding grads exchange "
                         "row-sparse over the data axes "
                         "(runtime/sparse_tensor.py)", ranks=[0])
            else:
                logger.warning(
                    "sparse_gradients: true, but the model does not expose "
                    "a sparse_embedding_grad knob; exchange stays dense")

        # random-LTD: scheduler drives the per-layer kept-token count; the
        # count is a trace-time constant, so crossing a schedule value
        # rebuilds the step fns (same retrace pattern as compression
        # schedule_offsets).  Reference data_routing/basic_layer.py:13.
        self.random_ltd_scheduler = None
        self._ltd_keep = None
        self._ltd_saturated = False
        if self._config.random_ltd_enabled:
            from .data_pipeline.random_ltd import RandomLTDScheduler

            mc = getattr(self.model_spec, "model_config", None)
            if mc is None or not hasattr(mc, "random_ltd_keep"):
                raise ValueError(
                    "data_routing.random_ltd requires a model that exposes "
                    "a random_ltd_keep knob (ModelSpec.model_config, e.g. "
                    "models/gpt2.GPT2Config)")
            self.random_ltd_scheduler = RandomLTDScheduler(
                self._config.random_ltd_params)
            # reference layer-range keys (random_ltd_layer_id_start /
            # random_ltd_layer_num) narrow WHICH layers drop tokens
            p = self._config.random_ltd_params
            if "random_ltd_layer_id_start" in p and \
                    hasattr(mc, "random_ltd_layer_start"):
                mc.random_ltd_layer_start = int(p["random_ltd_layer_id_start"])
            if "random_ltd_layer_num" in p and \
                    hasattr(mc, "random_ltd_layer_num"):
                mc.random_ltd_layer_num = int(p["random_ltd_layer_num"])

        # schedules and optimizer
        self._configure_lr_schedule()
        self._configure_optimizer()

        # 1-bit optimizers: past freeze_step the DP gradient exchange runs
        # through the error-compensated compressed all-reduce
        # (runtime/comm/compressed.py; reference runtime/comm/nccl.py:52).
        # Warmup stays dense, as the reference does.
        onebit_names = ("onebitadam", "onebitlamb", "zerooneadam")
        self.onebit_comm_enabled = bool(
            self._config.optimizer_name in onebit_names
            and self.topology.data_parallel_size > 1
            and self.topology.expert_parallel_size == 1
            and self.topology.model_parallel_size == 1
            and self.topology.pipe_parallel_size == 1
            and self.topology.sequence_parallel_size == 1
            # stage 1 composes: the exchange returns full mean grads and
            # the partitioned optimizer update slices them per dp shard
            # (the reference runs its 1-bit optimizers under ZeRO-1,
            # fp16/onebit/adam.py:11); stages 2/3 shard the grads
            # themselves and have no dense-exchange seam to compress
            and self.zero_stage in (0, 1)
            and not self.offload_enabled
            and not self.param_stream_enabled
            # sparse_embedding_lookup's backward opens its own shard_map;
            # nesting it inside the onebit step's shard_map is rejected by
            # jax (and the flattened compressed exchange covers the
            # embedding grads anyway)
            and not self._config.sparse_gradients_enabled)
        self._onebit_compressed = False
        self._onebit_freeze = int(
            (self._config.optimizer_params or {}).get("freeze_step", 100))
        if self._config.optimizer_name in onebit_names and \
                not self.onebit_comm_enabled and \
                self.topology.data_parallel_size > 1:
            if self.zero_stage >= 2:
                why = f"zero_optimization.stage={self.zero_stage} (needs <=1)"
            elif self.offload_enabled or self.param_stream_enabled:
                why = "offload/param streaming"
            elif self._config.sparse_gradients_enabled:
                why = ("sparse_gradients (its backward opens its own "
                       "shard_map; nesting inside the onebit step is "
                       "rejected by jax)")
            else:
                why = "a non-pure-dp mesh (tp/pp/ep/sp axes present)"
            msg = (
                "1-bit optimizer: the compressed gradient exchange does not "
                f"support {why}; the exchange would silently stay dense — a "
                "convergence-relevant behavior change vs the reference "
                "semantics. Remove the conflicting feature, or set "
                '"strict": false to accept the dense exchange.')
            if self._config.strict:
                raise ValueError(msg)
            logger.warning(msg + " (strict=false: keeping the dense "
                           "exchange; the optimizer's frozen-variance "
                           "semantics still apply)")

        # sharded state
        self._init_rng = jax.random.PRNGKey(self._config.seed or 42)
        self._dropout_rng = jax.random.PRNGKey((self._config.seed or 42) + 1)
        self._build_state()
        self._configure_stage3_liveness()
        self._build_step_fns()

        # data
        self.training_dataloader = self.deepspeed_io(training_data) \
            if training_data is not None else None
        self._data_iterator: Optional[Iterator] = None

        # timers/monitor/telemetry: one metrics registry backs the wall-
        # clock timer histograms, the train loss/lr/throughput gauges, and
        # the MonitorMaster event routing (_finalize_metrics writes the
        # registry snapshot through the CSV/TensorBoard/W&B backends on
        # report steps — telemetry/, docs/observability.md)
        self.metrics = MetricsRegistry()
        self.timers = SynchronizedWallClockTimer(registry=self.metrics)
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_batch_size(),
            steps_per_output=self._config.steps_per_print or 10)
        self.wall_clock_breakdown_enabled = self._config.wall_clock_breakdown
        self._g_train_loss = self.metrics.gauge(
            "train_loss", "last reported global-step loss",
            monitor_name="Train/Samples/train_loss")
        self._g_train_lr = self.metrics.gauge(
            "train_lr", "last reported learning rate",
            monitor_name="Train/Samples/lr")
        # fp16-only: an unconditional family would emit a dead-constant
        # loss_scale series (and CSV file) for every full-precision run
        self._g_loss_scale = self.metrics.gauge(
            "train_loss_scale", "fp16 dynamic loss scale",
            monitor_name="Train/Samples/loss_scale") \
            if self.fp16_enabled else None
        self._g_samples_per_sec = self.metrics.gauge(
            "train_samples_per_sec",
            "running-average training throughput (ThroughputTimer)",
            monitor_name="Train/Samples/throughput")
        self._g_global_steps = self.metrics.gauge(
            "train_global_steps", "optimizer steps completed")
        from ..monitor.monitor import MonitorMaster

        self.monitor = MonitorMaster(self._config.monitor_config)
        self._metrics_server = None

        self.checkpoint_manager = CheckpointManager(self)

        # micro-step accumulation buffers (forward/backward/step shim path)
        self._accum_grads: Optional[PyTree] = None
        self._accum_losses = []
        self._pending_batch = None

        log_dist(
            f"DeepSpeedEngine: mesh={self.topology}, zero_stage={self.zero_stage}, "
            f"dtype={self._config.precision_dtype}, "
            f"micro_bs/chip={self.train_micro_batch_size_per_gpu()}, "
            f"gas={self.gradient_accumulation_steps()}, "
            f"global_bs={self.train_batch_size()}", ranks=[0])

    # ------------------------------------------------------------------ config
    def train_batch_size(self) -> int:
        return self._config.train_batch_size

    def train_micro_batch_size_per_gpu(self) -> int:
        return self._config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self) -> int:
        return self._config.gradient_accumulation_steps

    def micro_batch_global(self) -> int:
        """Micro-batch across the whole data-parallel world (one scan step)."""
        return (self.train_micro_batch_size_per_gpu() *
                self.topology.data_parallel_size)

    def zero_optimization_stage(self) -> int:
        return self.zero_stage

    def gradient_clipping(self) -> float:
        return self._config.gradient_clipping

    def steps_per_print(self) -> int:
        return self._config.steps_per_print

    def loss_scale(self) -> float:
        if not self.fp16_enabled:
            return 1.0
        return float(jax.device_get(self.state["scaler"].cur_scale))

    def get_lr(self):
        if self.offload_enabled and self._offload_opt is not None:
            return [self._host_lr()]
        # index by *applied* steps so the reported lr matches the in-graph
        # optax schedule count, which does not advance on overflow-skipped
        # steps (nor does the reference scheduler)
        step = max(self.global_steps - self.skipped_steps, 0)
        if self.lr_schedule is not None:
            return [float(self.lr_schedule(step))]
        return [self._base_lr]

    def get_global_grad_norm(self) -> Optional[float]:
        gn = self._cached_metrics.get("grad_norm")
        return float(gn) if gn is not None else None

    # --------------------------------------------------------------- optimizer
    def _configure_lr_schedule(self) -> None:
        self._base_lr = (self._config.optimizer_params or {}).get("lr", 1e-3)
        if callable(self.client_lr_scheduler):
            self.lr_schedule = self.client_lr_scheduler
        elif self._config.scheduler_name:
            self.lr_schedule = get_lr_schedule(self._config.scheduler_name,
                                               self._config.scheduler_params or {})
        else:
            self.lr_schedule = None
        self.lr_scheduler = (LRScheduler(self.lr_schedule)
                             if self.lr_schedule is not None else None)

    def _configure_optimizer(self) -> None:
        if self.client_optimizer is not None:
            base_tx = self.client_optimizer
            log_dist("Using client optimizer (optax transformation)", ranks=[0])
        elif self._config.optimizer_name:
            base_tx = get_optimizer(self._config.optimizer_name,
                                    self._config.optimizer_params or {},
                                    lr_schedule=self.lr_schedule)
            log_dist(f"Using config optimizer = {self._config.optimizer_name}",
                     ranks=[0])
        else:
            base_tx = get_optimizer("adam", {"lr": self._base_lr},
                                    lr_schedule=self.lr_schedule)
        chain = []
        if self._config.gradient_clipping:
            chain.append(optax.clip_by_global_norm(self._config.gradient_clipping))
        chain.append(base_tx)
        self.tx = optax.chain(*chain) if len(chain) > 1 else base_tx
        self.optimizer = self.tx  # reference-compat alias in the return tuple

    # ------------------------------------------------------------------- state
    def _scaler_init(self) -> LossScaleState:
        if self.fp16_enabled and not self.dynamic_loss_scale:
            return LossScaleState.create(init_scale=self._config.loss_scale)
        return LossScaleState.create(
            init_scale=self._config.dynamic_loss_scale_args["init_scale"],
            delayed_shift=self._config.dynamic_loss_scale_args["delayed_shift"])

    def _build_state(self) -> None:
        if self.param_stream_enabled:
            self._build_state_streamed()
            self._init_offload_optimizer()
            return

        def onebit_errors(params):
            """Per-worker/server error-feedback buffers for the compressed
            exchange, [dp, ...]-stacked so they shard over dp.  Created at
            init (zeros are a no-op through the dense warmup) so the state
            pytree is stable across the freeze_step transition."""
            if not self.onebit_comm_enabled:
                return ()
            from .comm.compressed import error_shapes

            n = self.topology.data_parallel_size
            total = sum(int(np.prod(x.shape))
                        for x in jax.tree_util.tree_leaves(params))
            we_s, se_s = error_shapes((total,), n)
            return {"we": jnp.zeros((n,) + we_s, jnp.float32),
                    "se": jnp.zeros((n,) + se_s, jnp.float32)}

        def init_state(rng):
            # init_fn directly: a user-side OnDevice("meta") context must
            # not turn the ENGINE's init into abstract params (the engine
            # already materializes sharded-at-birth under jit)
            params = self.model_spec.init_fn(rng)
            params = _cast_floating(params, jnp.float32)  # fp32 master weights
            # offload: optimizer state is host-side (HostOffloadOptimizer)
            opt_state = () if self.offload_enabled else self.tx.init(params)
            return {
                "step": jnp.zeros((), jnp.int32),
                "params": params,
                "opt_state": opt_state,
                "scaler": self._scaler_init(),
                "onebit": onebit_errors(params),
            }

        abstract = jax.eval_shape(init_state, self._init_rng)
        self._abstract_params = abstract["params"]
        self.tp_specs = (self.model_spec.tp_rules(self._abstract_params)
                         if self.model_spec.tp_rules else None)
        rep = NamedSharding(self.mesh, P())
        from ..parallel.topology import DP_AXIS as _DP

        self.state_shardings = {
            "step": rep,
            "params": self.zero_plan.param_shardings(self._abstract_params,
                                                     self.tp_specs),
            "opt_state": self.zero_plan.opt_shardings_like(
                self._abstract_params, abstract["opt_state"], self.tp_specs),
            "scaler": jax.tree_util.tree_map(lambda _: rep, abstract["scaler"]),
            "onebit": jax.tree_util.tree_map(
                lambda _: NamedSharding(self.mesh, P(_DP)), abstract["onebit"]),
        }
        self.grad_shardings = self.zero_plan.grad_shardings(
            self._abstract_params, self.tp_specs)
        with self.mesh:
            self.state = jax.jit(
                init_state, out_shardings=self.state_shardings)(self._init_rng)
        n_params = sum(x.size for x in jax.tree_util.tree_leaves(self.state["params"]))
        log_dist(f"initialized {n_params/1e6:.2f}M parameters", ranks=[0])

        if self.offload_enabled:
            self._init_offload_optimizer()

    def _configure_stage3_liveness(self) -> None:
        """Map ``stage3_prefetch_bucket_size`` / ``stage3_max_live_parameters``
        (reference ``zero/config.py:79``, coordinator
        ``partitioned_param_coordinator.py:239``) onto the scan granularity:
        the model gathers ``scan_group_size`` layers per scan step, so the
        prefetch bucket sets the gather size and the live cap bounds the
        resident gathered weights (current + prefetched group)."""
        mc = getattr(self.model_spec, "model_config", None)
        if mc is not None and hasattr(mc, "scan_group_size"):
            mc.scan_group_size = 1  # clear a stale G from a reused config
        if self.zero_stage != 3 or self.param_stream_enabled:
            return
        hooks = getattr(self.model_spec, "pipeline_hooks", None) or {}
        key = hooks.get("blocks_key")
        if mc is None or key is None or not getattr(mc, "scan_layers", True) \
                or not hasattr(mc, "scan_group_size"):
            return  # model doesn't implement grouped gathers
        from .zero.liveness import blocks_param_count, stage3_group_size

        node = self._abstract_params
        try:
            for k in ((key,) if isinstance(key, str) else key):
                node = node[k]
        except (KeyError, TypeError):
            return
        num_layers, per_layer = blocks_param_count(node)
        g = stage3_group_size(self._config.zero_config, per_layer, num_layers)
        mc.scan_group_size = g
        if g > 1:
            log_dist(
                f"ZeRO-3 liveness: gathering {g} layers/scan step "
                f"({g * per_layer / 1e6:.1f}M params/bucket, "
                f"prefetch_bucket_size="
                f"{self._config.zero_config.prefetch_bucket_size:.0e}, "
                f"max_live_parameters="
                f"{self._config.zero_config.max_live_parameters:.0e})",
                ranks=[0])

    # ------------------------------------------------- ZeRO-Infinity streaming
    def _pp_blocks_path(self) -> tuple:
        key = self.model_spec.pipeline_hooks["blocks_key"]
        return (key,) if isinstance(key, str) else tuple(key)

    def _build_state_streamed(self) -> None:
        """offload_param: init params on HOST, keep the stacked blocks in a
        StreamedParamStore (device HBM never holds more than one layer of
        them), device state holds only the small resident params — the
        persistence-threshold analog (``parameter_offload.py:316``)."""
        from .zero.param_stream import StreamedParamStore

        path = self._pp_blocks_path()
        # local_devices: under multi-controller, jax.devices()[0] can be
        # another process's device — device_get of the init would fail there
        cpu = jax.local_devices(backend="cpu")[0]
        with jax.default_device(cpu):
            params_full = jax.jit(
                lambda r: _cast_floating(self.model_spec.init_fn(r),
                                         jnp.float32))(self._init_rng)
        params_full = jax.device_get(params_full)
        node = params_full
        for k in path[:-1]:
            node = node[k]
        blocks = node[path[-1]]
        self._param_store = StreamedParamStore(blocks, self.compute_dtype)
        node[path[-1]] = {}  # resident tree: blocks live host-side only
        resident = params_full

        if self.model_spec.tp_rules is not None:
            log_dist("offload_param: ignoring tp_rules — streamed blocks are "
                     "replicated (TP over streamed layers is future work)",
                     ranks=[0])
        self.tp_specs = None
        abstract = jax.eval_shape(lambda: resident)
        self._abstract_params = abstract
        rep = NamedSharding(self.mesh, P())
        self.state_shardings = {
            "step": rep,
            "params": self.zero_plan.param_shardings(abstract, None),
            "opt_state": (),
            "scaler": jax.tree_util.tree_map(
                lambda _: rep, jax.eval_shape(self._scaler_init)),
            "onebit": (),
        }
        self.grad_shardings = self.zero_plan.grad_shardings(abstract, None)
        with self.mesh:
            state_host = {
                "step": jnp.zeros((), jnp.int32),
                "params": resident,
                "opt_state": (),
                "scaler": self._scaler_init(),
                "onebit": (),
            }
            self.state = jax.device_put(state_host, self.state_shardings)
        n_res = sum(x.size for x in
                    jax.tree_util.tree_leaves(self.state["params"]))
        n_blk = sum(m.size for m in self._param_store.master)
        log_dist(
            f"param streaming: {n_blk/1e6:.2f}M block params host-resident, "
            f"{n_res/1e6:.2f}M resident on device", ranks=[0])

        # block-master optimizer on host; adopt its flat buffer as the store's
        # master so updates land in place
        from .zero.offload import HostOffloadOptimizer

        off = self._config.zero_config.offload_optimizer
        self._block_opt = HostOffloadOptimizer(
            self._param_store.master,
            self._config.optimizer_name or "adam",
            self._config.optimizer_params or {},
            device=off.device.value,
            nvme_path=off.nvme_path,
            sub_group_size=self._config.zero_config.sub_group_size)
        self._param_store.master = self._block_opt.param_leaves()
        self._param_store.refresh_compute()

    def _streamed_loss_fn(self):
        """Loss over streamed blocks: embed/head use resident params; the
        scan body fetches one layer from host per step and is checkpointed so
        the backward re-fetches instead of saving L layers of weights."""
        import inspect

        hooks = self.model_spec.pipeline_hooks
        embed_fn, block_fn = hooks["embed_fn"], hooks["block_fn"]
        head_loss_fn = hooks["head_loss_fn"]
        dropout = float(hooks.get("dropout", 0.0) or 0.0)
        if dropout > 0.0:
            raise ValueError(
                "offload_param does not support dropout yet (the streamed "
                "block vjp would need the rng threaded through its "
                "residuals); set dropout=0")
        store = self._param_store
        L = store.num_layers
        if len(inspect.signature(block_fn).parameters) >= 3:
            call_block = lambda layer, x: block_fn(layer, x, None)
        else:
            call_block = block_fn
        apply_streamed = store.streamed_block(call_block)

        def loss_fn(params, batch, rng, train):
            if isinstance(batch, dict) and batch.get("labels") is not None:
                inputs, targets = batch["input_ids"], batch["labels"]
            else:
                ids = batch["input_ids"] if isinstance(batch, dict) else batch
                inputs, targets = ids[:, :-1], ids[:, 1:]
            x = embed_fn(params, inputs)

            def body(x, i):
                return apply_streamed(i, x), None

            x, _ = jax.lax.scan(body, x, jnp.arange(L))
            return head_loss_fn(params, x, targets)

        return loss_fn

    # ------------------------------------------------- partitioned host offload
    def to_grad_layout(self, params):
        """Reshard a params-shaped pytree into the grad (ZeRO partition)
        layout — one cached jitted identity, shared by offload init,
        checkpoint resync, and tests."""
        if not hasattr(self, "_to_grad_layout_fn"):
            self._to_grad_layout_fn = jax.jit(
                lambda p: p, out_shardings=self.grad_shardings)
        with self.mesh:
            return self._to_grad_layout_fn(params)

    @staticmethod
    def _piece_key(index) -> tuple:
        """Hashable key for a shard's index tuple (slices)."""
        return tuple((s.start or 0, s.stop) for s in index)

    def _local_pieces(self, arr) -> list:
        """Unique (key, np.ndarray) pieces of this process's shards, sorted.

        Replicated leaves dedupe to one piece; ZeRO-sharded leaves yield this
        process's partitions — the host-side analog of the reference's
        per-rank flat partition (``stage_1_and_2.py:102``)."""
        seen = {}
        for sh in arr.addressable_shards:
            key = self._piece_key(sh.index)
            if key not in seen:
                seen[key] = np.asarray(sh.data)
        return sorted(seen.items())

    def _init_offload_optimizer(self) -> None:
        """Partitioned host offload: every process owns the master/moments of
        its ZeRO partition (the grad sharding), updates it with the C++ CPU
        optimizer, and the updated partitions reshard back to the param layout
        through a jitted identity — XLA emits the all-gather the reference
        issues by hand after the offloaded step (``stage_1_and_2.py:1772``).
        Works multi-process: no ``process_count == 1`` restriction."""
        from .zero.offload import HostOffloadOptimizer

        off = self._config.zero_config.offload_optimizer
        # reshard the fp32 params into the grad (ZeRO partition) layout once;
        # each process then extracts its local pieces
        partitioned = self.to_grad_layout(self.state["params"])
        flat_parts, _ = jax.tree_util.tree_flatten(partitioned)
        self._offload_piece_keys = []
        init_pieces = []
        for leaf in flat_parts:
            items = self._local_pieces(leaf)
            self._offload_piece_keys.append([k for k, _ in items])
            init_pieces.extend(v for _, v in items)
        self._offload_opt = HostOffloadOptimizer(
            init_pieces,
            self._config.optimizer_name or "adam",
            self._config.optimizer_params or {},
            device=off.device.value,
            nvme_path=off.nvme_path,
            sub_group_size=self._config.zero_config.sub_group_size)
        # updated partitions -> param layout (replicates/allgathers as needed)
        self._offload_gather_fn = jax.jit(
            lambda p: p, out_shardings=self.state_shardings["params"],
            donate_argnums=(0,))
        log_dist(
            f"optimizer offload -> {off.device.value} "
            f"({self._offload_opt.total/1e6:.2f}M local elements, "
            f"native={self._offload_opt.opt.__class__.__name__}, "
            f"process {jax.process_index()}/{jax.process_count()})",
            ranks=[0])

    def _offload_pieces_of(self, tree) -> list:
        """Flatten a (grad-sharded) pytree into this process's pieces, in the
        same order as the host optimizer's layout."""
        pieces = []
        for leaf, keys in zip(jax.tree_util.tree_leaves(tree),
                              self._offload_piece_keys):
            items = dict(self._local_pieces(leaf))
            pieces.extend(items[k] for k in keys)
        return pieces

    def _offload_rebuild_params(self, new_pieces: list):
        """Reassemble updated partitions into sharded jax arrays (grad layout)
        then reshard to the param layout on device."""
        flat_abs, treedef = jax.tree_util.tree_flatten(self._abstract_params)
        flat_specs = jax.tree_util.tree_leaves(
            self.grad_shardings, is_leaf=lambda x: hasattr(x, "spec"))
        arrays = []
        i = 0
        for leaf_abs, spec, keys in zip(flat_abs, flat_specs,
                                        self._offload_piece_keys):
            by_key = {k: np.asarray(new_pieces[i + j], np.float32)
                      for j, k in enumerate(keys)}
            i += len(keys)
            dev_map = spec.addressable_devices_indices_map(leaf_abs.shape)
            bufs = [jax.device_put(by_key[self._piece_key(idx)], d)
                    for d, idx in dev_map.items()]
            arrays.append(jax.make_array_from_single_device_arrays(
                leaf_abs.shape, spec, bufs))
        partitioned = jax.tree_util.tree_unflatten(treedef, arrays)
        with self.mesh:
            return self._offload_gather_fn(partitioned)

    # --------------------------------------------------------------- step fns
    def _micro_loss_closure(self):
        loss_fn = (self._streamed_loss_fn() if self.param_stream_enabled
                   else self.model_spec.loss_fn)
        self._loss_impl = loss_fn  # eval shares it (streamed blocks strip
        # params["blocks"], so model_spec.loss_fn would not trace there)
        compute_dtype = self.compute_dtype
        cast = self.fp16_enabled or self.bfloat16_enabled

        def micro_loss(params, micro, rng, scale):
            p = _cast_floating(params, compute_dtype) if cast else params
            loss = loss_fn(p, micro, rng, True)
            return (loss.astype(jnp.float32) * scale), loss

        return micro_loss

    def _scaler_bookkeeping(self):
        """Shared fp16 scaler-advance + metrics builders (one source of truth
        for the in-jit update path and the host-offload path)."""
        fp16 = self.fp16_enabled
        dynamic = self.dynamic_loss_scale
        scaler_args = self._config.dynamic_loss_scale_args

        def next_scaler(scaler, overflow):
            if not fp16:
                return scaler
            return update_scale(
                scaler, overflow,
                scale_window=scaler_args["scale_window"],
                min_scale=scaler_args["min_scale"],
                delayed_shift=scaler_args["delayed_shift"],
                dynamic=dynamic)

        def make_metrics(mean_loss, grad_norm, overflow, new_scaler):
            return {
                "loss": mean_loss,
                "grad_norm": grad_norm,
                "overflow": overflow,
                "loss_scale": new_scaler.cur_scale,
                "skipped": new_scaler.skipped,
            }

        return next_scaler, make_metrics

    def _make_apply_update(self):
        """Build the shared optimizer-apply closure (overflow skip, scaler
        update, metrics) — used by both the DP and pipeline step functions."""
        fp16 = self.fp16_enabled
        tx = self.tx
        next_scaler, make_metrics = self._scaler_bookkeeping()

        def apply_update(state, grads, mean_loss):
            """grads: fp32, already averaged over the global batch & unscaled."""
            params, opt_state, scaler = (state["params"], state["opt_state"],
                                         state["scaler"])
            grad_norm = optax.global_norm(grads)
            overflow = has_overflow(grads) if fp16 else jnp.asarray(False)

            def do_update(_):
                updates, new_opt = tx.update(grads, opt_state, params)
                new_params = optax.apply_updates(params, updates)
                return new_params, new_opt

            def skip_update(_):
                return params, opt_state

            if fp16:
                new_params, new_opt = jax.lax.cond(overflow, skip_update,
                                                   do_update, None)
            else:
                new_params, new_opt = do_update(None)
            new_scaler = next_scaler(scaler, overflow)
            new_state = {
                **state,  # pass through aux entries (e.g. onebit errors)
                "step": state["step"] + 1,
                "params": new_params,
                "opt_state": new_opt,
                "scaler": new_scaler,
            }
            metrics = make_metrics(mean_loss, grad_norm, overflow, new_scaler)
            return new_state, metrics

        return apply_update

    def _metrics_shardings(self):
        rep = NamedSharding(self.mesh, P())
        return {k: rep for k in
                ("loss", "grad_norm", "overflow", "loss_scale", "skipped")}

    def _build_step_fns(self) -> None:
        # recompile sentry (analysis/sentry.py): the config pins batch
        # shapes, so the fused train step compiles exactly once (budget 1)
        # and any retrace is contract drift — visible in sentry.report() /
        # retraces_observed.  multi-step/eval legitimately specialize per
        # shape (scan length = leading batch dim): budget None, count only.
        self.sentry = RecompileSentry(name="training")
        gas = self.gradient_accumulation_steps()
        fp16 = self.fp16_enabled
        micro_loss = self._micro_loss_closure()
        grad_shardings = self.grad_shardings
        apply_update = self._make_apply_update()

        def grads_of_micro(params, micro, rng, scale):
            (scaled_loss, loss), grads = jax.value_and_grad(
                micro_loss, has_aux=True)(params, micro, rng, scale)
            del scaled_loss
            return loss, grads

        def accumulate(state, batch, base_rng):
            """Scan the GAS microbatches; returns (unscaled fp32 grads, loss)."""
            params, scaler = state["params"], state["scaler"]
            scale = scaler.cur_scale if fp16 else jnp.asarray(1.0, jnp.float32)
            step_rng = jax.random.fold_in(base_rng, state["step"])

            if gas == 1:
                # fast path: no accumulator (saves a zero-init + add pass
                # over a full fp32 grad buffer per step)
                micro = jax.tree_util.tree_map(lambda x: x[0], batch)
                loss, grads = grads_of_micro(
                    params, micro, jax.random.fold_in(step_rng, 0), scale)
                inv = 1.0 / scale
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.float32) * inv, grads)
                grads = constrain(grads, grad_shardings)
                return grads, loss.astype(jnp.float32)

            zero_grads = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)
            zero_grads = constrain(zero_grads, grad_shardings)

            def body(carry, xs):
                acc, loss_sum = carry
                micro, idx = xs
                rng = jax.random.fold_in(step_rng, idx)
                loss, grads = grads_of_micro(params, micro, rng, scale)
                acc = jax.tree_util.tree_map(
                    lambda a, g: a + g.astype(jnp.float32), acc, grads)
                acc = constrain(acc, grad_shardings)
                return (acc, loss_sum + loss.astype(jnp.float32)), None

            (grads, loss_sum), _ = jax.lax.scan(
                body, (zero_grads, jnp.zeros((), jnp.float32)),
                (batch, jnp.arange(gas)))
            inv = 1.0 / (gas * scale)
            grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
            grads = constrain(grads, grad_shardings)
            mean_loss = loss_sum / gas
            return grads, mean_loss

        def train_step(state, batch, base_rng):
            """batch: pytree with leading dims [gas, micro_global, ...]."""
            grads, mean_loss = accumulate(state, batch, base_rng)
            return apply_update(state, grads, mean_loss)

        clip = self._config.gradient_clipping
        next_scaler, make_metrics = self._scaler_bookkeeping()
        self._next_scaler = next_scaler  # host-side reuse (param streaming)

        def offload_finish(state, grads, mean_loss):
            """Clip + overflow + scaler bookkeeping for grads headed to the
            host optimizer (grads already unscaled/averaged).  Under param
            streaming, clipping moves to the host where the streamed block
            grads can contribute to the global norm."""
            grad_norm = optax.global_norm(grads)
            if clip and not self.param_stream_enabled:
                factor = jnp.minimum(1.0, clip / (grad_norm + 1e-6))
                grads = jax.tree_util.tree_map(lambda g: g * factor, grads)
            overflow = has_overflow(grads) if fp16 else jnp.asarray(False)
            new_scaler = next_scaler(state["scaler"], overflow)
            metrics = make_metrics(mean_loss, grad_norm, overflow, new_scaler)
            partial = {"step": state["step"] + 1, "scaler": new_scaler}
            return grads, partial, metrics

        def offload_grads_step(state, batch, base_rng):
            """Device half of the offload step: grads + clip + scaler
            bookkeeping in-graph; the optimizer apply happens on host."""
            grads, mean_loss = accumulate(state, batch, base_rng)
            return offload_finish(state, grads, mean_loss)

        def micro_grads(params, scaler, batch, base_rng, idx):
            """One microbatch fwd+bwd for the forward/backward shim path."""
            scale = scaler.cur_scale if fp16 else jnp.asarray(1.0, jnp.float32)
            rng = jax.random.fold_in(base_rng, idx)
            loss, grads = grads_of_micro(params, batch, rng, scale)
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32) / (gas * scale), grads)
            grads = constrain(grads, grad_shardings)
            return loss, grads

        def eval_step(params, batch, base_rng):
            p = (_cast_floating(params, self.compute_dtype)
                 if (self.fp16_enabled or self.bfloat16_enabled) else params)
            return self._loss_impl(p, batch, base_rng, False)

        rep = NamedSharding(self.mesh, P())
        metrics_shardings = self._metrics_shardings()
        def multi_step(state, batches, base_rng):
            """k train_steps under one jit; scan length = leading batch dim
            (jit specializes per shape, so one callable serves every k)."""
            def body(st, b):
                return train_step(st, b, base_rng)

            return jax.lax.scan(body, state, batches)

        self._train_multi_fn = jax.jit(
            self.sentry.wrap(multi_step, "train_multi", budget=None),
            out_shardings=(self.state_shardings, metrics_shardings),
            donate_argnums=(0,))
        self._train_step_fn = jax.jit(
            self.sentry.wrap(train_step, "train_step"),
            out_shardings=(self.state_shardings, metrics_shardings),
            donate_argnums=(0,))
        if self.onebit_comm_enabled and self._onebit_compressed:
            self._install_onebit_step(metrics_shardings)
        if self.offload_enabled:
            scaler_rep = jax.tree_util.tree_map(
                lambda _: rep, self.state_shardings["scaler"])
            offload_out = (self.grad_shardings,
                           {"step": rep, "scaler": scaler_rep},
                           metrics_shardings)
            # donate_argnums=() is deliberate: these return grads + a
            # partial {step, scaler} — state itself outlives the call (the
            # host optimizer applies the update and params are rebuilt)
            self._offload_grads_fn = jax.jit(offload_grads_step,
                                             donate_argnums=(),
                                             out_shardings=offload_out)
            self._offload_finish_fn = jax.jit(offload_finish,
                                              donate_argnums=(),
                                              out_shardings=offload_out)
        self._micro_grads_fn = jax.jit(
            micro_grads, out_shardings=(rep, self.grad_shardings),
            static_argnums=())
        self._apply_update_fn = jax.jit(
            apply_update,
            out_shardings=(self.state_shardings, metrics_shardings),
            donate_argnums=(0,))
        self._eval_step_fn = jax.jit(
            self.sentry.wrap(eval_step, "eval_step", budget=None))
        self._tree_add_fn = jax.jit(
            lambda a, b: jax.tree_util.tree_map(jnp.add, a, b),
            donate_argnums=(0,))

    def _install_onebit_step(self, metrics_shardings) -> None:
        """Replace the train step with one whose DP gradient exchange runs
        through the 1-bit compressed all-reduce (reference
        ``runtime/comm/nccl.py:52``).

        The default step computes grads under global-jit semantics, where
        XLA inserts the dense psum implicitly — there is no seam to
        compress.  This variant runs the whole fwd/bwd inside ``shard_map``
        over dp, so each device holds its LOCAL gas-accumulated gradient,
        flattens it, and exchanges PACKED sign bits (8/byte) + per-chunk
        scales (~32x wire reduction) with persistent worker/server error feedback
        carried in ``state["onebit"]``.  Installed only past freeze_step;
        warmup uses the dense path (``_advance_onebit`` retraces at the
        boundary, the same pattern as compression schedule_offsets).
        """
        from jax.experimental.shard_map import shard_map
        from jax.flatten_util import ravel_pytree

        from ..parallel.topology import DP_AXIS
        from .comm.compressed import compressed_allreduce

        gas = self.gradient_accumulation_steps()
        fp16 = self.fp16_enabled
        micro_loss = self._micro_loss_closure()
        apply_update = self._make_apply_update()
        mesh = self.mesh

        leaves, treedef = jax.tree_util.tree_flatten(self._abstract_params)
        sizes = [int(np.prod(x.shape)) for x in leaves]
        offsets = np.concatenate([[0], np.cumsum(sizes)])

        def unflatten(flat):
            parts = [flat[int(o):int(o) + s].reshape(l.shape)
                     for o, s, l in zip(offsets, sizes, leaves)]
            return jax.tree_util.tree_unflatten(treedef, parts)

        def local_grads(params, scaler, step, batch, base_rng, we, se):
            """Runs per-device inside shard_map: batch is the LOCAL
            [gas, micro_local, ...] shard; we/se lose their stacking dim."""
            we, se = we[0], se[0]
            scale = (scaler.cur_scale if fp16
                     else jnp.asarray(1.0, jnp.float32))
            step_rng = jax.random.fold_in(
                jax.random.fold_in(base_rng, step),
                jax.lax.axis_index(DP_AXIS))

            def body(carry, xs):
                acc, loss_sum = carry
                micro, idx = xs
                rng = jax.random.fold_in(step_rng, idx)
                (_, loss), grads = jax.value_and_grad(
                    micro_loss, has_aux=True)(params, micro, rng, scale)
                acc = acc + ravel_pytree(grads)[0].astype(jnp.float32)
                return (acc, loss_sum + loss.astype(jnp.float32)), None

            total = int(offsets[-1])
            (flat, loss_sum), _ = jax.lax.scan(
                body, (jnp.zeros((total,), jnp.float32),
                       jnp.zeros((), jnp.float32)),
                (batch, jnp.arange(gas)))
            flat = flat / (gas * scale)
            mean_flat, nwe, nse = compressed_allreduce(flat, we, se, DP_AXIS)
            loss = jax.lax.pmean(loss_sum / gas, DP_AXIS)
            return mean_flat, loss, nwe[None], nse[None]

        P_ = P
        sm = shard_map(
            local_grads, mesh=mesh,
            in_specs=(P_(), P_(), P_(), P_(None, DP_AXIS), P_(),
                      P_(DP_AXIS), P_(DP_AXIS)),
            out_specs=(P_(), P_(), P_(DP_AXIS), P_(DP_AXIS)),
            check_rep=False)

        def train_step(state, batch, base_rng):
            mean_flat, mean_loss, nwe, nse = sm(
                state["params"], state["scaler"], state["step"], batch,
                base_rng, state["onebit"]["we"], state["onebit"]["se"])
            grads = unflatten(mean_flat)
            new_state, metrics = apply_update(state, grads, mean_loss)
            # fp16 overflow: an inf gradient turns the compression scales
            # inf and the residuals NaN — the param update is skipped by
            # apply_update, and the error buffers must roll back with it or
            # every later step inherits the NaN
            overflow = metrics["overflow"]
            new_state = {**new_state, "onebit": jax.tree_util.tree_map(
                lambda old, new: jnp.where(overflow, old, new),
                state["onebit"], {"we": nwe, "se": nse})}
            return new_state, metrics

        def multi_step(state, batches, base_rng):
            return jax.lax.scan(
                lambda st, b: train_step(st, b, base_rng), state, batches)

        self._train_step_fn = jax.jit(
            self.sentry.wrap(train_step, "train_step_onebit"),
            out_shardings=(self.state_shardings, metrics_shardings),
            donate_argnums=(0,))
        self._train_multi_fn = jax.jit(
            self.sentry.wrap(multi_step, "train_multi_onebit", budget=None),
            out_shardings=(self.state_shardings, metrics_shardings),
            donate_argnums=(0,))

    # ---------------------------------------------------------------- batching
    def _batch_sharding(self, leading_gas_dim, x=None):
        """Batch dim over (dp, ep); if sp>1, the sequence dim over sp too
        (when it divides — SP attention reshards internally otherwise).

        ``leading_gas_dim`` counts unsharded leading dims before the batch
        dim (bool for the historical [gas, micro] case; 2 for the
        multi-step [steps, gas, micro] layout of ``train_batches``)."""
        dims = [None] * int(leading_gas_dim) + [DATA_AXES]
        if x is not None:
            seq_dim = len(dims)
            sp = self.topology.sequence_parallel_size
            x_shape = getattr(x, "shape", ())
            # multi-process: the dataloader shards only the batch dim per
            # process, so seq-dim process-sharding would mis-assemble the
            # global array; SP attention reshards in-graph instead
            if (sp > 1 and jax.process_count() == 1
                    and len(x_shape) > seq_dim
                    and x_shape[seq_dim] % sp == 0):
                dims.append(SP_AXIS)
        return NamedSharding(self.mesh, P(*dims))

    def _shard_batch(self, batch, leading_gas_dim: bool = False):
        if jax.process_count() > 1:
            # each controller holds only its slice of the global batch (see
            # DeepSpeedDataLoader process_shard); assemble the global array
            return jax.tree_util.tree_map(
                lambda x: jax.make_array_from_process_local_data(
                    self._batch_sharding(leading_gas_dim, x), np.asarray(x)),
                batch)
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(
                jnp.asarray(x), self._batch_sharding(leading_gas_dim, x)),
            batch)

    def _stack_micros(self, micros) -> PyTree:
        return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *micros)

    def _reshape_global_batch(self, batch) -> PyTree:
        gas = self.gradient_accumulation_steps()
        mb = self.micro_batch_global()

        def reshape(x):
            x = np.asarray(x)
            assert x.shape[0] == gas * mb, (
                f"train_batch expects global batch {gas * mb}, got {x.shape[0]}")
            return x.reshape((gas, mb) + x.shape[1:])

        return jax.tree_util.tree_map(reshape, batch)

    def _apply_curriculum(self, batch):
        """Truncate token batches to the curriculum difficulty (reference
        ``engine.py:1806-1812`` curriculum seqlen).  Difficulty is quantized
        by the schedule so the set of compiled shapes stays small."""
        if self.curriculum_scheduler is None or \
                self.curriculum_scheduler.curriculum_type != "seqlen":
            return batch
        diff = self.curriculum_scheduler.update_difficulty(
            self.global_steps + 1)

        def trunc(x):
            x = np.asarray(x)
            if x.ndim >= 1 and np.issubdtype(x.dtype, np.integer) and \
                    x.shape[-1] > diff + 1:
                return x[..., : diff + 1]  # +1: targets shift by one
            return x

        return jax.tree_util.tree_map(trunc, batch)

    # ------------------------------------------------------------------- train
    def _advance_random_ltd(self, batch) -> None:
        """Move the model's kept-token count to this step's schedule value;
        retrace when it changes (the count is a static shape).  The count is
        clamped to the batch sequence length so a schedule whose max_value
        exceeds the trained sequence cannot trigger rebuilds of semantically
        identical dense programs; once the clamped value can no longer
        change, the scheduler is marked saturated (train_batches then takes
        the fused multi-step dispatch again)."""
        if self._ltd_saturated:
            return
        # trained sequence length: input_ids minus the shift-by-one ONLY
        # when targets come from shifting (no explicit labels)
        if isinstance(batch, dict) and "input_ids" in batch:
            seq = batch["input_ids"].shape[-1]
            if batch.get("labels") is None:
                seq -= 1
        elif isinstance(batch, (tuple, list)) and len(batch) >= 2:
            # (input_ids, labels): explicit labels, no shift-by-one
            # (models/gpt2.py loss convention)
            seq = jax.tree_util.tree_leaves(batch[0])[0].shape[-1]
        else:
            seq = jax.tree_util.tree_leaves(batch)[0].shape[-1] - 1
        keep = self.random_ltd_scheduler.get_keep_count(
            self.global_steps, seq)
        if keep != self._ltd_keep:
            self._ltd_keep = keep
            self.model_spec.model_config.random_ltd_keep = keep
            log_dist(f"random-LTD: kept-token count -> {keep} at step "
                     f"{self.global_steps}", ranks=[0])
            self._build_step_fns()
        # latch ONLY when the schedule is fully ramped AND the model holds
        # the unclamped endpoint: a seq-clamped value must keep following
        # the batch (curriculum seqlen can grow later)
        if keep >= self.random_ltd_scheduler.max_value and \
                self.random_ltd_scheduler.get_keep_count(
                    self.global_steps, 1 << 30) >= \
                self.random_ltd_scheduler.max_value:
            self._ltd_saturated = True
            log_dist(f"random-LTD: schedule saturated at {keep} kept tokens; "
                     "no further retraces", ranks=[0])

    def train_batch(self, batch=None, data_iter=None) -> Tuple[Any, Dict]:
        """Run one full global step (all GAS microbatches + update) in one jit.

        ``batch`` leading dim may be ``train_batch_size`` (reshaped to
        [gas, micro]) or already [gas, micro_global, ...].  Alternatively pass
        ``data_iter`` yielding micro-global batches (reference
        ``PipelineEngine.train_batch`` signature).
        """
        if batch is None:
            it = data_iter or self._ensure_data_iterator()
            micros = [next(it) for _ in range(self.gradient_accumulation_steps())]
            batch = self._stack_micros(micros)
        else:
            first = jax.tree_util.tree_leaves(batch)[0]
            if first.shape[0] == self.train_batch_size() and \
                    self.gradient_accumulation_steps() * self.micro_batch_global() \
                    == self.train_batch_size():
                batch = self._reshape_global_batch(batch)
        batch = self._apply_curriculum(batch)
        batch = self._shard_batch(batch, leading_gas_dim=True)

        # compression schedule_offsets: advance the trace-time step marker
        # when a mechanism's offset is crossed and retrace (reference applies
        # each mechanism from its own schedule_offset onward)
        toggle = getattr(self.model_spec, "_compression_toggle", None)
        if toggle is not None:
            completed = self.global_steps  # steps finished before this one
            crossed = [off for off in self.model_spec._compression_offsets
                       if toggle.step < off <= completed]
            if crossed:
                toggle.step = completed
                log_dist(
                    f"compression: mechanisms with schedule_offset in "
                    f"{crossed} activate after {completed} steps", ranks=[0])
                self._build_step_fns()

        if self.random_ltd_scheduler is not None:
            self._advance_random_ltd(batch)

        # 1-bit: dense warmup until freeze_step, compressed exchange after
        # (reference keeps the variance-adaptation phase uncompressed)
        if self.onebit_comm_enabled and not self._onebit_compressed and \
                self.global_steps >= self._onebit_freeze:
            self._onebit_compressed = True
            log_dist(
                f"1-bit: freeze_step {self._onebit_freeze} reached — "
                "gradient exchange switches to the compressed all-reduce "
                "(packed sign bits + per-chunk scales, ~32x wire "
                "reduction)",
                ranks=[0])
            self._build_step_fns()

        fp = self._config.flops_profiler_config
        profiling_now = fp.enabled and \
            self.global_steps + 1 == fp.profile_step
        t0 = time.perf_counter() if profiling_now else None

        self.tput_timer.start()
        self.timers(TRAIN_BATCH_TIMER).start()
        if self.offload_enabled:
            self.state, metrics = self._train_step_offload(self.state, batch)
        else:
            self.state, metrics = self._train_step_fn(self.state, batch,
                                                      self._dropout_rng)
        self.global_steps += 1
        self.micro_steps += self.gradient_accumulation_steps()
        self.global_samples += self.train_batch_size()
        # sync only on report steps: a per-step block would serialize the
        # dispatch pipeline (expensive host round-trip on remote backends)
        sync = metrics["loss"] if (profiling_now or self.global_steps %
                                   max(self.steps_per_print(), 1) == 0) \
            else None
        self.tput_timer.stop(global_step=True, sync_arrays=sync)
        # same sync decision: un-synced steps record dispatch latency, the
        # periodic synced step bounds the true step time (timer docstring)
        self.timers(TRAIN_BATCH_TIMER).stop(sync_arrays=sync)
        self._finalize_metrics(metrics)

        if profiling_now:
            # reference hooks the profiler at profile_step
            # (``runtime/engine.py:315,1796``); cost-analyze the compiled
            # step and reuse THIS step's measured wall clock — the profiler
            # observes training, it does not run extra updates
            from ..profiling.flops_profiler import FlopsProfiler

            latency = time.perf_counter() - t0
            self.flops_profiler = FlopsProfiler(engine=self)
            self.flops_profiler.profile_engine_step(batch, latency=latency)
            self.flops_profiler.print_profile(fp.output_file)
        return self.state, self._cached_metrics

    def train_batches(self, batches) -> Tuple[Any, Dict]:
        """Run several consecutive global steps in ONE device dispatch.

        ``batches``: a list of global batches (each as accepted by
        ``train_batch``) or a pytree already stacked on a leading steps dim
        ``[k, gas, micro_global, ...]``.  Semantically identical to ``k``
        ``train_batch`` calls — the update happens every ``gas``
        microbatches, RNG folds per step — but the k steps execute as one
        ``lax.scan``, so per-step host dispatch latency (dominant on
        remote/tunneled backends; the problem the reference solves with
        CUDA-graph capture, ``inference/engine.py:479``) is paid once per k.

        Falls back to per-step ``train_batch`` when a host-side feature
        needs to observe every step (offload optimizer, compression
        schedule offsets, curriculum seqlen, flops profiling).
        """
        if isinstance(batches, (list, tuple)):
            k = len(batches)
            stacked = None
        else:
            leaves = jax.tree_util.tree_leaves(batches)
            k = leaves[0].shape[0] if leaves else 0
            stacked = batches
        if k < 1:
            raise ValueError(
                "train_batches requires at least one batch (got an empty "
                f"{'list' if stacked is None else 'stacked pytree'})")
        fp = self._config.flops_profiler_config
        host_side_feature = (
            self.offload_enabled
            or getattr(self.model_spec, "_compression_toggle", None) is not None
            or (self.random_ltd_scheduler is not None
                and not self._ltd_saturated)
            or (self.onebit_comm_enabled and not self._onebit_compressed)
            or (self.curriculum_scheduler is not None
                and self.curriculum_scheduler.curriculum_type == "seqlen")
            or (fp.enabled
                and self.global_steps < fp.profile_step <= self.global_steps + k))
        if host_side_feature or k == 1:
            if stacked is not None:
                batches = [jax.tree_util.tree_map(lambda x, i=i: x[i], stacked)
                           for i in range(k)]
            for b in batches:
                out = self.train_batch(b)
            return out

        if stacked is None:
            reshaped = []
            for b in batches:
                first = jax.tree_util.tree_leaves(b)[0]
                if first.shape[0] == self.train_batch_size() and \
                        self.gradient_accumulation_steps() * \
                        self.micro_batch_global() == self.train_batch_size():
                    b = self._reshape_global_batch(b)
                reshaped.append(b)
            stacked = jax.tree_util.tree_map(
                lambda *xs: np.stack([np.asarray(x) for x in xs]), *reshaped)
        dev = self._shard_batch(stacked, leading_gas_dim=2)

        self.tput_timer.start()
        self.state, mstack = self._train_multi_fn(
            self.state, dev, self._dropout_rng)
        self.global_steps += k
        self.micro_steps += k * self.gradient_accumulation_steps()
        self.global_samples += k * self.train_batch_size()
        metrics = jax.tree_util.tree_map(lambda a: a[-1], mstack)
        # mirror the timer's multi-step report condition (% < k, not == 0):
        # a report without a sync would print dispatch-only throughput
        sync = metrics["loss"] if (self.global_steps %
                                   max(self.steps_per_print(), 1) < k) \
            else None
        self.tput_timer.stop(global_step=True, sync_arrays=sync, steps=k)
        self._finalize_metrics(metrics, steps=k)
        return self.state, self._cached_metrics

    def _train_step_offload(self, state, batch):
        """Offload step: device grads -> host C++ optimizer -> device params.

        The transfer/step/transfer is the TPU analog of the reference's
        PCIe grad-offload + CPU-Adam + param copy-back cycle
        (``stage_1_and_2.py:1096``, ``csrc/adam/cpu_adam.cpp``).
        """
        grads, partial, metrics = self._offload_grads_fn(state, batch,
                                                         self._dropout_rng)
        return self._host_apply(state, grads, partial, metrics)

    def _host_lr(self) -> float:
        """LR for the host optimizer: indexed by *applied* steps so overflow-
        skipped steps don't advance the schedule (matches the in-graph optax
        scale_by_schedule count and the reference's scheduler semantics)."""
        if self.lr_schedule is not None:
            return float(self.lr_schedule(self._offload_opt.applied_steps))
        return self._base_lr

    def _host_apply(self, state, grads, partial, metrics):
        new_params = state["params"]
        overflow = self.fp16_enabled and bool(
            jax.device_get(metrics["overflow"]))
        if self.param_stream_enabled:
            # join in-flight grad-push io_callbacks before reading the host
            # accumulator — array readiness does not imply callback completion
            jax.effects_barrier()
            # scale/average the host-accumulated block grads exactly like the
            # in-graph path did for the resident grads
            scale = (float(jax.device_get(state["scaler"].cur_scale))
                     if self.fp16_enabled else 1.0)
            factor = 1.0 / (self.gradient_accumulation_steps() * scale)
            block_grads = self._param_store.pop_grads()
            for g in block_grads:
                g *= factor
            if jax.process_count() > 1:
                # combine per-process contributions (each process's grad-push
                # callbacks saw only its addressable devices' cotangents)
                block_grads = dist.host_all_reduce_sum(block_grads)
            if self.fp16_enabled and not overflow:
                block_overflow = not all(np.isfinite(g).all()
                                         for g in block_grads)
                if block_overflow:
                    # the in-graph scaler bookkeeping saw only resident grads
                    # and advanced as a successful step; redo it with
                    # overflow=True so the scale backs off (no livelock)
                    overflow = True
                    new_scaler = self._next_scaler(state["scaler"],
                                                   jnp.asarray(True))
                    partial = dict(partial)
                    partial["scaler"] = new_scaler
                    metrics = dict(metrics)
                    metrics["overflow"] = np.asarray(True)
                    metrics["loss_scale"] = new_scaler.cur_scale
                    metrics["skipped"] = new_scaler.skipped
        if not overflow:
            grad_pieces = [np.array(p, np.float32) for p in
                           self._offload_pieces_of(grads)]
            if self.param_stream_enabled:
                # host-side global clip across resident + streamed grads
                clip = self._config.gradient_clipping
                sq = sum(float(np.vdot(p, p)) for p in grad_pieces) + \
                    sum(float(np.vdot(g, g)) for g in block_grads)
                total_norm = float(np.sqrt(sq))
                if clip:
                    c = min(1.0, clip / (total_norm + 1e-6))
                    for p in grad_pieces:
                        p *= c
                    for g in block_grads:
                        g *= c
                self._block_opt.step(block_grads, lr=self._host_lr())
                self._param_store.refresh_compute()
            new_pieces = self._offload_opt.step(grad_pieces,
                                                lr=self._host_lr())
            new_params = self._offload_rebuild_params(new_pieces)
        new_state = {
            **state,  # pass through aux entries (e.g. onebit errors)
            "step": partial["step"],
            "params": new_params,
            "opt_state": state["opt_state"],
            "scaler": partial["scaler"],
        }
        return new_state, metrics

    def _ensure_data_iterator(self):
        if self._data_iterator is None:
            assert self.training_dataloader is not None, (
                "no training_data was passed to initialize() and no batch/"
                "data_iter given")
            self._data_iterator = iter(RepeatingLoader(self.training_dataloader))
        return self._data_iterator

    @property
    def skipped_steps(self) -> int:
        """Cumulative overflow-skipped steps; forces a metrics sync only when
        read (the scaler carries the cumulative count in-graph)."""
        m = getattr(self, "_cached_metrics", None)
        if m is not None and "skipped" in m:
            return int(m.get("skipped", self._skipped_steps_base))
        return self._skipped_steps_base

    @skipped_steps.setter
    def skipped_steps(self, v: int) -> None:
        self._skipped_steps_base = int(v)
        # drop stale metrics so a checkpoint restore's value takes effect
        # (the getter prefers the live metrics' cumulative counter)
        self._cached_metrics = {}

    def _finalize_metrics(self, metrics, steps: int = 1) -> None:
        # Lazy: metrics stay device-side until someone reads them.  A
        # device_get here would force a host round-trip EVERY step (hundreds
        # of ms on remote/tunneled backends), serializing the pipeline; the
        # log/monitor branches below force them only every steps_per_print.
        # ``steps``: report-window width for multi-step intervals (a k-step
        # train_batches can jump over the == 0 boundary).
        self._cached_metrics = _LazyMetrics(metrics)
        report = self.global_steps % max(self.steps_per_print(), 1) < steps
        if self.lr_scheduler is not None:
            self.lr_scheduler.step(self.global_steps)
        if report:
            # registry gauges are refreshed on report steps only — the same
            # cadence the metrics sync runs at, so this forces no extra
            # device round-trip — then the whole registry snapshot routes
            # through the MonitorMaster backends: loss/lr/loss-scale under
            # their historical event names (monitor_name), plus throughput
            # and the wall-clock timer histograms (telemetry/metrics.py
            # to_events)
            self._g_train_loss.set(float(self._cached_metrics["loss"]))
            self._g_train_lr.set(float(self.get_lr()[0]))
            self._g_global_steps.set(self.global_steps)
            if self._g_loss_scale is not None:
                self._g_loss_scale.set(
                    float(self._cached_metrics["loss_scale"]))
            sps = self.tput_timer.avg_samples_per_sec()
            if sps == sps:                 # NaN until start_step is passed
                self._g_samples_per_sec.set(sps)
            if self.monitor.enabled:
                self.monitor.write_registry(self.metrics,
                                            self.global_samples)
        if report:
            log_dist(
                f"step={self.global_steps} loss={self._cached_metrics['loss']:.4f} "
                f"lr={self.get_lr()[0]:.3e} "
                f"grad_norm={self._cached_metrics['grad_norm']:.3f}", ranks=[0])

    def start_metrics_server(self, port: int = 0,
                             host: str = "127.0.0.1"):
        """Serve this engine's registry live (``telemetry/server.py``):
        ``/metrics`` = Prometheus text, ``/stats`` = JSON snapshot — the
        training registry joins the same exposition layer the serving
        fleet scrapes (and federates with it:
        ``telemetry.federate({"train": engine.metrics, ...})``).
        ``port=0`` binds an ephemeral port; idempotent; the returned
        server's ``stop()`` shuts it down."""
        from ..telemetry.server import MetricsServer

        if self._metrics_server is None:
            self._metrics_server = MetricsServer(
                metrics_text=self.metrics.prometheus_text,
                stats=self.metrics.snapshot,
                host=host, port=port).start()
        return self._metrics_server

    # -------------------------------------------- reference micro-step shims
    def forward(self, batch) -> jnp.ndarray:
        """Compute the microbatch loss+grads; loss returned, grads cached for
        ``backward``. (JAX has no separate autograd pass — fwd+bwd fuse.)"""
        if self.wall_clock_breakdown_enabled:
            self.timers(FORWARD_GLOBAL_TIMER).start()
        batch = self._shard_batch(batch, leading_gas_dim=False)
        loss, grads = self._micro_grads_fn(
            self.state["params"], self.state["scaler"], batch,
            self._dropout_rng,
            jnp.asarray(self.micro_steps, jnp.int32))
        self._pending = (loss, grads)
        if self.wall_clock_breakdown_enabled:
            self.timers(FORWARD_GLOBAL_TIMER).stop(sync_arrays=loss)
        return loss

    __call__ = forward

    def backward(self, loss=None, allreduce_gradients: bool = True):
        """Accumulate the cached microbatch grads (already averaged by 1/GAS)."""
        if self.wall_clock_breakdown_enabled:
            self.timers(BACKWARD_GLOBAL_TIMER).start()
        assert getattr(self, "_pending", None) is not None, \
            "backward() called without a preceding forward()"
        loss_val, grads = self._pending
        self._pending = None
        if self._accum_grads is None:
            self._accum_grads = grads
            self._accum_losses = [loss_val]
        else:
            self._accum_grads = self._tree_add_fn(self._accum_grads, grads)
            self._accum_losses.append(loss_val)
        self.micro_steps += 1
        if self.wall_clock_breakdown_enabled:
            self.timers(BACKWARD_GLOBAL_TIMER).stop(sync_arrays=loss_val)
        return loss_val

    def is_gradient_accumulation_boundary(self) -> bool:
        return self.micro_steps % self.gradient_accumulation_steps() == 0

    def step(self):
        """Apply the accumulated update at a GAS boundary (reference :2126)."""
        if not self.is_gradient_accumulation_boundary():
            return
        if self.wall_clock_breakdown_enabled:
            self.timers(STEP_GLOBAL_TIMER).start()
        assert self._accum_grads is not None, "step() without accumulated grads"
        mean_loss = (jnp.stack([jnp.asarray(l, jnp.float32)
                                for l in self._accum_losses]).mean()
                     if self._accum_losses else jnp.asarray(0.0, jnp.float32))
        if self.offload_enabled:
            grads, partial, metrics = self._offload_finish_fn(
                self.state, self._accum_grads, mean_loss)
            self.state, metrics = self._host_apply(self.state, grads, partial,
                                                   metrics)
        else:
            self.state, metrics = self._apply_update_fn(
                self.state, self._accum_grads, mean_loss)
        self._accum_grads = None
        self._accum_losses = []
        self.global_steps += 1
        self.global_samples += self.train_batch_size()
        self._finalize_metrics(metrics)
        if self.wall_clock_breakdown_enabled:
            self.timers(STEP_GLOBAL_TIMER).stop(
                sync_arrays=self.state["scaler"].cur_scale)

    # -------------------------------------------------------------------- eval
    def eval_batch(self, batch, rng=None):
        batch = self._shard_batch(batch, leading_gas_dim=False)
        rng = rng if rng is not None else self._dropout_rng
        return self._eval_step_fn(self.state["params"], batch, rng)

    # -------------------------------------------------------------------- data
    def deepspeed_io(self, dataset, batch_size: Optional[int] = None,
                     route=None, pin_memory: bool = True, data_sampler=None,
                     collate_fn=None, num_local_io_workers=None
                     ) -> DeepSpeedDataLoader:
        """Reference ``engine.py:318 deepspeed_io``: build the framework loader."""
        return DeepSpeedDataLoader(
            dataset,
            batch_size=batch_size or self.micro_batch_global(),
            collate_fn=collate_fn or self.collate_fn,
            seed=self._config.seed or 0,
            drop_last=self._config.dataloader_drop_last,
            data_sampler=data_sampler,
            process_rank=dist.get_process_rank(),
            process_count=dist.get_process_world_size())

    # ------------------------------------------------------------- checkpoints
    def save_checkpoint(self, save_dir=None, tag=None, client_state=None,
                        save_latest=True):
        return self.checkpoint_manager.save(save_dir, tag=tag,
                                            client_state=client_state or {},
                                            save_latest=save_latest)

    def load_checkpoint(self, load_dir=None, tag=None, load_module_strict=True,
                        load_optimizer_states=True, load_lr_scheduler_states=True,
                        load_module_only=False):
        return self.checkpoint_manager.load(
            load_dir, tag=tag, load_optimizer_states=load_optimizer_states,
            load_module_only=load_module_only)

    # -------------------------------------------------------------------- misc
    @property
    def params(self):
        return self.state["params"]

    def get_fp32_params(self):
        return self.state["params"]

    def module_state_dict(self):
        return self.state["params"]

    def train(self, mode: bool = True):
        self._train_mode = mode
        return self

    def eval(self):
        return self.train(False)
