"""Eigenvalue estimation (reference ``runtime/eigenvalue.py:9 Eigenvalue``):
power iteration over layerwise curvature, used by MoQ to pace each layer's
quantization schedule (layers with larger leading eigenvalues are more
sensitive and quantize more slowly)."""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


def power_iteration(matvec: Callable, v0, iters: int = 20,
                    tol: float = 1e-4):
    """Largest-magnitude eigenvalue of the implicit symmetric operator
    ``matvec`` (reference uses the same stop criterion on successive
    estimates)."""
    v = v0 / (jnp.linalg.norm(v0) + 1e-12)
    lam = jnp.zeros(())

    def body(carry, _):
        v, lam = carry
        w = matvec(v)
        lam_new = jnp.vdot(v, w).real
        v_new = w / (jnp.linalg.norm(w) + 1e-12)
        return (v_new, lam_new), lam_new

    (v, lam), _ = jax.lax.scan(body, (v, lam), None, length=iters)
    return lam, v


def hessian_eigenvalue(loss_fn: Callable, params, *args, key=None,
                       iters: int = 20):
    """Leading eigenvalue of the loss Hessian wrt ``params`` via
    hvp = grad-of-grad (the reference's double-backprop, ``eigenvalue.py``
    ``compute_eigenvalue``)."""
    flat, treedef = jax.tree_util.tree_flatten(params)
    sizes = [int(np.prod(x.shape)) for x in flat]

    def unflatten(v):
        out, i = [], 0
        for x, n in zip(flat, sizes):
            out.append(v[i:i + n].reshape(x.shape).astype(x.dtype))
            i += n
        return jax.tree_util.tree_unflatten(treedef, out)

    def flatten(tree):
        return jnp.concatenate([jnp.ravel(x).astype(jnp.float32)
                                for x in jax.tree_util.tree_leaves(tree)])

    g_fn = jax.grad(lambda p: loss_fn(p, *args))

    def hvp(v):
        _, tangent = jax.jvp(g_fn, (params,), (unflatten(v),))
        return flatten(tangent)

    n = sum(sizes)
    key = key if key is not None else jax.random.PRNGKey(0)
    v0 = jax.random.normal(key, (n,), jnp.float32)
    lam, _ = power_iteration(hvp, v0, iters)
    return lam


class Eigenvalue:
    """Per-layer eigenvalue table with the reference's normalization
    (``eigenvalue.py``: ratios against the max, floored)."""

    def __init__(self, verbose: bool = False, max_iter: int = 20,
                 tol: float = 1e-2, stability: float = 1e-6,
                 gas_boundary_resolution: int = 1):
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.verbose = verbose

    def compute_eigenvalue(self, loss_fn, layer_params: Dict[str, any],
                           *args) -> Dict[str, float]:
        out = {}
        for name, p in layer_params.items():
            lam = hessian_eigenvalue(loss_fn, p, *args, iters=self.max_iter)
            out[name] = float(jnp.abs(lam)) + self.stability  # graft: noqa(GL012) one scalar per LAYER (not per step); the normalize below needs every λ on host anyway
        mx = max(out.values()) if out else 1.0
        return {k: v / mx for k, v in out.items()}
