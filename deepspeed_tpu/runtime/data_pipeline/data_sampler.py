"""Curriculum data sampling (reference
``data_pipeline/data_sampling/data_sampler.py:33 DeepSpeedDataSampler`` +
``data_analyzer.py DataAnalyzer``): rank-sharded sample selection driven by
per-sample difficulty metrics — at each step the sampler draws only samples
whose difficulty is within the curriculum's current value.

The analyzer computes per-sample metrics (e.g. sequence length) over an
indexable dataset and persists them; the sampler filters+shuffles
deterministically per epoch and shards by data-parallel rank.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from .curriculum_scheduler import CurriculumScheduler


class DataAnalyzer:
    """Compute + persist per-sample difficulty metrics
    (reference ``data_analyzer.py``; the 'seqlen' metric is the one the
    curriculum uses by default)."""

    def __init__(self, dataset: Sequence,
                 metric_fns: Optional[Dict[str, Callable[[Any], float]]] = None):
        self.dataset = dataset
        self.metric_fns = metric_fns or {"seqlen": _seqlen_metric}

    def run(self) -> Dict[str, np.ndarray]:
        out = {name: np.empty(len(self.dataset), np.float64)
               for name in self.metric_fns}
        for i in range(len(self.dataset)):
            sample = self.dataset[i]
            for name, fn in self.metric_fns.items():
                out[name][i] = fn(sample)
        return out

    def save(self, path: str) -> Dict[str, np.ndarray]:
        metrics = self.run()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        np.savez(path, **metrics)
        return metrics

    @staticmethod
    def load(path: str) -> Dict[str, np.ndarray]:
        with np.load(path) as z:
            return {k: z[k] for k in z.files}


def _seqlen_metric(sample) -> float:
    if isinstance(sample, dict):
        ids = sample.get("input_ids", next(iter(sample.values())))
    else:
        ids = sample
    return float(np.asarray(ids).shape[-1])


class DeepSpeedDataSampler:
    """Difficulty-gated, epoch-shuffled, rank-sharded index sampler.

    Per step: eligible = samples with metric <= current curriculum
    difficulty; indices are drawn in a deterministic per-epoch shuffle and
    split contiguously across dp ranks (reference
    ``data_sampler.py:33,get_next_global_batch``)."""

    def __init__(self, metric_values: np.ndarray,
                 curriculum: Optional[CurriculumScheduler],
                 global_batch_size: int,
                 process_rank: int = 0, process_count: int = 1,
                 seed: int = 0):
        assert global_batch_size % process_count == 0
        self.metric = np.asarray(metric_values)
        self.curriculum = curriculum
        self.global_batch = global_batch_size
        self.rank = process_rank
        self.world = process_count
        self.seed = seed
        self.global_step = 0
        self._epoch = 0
        self._order: Optional[np.ndarray] = None
        self._cursor = 0

    def _reshuffle(self) -> None:
        rng = np.random.default_rng(self.seed + self._epoch)
        self._order = rng.permutation(len(self.metric))
        self._cursor = 0

    def set_custom_curriculum(self, scheduler: CurriculumScheduler) -> None:
        self.curriculum = scheduler

    def next_batch_indices(self) -> np.ndarray:
        """Global-batch sample indices for this rank at the current step."""
        if self._order is None:
            self._reshuffle()
        if self.curriculum is not None:
            difficulty = self.curriculum.update_difficulty(self.global_step)
            eligible_mask = self.metric <= difficulty
        else:
            eligible_mask = np.ones(len(self.metric), bool)
        n_eligible = int(eligible_mask.sum())
        if n_eligible < self.global_batch:
            # wrapping the epoch would silently fill the batch with
            # duplicates (and give ranks overlapping shares)
            raise RuntimeError(
                f"curriculum difficulty "
                f"{self.curriculum.current_difficulty if self.curriculum else None} "
                f"admits fewer samples than one global batch "
                f"({n_eligible} eligible / {self.global_batch} needed)")

        # batches never repeat a sample: on an epoch-boundary wrap, indices
        # already picked from the old permutation's tail are skipped in the
        # fresh one (n_eligible >= global_batch guarantees termination
        # within the fresh epoch)
        picked: List[int] = []
        picked_set: set = set()
        while len(picked) < self.global_batch:
            if self._cursor >= len(self._order):
                self._epoch += 1
                self._reshuffle()
            idx = int(self._order[self._cursor])
            self._cursor += 1
            if eligible_mask[idx] and idx not in picked_set:
                picked.append(idx)
                picked_set.add(idx)
        self.global_step += 1
        per_rank = self.global_batch // self.world
        mine = picked[self.rank * per_rank:(self.rank + 1) * per_rank]
        return np.asarray(mine)

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            yield self.next_batch_indices()

    def state_dict(self) -> dict:
        return {"global_step": self.global_step, "epoch": self._epoch,
                "cursor": self._cursor}

    def load_state_dict(self, sd: dict) -> None:
        self.global_step = int(sd["global_step"])
        self._epoch = int(sd["epoch"])
        self._reshuffle()
        self._cursor = int(sd["cursor"])
