"""Curriculum data sampling (reference
``data_pipeline/data_sampling/data_sampler.py:33 DeepSpeedDataSampler`` +
``data_analyzer.py DataAnalyzer``): rank-sharded sample selection driven by
per-sample difficulty metrics — at each step the sampler draws only samples
whose difficulty is within the curriculum's current value.

The analyzer computes per-sample metrics (e.g. sequence length) over an
indexable dataset and persists them; the sampler filters+shuffles
deterministically per epoch and shards by data-parallel rank.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from .curriculum_scheduler import CurriculumScheduler


class DataAnalyzer:
    """Map-reduce per-sample difficulty metrics (reference
    ``data_analyzer.py:18``): the MAP phase shards the dataset across
    workers, each computing its metrics and persisting a per-worker file;
    the REDUCE phase merges them into ``sample_to_metric`` (per-sample
    values), ``metric_to_sample`` (value -> sorted sample indices — what
    the curriculum consumes), and value percentiles.

    Two metric kinds, as in the reference:
     - ``single_value_per_sample`` — ``fn(sample) -> float`` (e.g. seqlen);
     - ``accumulate_value_over_samples`` — ``fn(sample) -> np.ndarray``
       accumulated elementwise over the WHOLE dataset first (e.g. token
       counts), then ``finalize(accumulated, sample) -> float`` maps each
       sample against the global statistic (e.g. vocab rarity: the
       reference's ``total_vocab_freq`` curriculum metric).
    """

    def __init__(self, dataset: Sequence,
                 metric_fns: Optional[Dict[str, Callable[[Any], float]]] = None,
                 accumulate_fns: Optional[Dict[str, tuple]] = None):
        self.dataset = dataset
        self.metric_fns = metric_fns if metric_fns is not None else \
            ({"seqlen": _seqlen_metric} if accumulate_fns is None else {})
        #: name -> (accumulate_fn, finalize_fn)
        self.accumulate_fns = accumulate_fns or {}

    # ---------------------------------------------------------------- map
    def _worker_range(self, worker_id: int, num_workers: int) -> range:
        n = len(self.dataset)
        per = -(-n // num_workers)
        return range(worker_id * per, min((worker_id + 1) * per, n))

    def run_map(self, worker_id: int = 0, num_workers: int = 1,
                save_dir: Optional[str] = None) -> Dict[str, Any]:
        """One worker's shard of the metric computation (reference
        ``run_map_helper``).  Returns (and optionally persists) the
        worker's partial results."""
        idx = self._worker_range(worker_id, num_workers)
        part: Dict[str, Any] = {
            "_range": np.asarray([idx.start, idx.stop], np.int64)}
        for name in self.metric_fns:
            part[name] = np.empty(len(idx), np.float64)
        acc: Dict[str, Any] = {name: None for name in self.accumulate_fns}
        for j, i in enumerate(idx):
            sample = self.dataset[i]
            for name, fn in self.metric_fns.items():
                part[name][j] = fn(sample)
            for name, (accf, _) in self.accumulate_fns.items():
                v = np.asarray(accf(sample), np.float64)
                acc[name] = v if acc[name] is None else acc[name] + v
        for name, v in acc.items():
            part["_acc_" + name] = v if v is not None else np.zeros(0)
        if save_dir is not None:
            os.makedirs(save_dir, exist_ok=True)
            np.savez(os.path.join(save_dir, f"map_{worker_id:05d}.npz"),
                     **part)
        return part

    def run_finalize_map(self, totals: Dict[str, np.ndarray],
                         worker_id: int = 0, num_workers: int = 1,
                         save_dir: Optional[str] = None) -> Dict[str, Any]:
        """Second SHARDED map for accumulate metrics (reference runs
        finalize as another distributed map pass): given the reduced global
        statistics, each worker scores its own dataset shard — the reducer
        never touches the dataset.  ``totals`` comes from
        :meth:`reduce_totals`."""
        idx = self._worker_range(worker_id, num_workers)
        part: Dict[str, Any] = {
            "_range": np.asarray([idx.start, idx.stop], np.int64)}
        for name, (_, finalize) in self.accumulate_fns.items():
            vals = np.empty(len(idx), np.float64)
            for j, i in enumerate(idx):
                vals[j] = finalize(totals[name], self.dataset[i])
            part[name] = vals
        if save_dir is not None:
            os.makedirs(save_dir, exist_ok=True)
            np.savez(os.path.join(save_dir, f"fin_{worker_id:05d}.npz"),
                     **part)
        return part

    def reduce_totals(self, parts: List[Dict[str, Any]]
                      ) -> Dict[str, np.ndarray]:
        """Merge the map phase's accumulated statistics (cheap: O(workers))."""
        totals: Dict[str, np.ndarray] = {}
        for name in self.accumulate_fns:
            total = None
            for p in parts:
                v = p["_acc_" + name]
                if v.size:
                    total = v if total is None else total + v
            totals[name] = total
        return totals

    # ------------------------------------------------------------- reduce
    @staticmethod
    def _load_parts(save_dir: str, prefix: str) -> List[Dict[str, Any]]:
        files = sorted(f for f in os.listdir(save_dir)
                       if f.startswith(prefix) and f.endswith(".npz"))
        parts = []
        for f in files:
            with np.load(os.path.join(save_dir, f)) as z:
                parts.append({k: z[k] for k in z.files})
        return parts

    def run_reduce(self, parts: Optional[List[Dict[str, Any]]] = None,
                   save_dir: Optional[str] = None,
                   finalize_parts: Optional[List[Dict[str, Any]]] = None,
                   n_buckets: int = 100) -> Dict[str, Any]:
        """Merge worker partials (reference ``run_reduce``/``merge_*``):
        per-metric ``sample_to_metric``, value-sorted ``metric_to_sample``
        index, and percentile table.

        For accumulate metrics, pass ``finalize_parts`` from a second
        sharded :meth:`run_finalize_map` pass (or leave ``fin_*`` files in
        ``save_dir``); the reduce is then O(workers).  With neither, the
        reducer finalizes serially — fine for small datasets only."""
        if parts is None:
            assert save_dir is not None, "need parts or a save_dir to load"
            parts = self._load_parts(save_dir, "map_")
        if finalize_parts is None and save_dir is not None:
            finalize_parts = self._load_parts(save_dir, "fin_") or None

        def check_tiling(ps, what):
            ps = sorted(ps, key=lambda p: int(p["_range"][0]))
            cursor = 0
            for p in ps:
                lo, hi = (int(x) for x in p["_range"])
                if lo != cursor:
                    raise ValueError(
                        f"{what} shards do not tile the dataset: expected "
                        f"range starting at {cursor}, got [{lo}, {hi}) — "
                        "missing/stale worker files in save_dir?")
                cursor = hi
            return ps, cursor

        parts, n = check_tiling(parts, "map")
        out: Dict[str, Any] = {}
        for name in self.metric_fns:
            s2m = np.empty(n, np.float64)
            for p in parts:
                lo, hi = (int(x) for x in p["_range"])
                s2m[lo:hi] = p[name]
            out[name] = s2m
        # accumulate metrics: merge the second (sharded) finalize pass, or
        # fall back to a serial pass on the reducer
        if self.accumulate_fns and finalize_parts is not None:
            fin, n_fin = check_tiling(finalize_parts, "finalize")
            if n_fin != n:
                raise ValueError(
                    f"finalize shards cover {n_fin} samples, map covers {n}")
            for name in self.accumulate_fns:
                s2m = np.empty(n, np.float64)
                for p in fin:
                    lo, hi = (int(x) for x in p["_range"])
                    s2m[lo:hi] = p[name]
                out[name] = s2m
        elif self.accumulate_fns:
            totals = self.reduce_totals(parts)
            for name, (_, finalize) in self.accumulate_fns.items():
                if totals[name] is None:
                    # every worker shard produced an empty accumulator —
                    # surface that instead of a TypeError from finalize()
                    raise ValueError(
                        f"accumulate metric '{name}' has no accumulated "
                        "totals (all map shards were empty); cannot "
                        "finalize — check the dataset/worker ranges")
                s2m = np.empty(n, np.float64)
                for i in range(n):
                    s2m[i] = finalize(totals[name], self.dataset[i])
                out[name] = s2m
        result: Dict[str, Any] = {}
        for name, s2m in out.items():
            order = np.argsort(s2m, kind="stable")
            qs = np.linspace(0.0, 1.0, n_buckets + 1)
            result[name] = {
                "sample_to_metric": s2m,
                "metric_to_sample": order,         # ascending difficulty
                "percentiles": np.quantile(s2m, qs),
            }
        if save_dir is not None:
            os.makedirs(save_dir, exist_ok=True)
            flat = {}
            for name, d in result.items():
                for k, v in d.items():
                    flat[f"{name}.{k}"] = v
            np.savez(os.path.join(save_dir, "reduce.npz"), **flat)
        return result

    def get_metric_value_percentiles(self, name: str, result=None,
                                     save_dir=None) -> np.ndarray:
        """Reference ``get_metric_value_percentiles``: the value at each
        percentile bucket, for mapping curriculum difficulty (a percentile)
        to a metric threshold."""
        if result is None:
            result = self.load_reduced(save_dir)
        return result[name]["percentiles"]

    @staticmethod
    def load_reduced(save_dir: str) -> Dict[str, Dict[str, np.ndarray]]:
        with np.load(os.path.join(save_dir, "reduce.npz")) as z:
            out: Dict[str, Dict[str, np.ndarray]] = {}
            for k in z.files:
                name, key = k.rsplit(".", 1)
                out.setdefault(name, {})[key] = z[k]
        return out

    # ------------------------------------------- single-call conveniences
    def run(self) -> Dict[str, np.ndarray]:
        red = self.run_reduce([self.run_map()])
        return {k: v["sample_to_metric"] for k, v in red.items()}

    def save(self, path: str) -> Dict[str, np.ndarray]:
        metrics = self.run()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        np.savez(path, **metrics)
        return metrics

    @staticmethod
    def load(path: str) -> Dict[str, np.ndarray]:
        with np.load(path) as z:
            return {k: z[k] for k in z.files}


def vocab_rarity_metric(vocab_size: int):
    """The reference curriculum's ``total_vocab_freq``-style metric as an
    (accumulate, finalize) pair: global token counts, then per-sample mean
    negative log frequency (rarer tokens = harder samples)."""
    def accumulate(sample):
        ids = sample["input_ids"] if isinstance(sample, dict) else sample
        return np.bincount(np.asarray(ids).reshape(-1),
                           minlength=vocab_size).astype(np.float64)

    # the -log(freq) table is invariant per totals: build it once, not per
    # sample.  The cache HOLDS the totals array and compares identity with
    # ``is`` — an id()-keyed cache could alias a freed array's reused
    # address and silently serve a stale table.
    cache = {"totals": None, "table": None}

    def finalize(total_counts, sample):
        if cache["totals"] is not total_counts:
            freq = total_counts / max(total_counts.sum(), 1.0)
            cache["totals"] = total_counts
            cache["table"] = -np.log(np.maximum(freq, 1e-12))
        ids = np.asarray(sample["input_ids"] if isinstance(sample, dict)
                         else sample).reshape(-1)
        return float(cache["table"][ids].mean())

    return accumulate, finalize


def _seqlen_metric(sample) -> float:
    if isinstance(sample, dict):
        ids = sample.get("input_ids", next(iter(sample.values())))
    else:
        ids = sample
    return float(np.asarray(ids).shape[-1])


class DeepSpeedDataSampler:
    """Difficulty-gated, epoch-shuffled, rank-sharded index sampler.

    Per step: eligible = samples with metric <= current curriculum
    difficulty; indices are drawn in a deterministic per-epoch shuffle and
    split contiguously across dp ranks (reference
    ``data_sampler.py:33,get_next_global_batch``)."""

    def __init__(self, metric_values: np.ndarray,
                 curriculum: Optional[CurriculumScheduler],
                 global_batch_size: int,
                 process_rank: int = 0, process_count: int = 1,
                 seed: int = 0):
        assert global_batch_size % process_count == 0
        self.metric = np.asarray(metric_values)
        self.curriculum = curriculum
        self.global_batch = global_batch_size
        self.rank = process_rank
        self.world = process_count
        self.seed = seed
        self.global_step = 0
        self._epoch = 0
        self._order: Optional[np.ndarray] = None
        self._cursor = 0

    def _reshuffle(self) -> None:
        rng = np.random.default_rng(self.seed + self._epoch)
        self._order = rng.permutation(len(self.metric))
        self._cursor = 0

    def set_custom_curriculum(self, scheduler: CurriculumScheduler) -> None:
        self.curriculum = scheduler

    def next_batch_indices(self) -> np.ndarray:
        """Global-batch sample indices for this rank at the current step."""
        if self._order is None:
            self._reshuffle()
        if self.curriculum is not None:
            difficulty = self.curriculum.update_difficulty(self.global_step)
            eligible_mask = self.metric <= difficulty
        else:
            eligible_mask = np.ones(len(self.metric), bool)
        n_eligible = int(eligible_mask.sum())
        if n_eligible < self.global_batch:
            # wrapping the epoch would silently fill the batch with
            # duplicates (and give ranks overlapping shares)
            raise RuntimeError(
                f"curriculum difficulty "
                f"{self.curriculum.current_difficulty if self.curriculum else None} "
                f"admits fewer samples than one global batch "
                f"({n_eligible} eligible / {self.global_batch} needed)")

        # batches never repeat a sample: on an epoch-boundary wrap, indices
        # already picked from the old permutation's tail are skipped in the
        # fresh one (n_eligible >= global_batch guarantees termination
        # within the fresh epoch)
        picked: List[int] = []
        picked_set: set = set()
        while len(picked) < self.global_batch:
            if self._cursor >= len(self._order):
                self._epoch += 1
                self._reshuffle()
            idx = int(self._order[self._cursor])
            self._cursor += 1
            if eligible_mask[idx] and idx not in picked_set:
                picked.append(idx)
                picked_set.add(idx)
        self.global_step += 1
        per_rank = self.global_batch // self.world
        mine = picked[self.rank * per_rank:(self.rank + 1) * per_rank]
        return np.asarray(mine)

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            yield self.next_batch_indices()

    def state_dict(self) -> dict:
        return {"global_step": self.global_step, "epoch": self._epoch,
                "cursor": self._cursor}

    def load_state_dict(self, sd: dict) -> None:
        self.global_step = int(sd["global_step"])
        self._epoch = int(sd["epoch"])
        self._reshuffle()
        self._cursor = int(sd["cursor"])
