"""Memory-mapped indexed dataset (reference
``data_pipeline/data_sampling/indexed_dataset.py`` ``MMapIndexedDataset``,
itself the Megatron format): a ``.bin`` of concatenated token arrays plus a
``.idx`` with dtype code, sizes, and byte offsets; reads are zero-copy mmap
views.

Format (little-endian):
  .idx: magic b"DSTPUIDX" | version u64 | dtype_code u8 | count u64 |
        sizes u32[count] | pointers u64[count]
  .bin: raw element data, row-major, concatenated
"""

from __future__ import annotations

import os
import struct
from typing import List, Sequence

import numpy as np

_MAGIC = b"DSTPUIDX"
_VERSION = 1

_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32, 5: np.int64,
           6: np.float32, 7: np.float64, 8: np.uint16, 9: np.uint32}
_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    """Streaming writer: ``add_item`` rows, then ``finalize``."""

    def __init__(self, prefix: str, dtype=np.int32):
        self._prefix = prefix
        self._dtype = np.dtype(dtype)
        assert self._dtype in _CODES, f"unsupported dtype {dtype}"
        self._bin = open(data_file_path(prefix), "wb")
        self._sizes: List[int] = []
        self._pointers: List[int] = []
        self._offset = 0

    def add_item(self, array: Sequence) -> None:
        arr = np.ascontiguousarray(array, dtype=self._dtype)
        self._bin.write(arr.tobytes())
        self._pointers.append(self._offset)
        self._sizes.append(arr.size)
        self._offset += arr.nbytes

    def finalize(self) -> None:
        self._bin.close()
        with open(index_file_path(self._prefix), "wb") as idx:
            idx.write(_MAGIC)
            idx.write(struct.pack("<Q", _VERSION))
            idx.write(struct.pack("<B", _CODES[self._dtype]))
            idx.write(struct.pack("<Q", len(self._sizes)))
            idx.write(np.asarray(self._sizes, np.uint32).tobytes())
            idx.write(np.asarray(self._pointers, np.uint64).tobytes())


class MMapIndexedDataset:
    """Zero-copy reader; ``ds[i]`` returns a read-only numpy view."""

    def __init__(self, prefix: str):
        with open(index_file_path(prefix), "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError(
                    f"{index_file_path(prefix)}: bad magic {magic!r}")
            (version,) = struct.unpack("<Q", f.read(8))
            if version != _VERSION:
                raise ValueError(f"unsupported index version {version}")
            (code,) = struct.unpack("<B", f.read(1))
            self._dtype = np.dtype(_DTYPES[code])
            (count,) = struct.unpack("<Q", f.read(8))
            self._sizes = np.frombuffer(f.read(4 * count), np.uint32)
            self._pointers = np.frombuffer(f.read(8 * count), np.uint64)
        self._data = np.memmap(data_file_path(prefix), mode="r", dtype=np.uint8)

    def __len__(self) -> int:
        return len(self._sizes)

    @property
    def sizes(self) -> np.ndarray:
        return self._sizes

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        size = int(self._sizes[i])
        off = int(self._pointers[i])
        return np.frombuffer(self._data, self._dtype, count=size, offset=off)

    def get(self, i, offset: int = 0, length: int = None):
        row = self[i]
        return row[offset: None if length is None else offset + length]

    @staticmethod
    def exists(prefix: str) -> bool:
        return (os.path.exists(index_file_path(prefix)) and
                os.path.exists(data_file_path(prefix)))
