"""Curriculum learning scheduler.

Reference ``runtime/data_pipeline/curriculum_scheduler.py:9
CurriculumScheduler``: maps the global step to a "difficulty" (for
``curriculum_type: seqlen``, the sequence length trained on), under one of
four schedules.  The math is framework-neutral; the engine consumes the
difficulty by truncating batches (reference ``runtime/engine.py:1806-1812``).

Schedules (same config keys as the reference):
 - ``fixed_linear``:   difficulty ramps linearly from ``min_difficulty`` to
   ``max_difficulty`` over ``total_curriculum_step`` steps, quantized to
   ``difficulty_step`` (quantization keeps the set of jit shapes small);
 - ``fixed_root``:     same but on a root curve (``root_degree``);
 - ``fixed_discrete``: explicit ``difficulty`` list with ``max_step``
   boundaries;
 - ``custom``:         user callable ``fn(step) -> difficulty``.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"


class CurriculumScheduler:

    def __init__(self, config: Dict[str, Any]):
        self.curriculum_type = config.get("curriculum_type", "seqlen")
        self.min_difficulty = int(config.get("min_difficulty", 8))
        self.max_difficulty = int(config.get("max_difficulty", 1024))
        self.schedule_type = config.get("schedule_type", FIXED_LINEAR)
        self.config = config.get("schedule_config", {})
        self.current_difficulty = self.min_difficulty
        self.custom_fn: Optional[Callable[[int], int]] = config.get(
            "schedule_fn")
        if self.schedule_type == FIXED_DISCRETE:
            diffs = self.config.get("difficulty", [])
            steps = self.config.get("max_step", [])
            assert len(diffs) >= 1 and len(steps) == len(diffs) - 1, (
                "fixed_discrete needs N difficulties and N-1 max_step "
                "boundaries")
        elif self.schedule_type in (FIXED_LINEAR, FIXED_ROOT):
            assert self.config.get("total_curriculum_step", 0) > 0, (
                f"{self.schedule_type} needs schedule_config."
                "total_curriculum_step")
        elif self.schedule_type == CUSTOM:
            assert callable(self.custom_fn), "custom schedule needs schedule_fn"
        else:
            raise ValueError(f"unknown schedule_type {self.schedule_type!r}")

    def _quantize(self, d: float) -> int:
        step = int(self.config.get("difficulty_step", 8))
        d = int(d // step * step)
        return max(self.min_difficulty, min(d, self.max_difficulty))

    def get_difficulty(self, global_step: int) -> int:
        t = self.config.get("total_curriculum_step", 1)
        if self.schedule_type == FIXED_LINEAR:
            frac = min(global_step / t, 1.0)
        elif self.schedule_type == FIXED_ROOT:
            deg = float(self.config.get("root_degree", 2))
            frac = min(global_step / t, 1.0) ** (1.0 / deg)
        elif self.schedule_type == FIXED_DISCRETE:
            diffs = self.config["difficulty"]
            bounds = self.config["max_step"]
            for d, b in zip(diffs, bounds):
                if global_step < b:
                    return int(d)
            return int(diffs[-1])
        else:  # custom
            return int(self.custom_fn(global_step))
        raw = self.min_difficulty + frac * (self.max_difficulty -
                                            self.min_difficulty)
        return self._quantize(raw)

    def update_difficulty(self, global_step: int) -> int:
        self.current_difficulty = self.get_difficulty(global_step)
        return self.current_difficulty

    def is_fully_ramped(self, global_step: int) -> bool:
        return self.get_difficulty(global_step) >= self.max_difficulty

    def state_dict(self) -> Dict[str, Any]:
        return {"current_difficulty": self.current_difficulty}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.current_difficulty = int(sd["current_difficulty"])
