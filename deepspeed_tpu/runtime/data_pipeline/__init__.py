"""Data-efficiency pipeline (reference ``runtime/data_pipeline/*``):
curriculum learning, memory-mapped indexed datasets, random layerwise token
dropping (random-LTD)."""

from .curriculum_scheduler import CurriculumScheduler
from .data_sampler import DataAnalyzer, DeepSpeedDataSampler
from .indexed_dataset import MMapIndexedDataset, MMapIndexedDatasetBuilder
from .random_ltd import RandomLTDScheduler, token_drop, token_restore

__all__ = ["CurriculumScheduler", "DataAnalyzer", "DeepSpeedDataSampler",
           "MMapIndexedDataset", "MMapIndexedDatasetBuilder",
           "RandomLTDScheduler", "token_drop", "token_restore"]
