"""Random layerwise token dropping (random-LTD).

Reference: ``data_pipeline/data_routing/basic_layer.py:13
RandomLayerTokenDrop`` + the gather/scatter CUDA kernels
(``csrc/random_ltd/*``): middle transformer layers process a random subset
of tokens; dropped tokens skip the layer and are re-inserted afterwards, on
a schedule that grows the kept-token count until all layers see the full
sequence.

TPU realisation: ``token_drop`` draws a sorted random keep-index set (sorted
so causal attention within the kept subset remains causal in the original
order) and gathers with ``jnp.take_along_axis``; ``token_restore`` scatters
the processed subset back over the layer input (dropped positions ride the
residual stream unchanged — exactly the reference semantics).  The kept
count is a *static* per-compile constant; the scheduler quantizes it so
retraces are few.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from .curriculum_scheduler import CurriculumScheduler


def token_drop(x, rng, keep: int):
    """Select ``keep`` random (order-preserving) positions of x [B, S, D].

    Returns (x_kept [B, keep, D], indices [B, keep]).  The kernel-analog of
    ``gather_tokens`` (``csrc/random_ltd/gather_scatter.cu``)."""
    b, s, _ = x.shape
    scores = jax.random.uniform(rng, (b, s))
    _, idx = jax.lax.top_k(scores, keep)          # random subset
    idx = jnp.sort(idx, axis=1)                   # preserve temporal order
    return jnp.take_along_axis(x, idx[..., None], axis=1), idx


def token_restore(x_full, x_kept, idx):
    """Scatter processed kept tokens back over the layer input
    (``scatter_tokens`` analog): dropped positions keep x_full's values."""
    b = x_full.shape[0]
    batch_idx = jnp.arange(b)[:, None]
    return x_full.at[batch_idx, idx].set(x_kept)


class RandomLTDScheduler:
    """Maps global step -> kept-token count (reference
    ``random_ltd_scheduler``); reuses the curriculum schedule math."""

    def __init__(self, config: Dict[str, Any]):
        cfg = dict(config)
        self.total_layers = int(cfg.get("random_ltd_layer_num", 0))
        self.ltd_start = int(cfg.get("random_ltd_layer_id_start", 1))
        sched = cfg.get("random_ltd_schedule", cfg)
        self._sched = CurriculumScheduler({
            "curriculum_type": "seqlen",
            "min_difficulty": sched.get("min_value",
                                        cfg.get("min_value", 128)),
            "max_difficulty": sched.get("max_value",
                                        cfg.get("max_value", 1024)),
            "schedule_type": sched.get("schedule_type", "fixed_linear"),
            "schedule_config": sched.get("schedule_config", {
                "total_curriculum_step": cfg.get("total_ltd_step", 1000),
                "difficulty_step": cfg.get("difficulty_step", 64),
            }),
        })

    def get_keep_count(self, global_step: int, seq_len: int) -> int:
        return min(self._sched.get_difficulty(global_step), seq_len)

    @property
    def max_value(self) -> int:
        """Schedule endpoint (kept count when fully ramped)."""
        return self._sched.max_difficulty

    def applies_to_layer(self, layer_idx: int, num_layers: int) -> bool:
        """First and last layer always see the full sequence (reference
        keeps boundary layers dense)."""
        return 0 < layer_idx < num_layers - 1

    def state_dict(self):
        return self._sched.state_dict()

    def load_state_dict(self, sd):
        self._sched.load_state_dict(sd)
