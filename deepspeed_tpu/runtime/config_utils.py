"""Config model base.

Analog of reference ``deepspeed/runtime/config_utils.py`` (``DeepSpeedConfigModel``),
on pydantic v2.  Supports the reference's ``"auto"`` sentinel on annotated fields and
deprecated-field aliasing.
"""

from __future__ import annotations

import json
from typing import Any, Dict

from pydantic import BaseModel, ConfigDict

from ..utils.logging import logger

AUTO = "auto"


class DeepSpeedConfigModel(BaseModel):
    """Base for all config blocks: validate on assignment, warn on unknown keys."""

    model_config = ConfigDict(extra="allow", validate_assignment=True,
                              populate_by_name=True, arbitrary_types_allowed=True,
                              protected_namespaces=())

    def __init__(self, strict: bool = False, **data):
        known = set(self.__class__.model_fields.keys())
        aliases = {
            f.alias
            for f in self.__class__.model_fields.values() if f.alias is not None
        }
        unknown = {k for k in data if k not in known and k not in aliases}
        if unknown:
            msg = (f"{self.__class__.__name__}: unknown config keys {sorted(unknown)}")
            if strict:
                raise ValueError(msg)
            logger.warning(msg)
        super().__init__(**data)

    def dict(self, **kwargs) -> Dict[str, Any]:  # pydantic v1 compat shim
        return self.model_dump(**kwargs)


def get_scalar_param(param_dict: dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_list_param(param_dict: dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict: dict, param_name: str, param_default_value):
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """json object_pairs_hook that rejects duplicate keys (reference behavior)."""
    d = dict((k, v) for k, v in ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter = {}
        for k, _ in ordered_pairs:
            counter[k] = counter.get(k, 0) + 1
        keys = [k for k, v in counter.items() if v > 1]
        raise ValueError(f"Duplicate keys in DeepSpeed config: {keys}")
    return d


class ScientificNotationEncoder(json.JSONEncoder):

    def iterencode(self, o, _one_shot=False):
        if isinstance(o, float) and o >= 1e3:
            return iter([f"{o:e}"])
        return super().iterencode(o, _one_shot=_one_shot)
