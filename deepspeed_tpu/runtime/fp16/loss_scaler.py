"""Loss scaling for fp16 training — functional form.

Analog of reference ``deepspeed/runtime/fp16/loss_scaler.py`` (``LossScaler`` :54,
``DynamicLossScaler`` :77).  The reference mutates a scaler object between steps;
under jit the scaler is a small state pytree updated inside the compiled step, so
overflow handling costs no host sync:

    state  = {cur_scale, cur_hysteresis, good_steps, skipped}
    scaled_loss = loss * cur_scale
    overflow    = any(!isfinite(grads))   (global: XLA reduces across the mesh)
    on overflow: scale /= 2 (after hysteresis), skip update
    else: after scale_window good steps, scale *= 2
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

INITIAL_LOSS_SCALE = "init_scale"
SCALE_WINDOW = "scale_window"
DELAYED_SHIFT = "delayed_shift"
CONSECUTIVE_HYSTERESIS = "consecutive_hysteresis"
MIN_LOSS_SCALE = "min_scale"


class LossScaleState(NamedTuple):
    """All-array state so it passes through jit; whether scaling is *dynamic* is a
    static property of the compiled step (see ``update_scale(dynamic=...)``)."""
    cur_scale: jnp.ndarray      # f32 scalar
    cur_hysteresis: jnp.ndarray  # i32 scalar
    good_steps: jnp.ndarray     # i32 scalar
    skipped: jnp.ndarray        # i32 scalar, total skipped steps (diagnostics)

    @staticmethod
    def create(init_scale: float = 2.0**16,
               delayed_shift: int = 2) -> "LossScaleState":
        return LossScaleState(
            cur_scale=jnp.asarray(init_scale, jnp.float32),
            cur_hysteresis=jnp.asarray(delayed_shift, jnp.int32),
            good_steps=jnp.asarray(0, jnp.int32),
            skipped=jnp.asarray(0, jnp.int32))


def has_overflow(grads) -> jnp.ndarray:
    """True iff any grad entry is NaN/Inf. In-graph global check — the analog of the
    reference's cross-rank overflow all-reduce (``CheckOverflow``)."""
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return jnp.asarray(False)
    flags = [jnp.logical_not(jnp.all(jnp.isfinite(g))) for g in leaves]
    return jnp.any(jnp.stack(flags))


def update_scale(state: LossScaleState, overflow: jnp.ndarray,
                 scale_window: int = 1000, min_scale: float = 1.0,
                 scale_factor: float = 2.0, delayed_shift: int = 2,
                 consecutive_hysteresis: bool = False,
                 dynamic: bool = True) -> LossScaleState:
    """Post-step scale adjustment (reference ``DynamicLossScaler.update_scale``).

    ``dynamic=False`` reproduces the static ``LossScaler`` (:54): the scale never
    moves, but skipped steps are still counted.
    """
    if not dynamic:
        return state._replace(skipped=state.skipped + overflow.astype(jnp.int32))

    def on_overflow(s: LossScaleState) -> LossScaleState:
        exhausted = s.cur_hysteresis <= 1
        new_scale = jnp.where(
            exhausted, jnp.maximum(s.cur_scale / scale_factor, min_scale),
            s.cur_scale)
        new_hyst = jnp.where(exhausted, s.cur_hysteresis, s.cur_hysteresis - 1)
        return s._replace(cur_scale=new_scale, cur_hysteresis=new_hyst,
                          good_steps=jnp.zeros_like(s.good_steps),
                          skipped=s.skipped + 1)

    def on_good(s: LossScaleState) -> LossScaleState:
        window_hit = (s.good_steps + 1) % scale_window == 0
        # reference: hysteresis refills every good step when
        # consecutive_hysteresis, otherwise only at scale_window boundaries
        if consecutive_hysteresis:
            new_hyst = jnp.asarray(delayed_shift, jnp.int32)
        else:
            new_hyst = jnp.where(window_hit,
                                 jnp.asarray(delayed_shift, jnp.int32),
                                 s.cur_hysteresis)
        return s._replace(
            cur_scale=jnp.where(window_hit, s.cur_scale * scale_factor, s.cur_scale),
            cur_hysteresis=new_hyst, good_steps=s.good_steps + 1)

    return jax.lax.cond(overflow, on_overflow, on_good, state)
