"""1-bit optimizers: OnebitAdam, OnebitLamb, ZeroOneAdam.

Reference: ``runtime/fp16/onebit/{adam,lamb,zoadam}.py`` — two-stage
optimizers: a full-precision **warmup** stage (exact Adam/Lamb, variance
learning), then a **compressed** stage where the variance freezes and only
the momentum is exchanged, 1-bit quantized with per-worker error feedback
(``runtime/comm/nccl.py:52``), cutting gradient-communication volume ~32x
(fp32) while matching sample-wise convergence
(``docs/_posts/2020-09-09-onebit-adam-blog-post.md``).

TPU realisation: the optimizers are optax ``GradientTransformation``s that
run *inside the jitted step on already-reduced gradients* (XLA SPMD performs
the reduction), so the stage semantics — frozen variance, sign-quantized
momentum with error feedback — are preserved exactly; the *wire* compression
lives in ``runtime/comm/compressed.py`` (``compressed_allreduce``: int8
signs over ICI inside ``shard_map``) for loops that manage their own
gradient exchange.  Engine config names match the reference
("OneBitAdam", "OneBitLamb", "ZeroOneAdam").
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax


class OnebitState(NamedTuple):
    count: jnp.ndarray
    m: optax.Updates
    v: optax.Updates
    error: optax.Updates


def _quantize_with_feedback(m, error):
    """sign+scale quantization of the momentum, error carried forward."""
    comp = jax.tree_util.tree_map(lambda a, e: a + e, m, error)
    scale = jax.tree_util.tree_map(
        lambda c: jnp.mean(jnp.abs(c)), comp)
    mq = jax.tree_util.tree_map(
        lambda c, s: jnp.sign(c) * s, comp, scale)
    new_error = jax.tree_util.tree_map(lambda c, q: c - q, comp, mq)
    return mq, new_error


def scale_by_onebit_adam(b1: float = 0.9, b2: float = 0.999,
                         eps: float = 1e-8, freeze_step: int = 100
                         ) -> optax.GradientTransformation:
    """Adam whose variance freezes at ``freeze_step``; afterwards the update
    direction is the 1-bit-quantized momentum (reference ``onebit/adam.py``
    ``comm_time``/compressed stage)."""

    def init_fn(params):
        z = lambda: jax.tree_util.tree_map(jnp.zeros_like, params)
        return OnebitState(jnp.zeros((), jnp.int32), z(), z(), z())

    def update_fn(updates, state, params=None):
        count = state.count + 1
        warm = count <= freeze_step
        m = jax.tree_util.tree_map(
            lambda mm, g: b1 * mm + (1 - b1) * g, state.m, updates)
        # variance only learns during warmup (frozen afterwards)
        v = jax.tree_util.tree_map(
            lambda vv, g: jnp.where(warm, b2 * vv + (1 - b2) * g * g, vv),
            state.v, updates)
        mq, err_q = _quantize_with_feedback(m, state.error)
        m_eff = jax.tree_util.tree_map(
            lambda mm, q: jnp.where(warm, mm, q), m, mq)
        error = jax.tree_util.tree_map(
            lambda e, ne: jnp.where(warm, e, ne), state.error, err_q)
        bc1 = 1 - b1 ** count.astype(jnp.float32)
        bc2 = 1 - b2 ** jnp.minimum(count, freeze_step).astype(jnp.float32)
        out = jax.tree_util.tree_map(
            lambda mm, vv: (mm / bc1) / (jnp.sqrt(vv / bc2) + eps),
            m_eff, v)
        return out, OnebitState(count, m, v, error)

    return optax.GradientTransformation(init_fn, update_fn)


def scale_by_zero_one_adam(b1: float = 0.9, b2: float = 0.999,
                           eps: float = 1e-8,
                           var_freeze_step: int = 100,
                           var_update_scaler: int = 16,
                           local_step_scaler: int = 32678,
                           local_step_clipper: int = 16
                           ) -> optax.GradientTransformation:
    """0/1 Adam (reference ``onebit/zoadam.py``): the variance keeps updating
    after the freeze point but only at exponentially-spaced steps (the
    "variance update policy"), and compression applies between those
    refreshes — a strict generalization of 1-bit Adam."""

    def init_fn(params):
        z = lambda: jax.tree_util.tree_map(jnp.zeros_like, params)
        return OnebitState(jnp.zeros((), jnp.int32), z(), z(), z())

    def update_fn(updates, state, params=None):
        count = state.count + 1
        cf = count.astype(jnp.float32)
        warm = count <= var_freeze_step
        # after freeze: update variance when (count - freeze) is a multiple
        # of var_update_scaler (the reference's interval policy, simplified
        # to a fixed interval)
        refresh = jnp.logical_and(
            jnp.logical_not(warm),
            jnp.equal(jnp.mod(count - var_freeze_step, var_update_scaler), 0))
        learn_v = jnp.logical_or(warm, refresh)
        m = jax.tree_util.tree_map(
            lambda mm, g: b1 * mm + (1 - b1) * g, state.m, updates)
        v = jax.tree_util.tree_map(
            lambda vv, g: jnp.where(learn_v, b2 * vv + (1 - b2) * g * g, vv),
            state.v, updates)
        mq, err_q = _quantize_with_feedback(m, state.error)
        m_eff = jax.tree_util.tree_map(
            lambda mm, q: jnp.where(warm, mm, q), m, mq)
        error = jax.tree_util.tree_map(
            lambda e, ne: jnp.where(warm, e, ne), state.error, err_q)
        bc1 = 1 - b1 ** cf
        out = jax.tree_util.tree_map(
            lambda mm, vv: (mm / bc1) / (jnp.sqrt(vv) + eps), m_eff, v)
        return out, OnebitState(count, m, v, error)

    return optax.GradientTransformation(init_fn, update_fn)


def _trust_ratio(min_coeff: float, max_coeff: float):
    def apply(params, updates):
        def per_leaf(p, u):
            pn = jnp.linalg.norm(p.reshape(-1))
            un = jnp.linalg.norm(u.reshape(-1))
            ratio = jnp.where(un > 0, pn / jnp.maximum(un, 1e-12), 1.0)
            return u * jnp.clip(ratio, min_coeff, max_coeff)

        return jax.tree_util.tree_map(per_leaf, params, updates)

    return apply


def onebit_adam(lr=1e-3, betas: Tuple[float, float] = (0.9, 0.999),
                eps: float = 1e-8, weight_decay: float = 0.0,
                freeze_step: int = 100) -> optax.GradientTransformation:
    txs = [scale_by_onebit_adam(betas[0], betas[1], eps, freeze_step)]
    if weight_decay:
        txs.append(optax.add_decayed_weights(weight_decay))
    txs.append(optax.scale_by_learning_rate(lr))
    return optax.chain(*txs)


def onebit_lamb(lr=1e-3, betas: Tuple[float, float] = (0.9, 0.999),
                eps: float = 1e-8, weight_decay: float = 0.0,
                freeze_step: int = 100, min_coeff: float = 0.01,
                max_coeff: float = 0.3) -> optax.GradientTransformation:
    """1-bit LAMB (reference ``onebit/lamb.py``): onebit-adam direction with
    the LAMB layerwise trust ratio applied on top."""
    inner = scale_by_onebit_adam(betas[0], betas[1], eps, freeze_step)
    trust = _trust_ratio(min_coeff, max_coeff)

    def init_fn(params):
        return inner.init(params)

    def update_fn(updates, state, params=None):
        out, state = inner.update(updates, state, params)
        if weight_decay:
            out = jax.tree_util.tree_map(
                lambda u, p: u + weight_decay * p, out, params)
        out = trust(params, out)
        return out, state

    return optax.chain(optax.GradientTransformation(init_fn, update_fn),
                       optax.scale_by_learning_rate(lr))


def zero_one_adam(lr=1e-3, betas: Tuple[float, float] = (0.9, 0.999),
                  eps: float = 1e-8, weight_decay: float = 0.0,
                  var_freeze_step: int = 100, var_update_scaler: int = 16
                  ) -> optax.GradientTransformation:
    txs = [scale_by_zero_one_adam(betas[0], betas[1], eps, var_freeze_step,
                                  var_update_scaler)]
    if weight_decay:
        txs.append(optax.add_decayed_weights(weight_decay))
    txs.append(optax.scale_by_learning_rate(lr))
    return optax.chain(*txs)
