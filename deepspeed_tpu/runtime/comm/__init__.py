"""Custom communication backends (reference ``runtime/comm/*``):
error-compensated compressed collectives for the 1-bit optimizers."""

from .compressed import (CompressedBackend, compressed_allreduce,
                         error_shapes)

__all__ = ["CompressedBackend", "compressed_allreduce", "error_shapes"]
