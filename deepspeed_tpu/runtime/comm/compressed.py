"""Error-compensated 1-bit compressed collectives.

Reference: ``runtime/comm/nccl.py:52 NcclBackend.compressed_allreduce`` — the
1-bit Adam communication layer.  The algorithm is two-stage, chunked:

 1. every worker splits its tensor into ``n`` chunks, 1-bit-compresses each
    (sign int8 + one f32 scale per chunk, residual kept as **worker error**
    feedback), and all-to-alls the chunks so worker ``j`` holds everyone's
    chunk ``j``;
 2. worker ``j`` decompresses and averages its chunk, compresses the average
    (residual kept as **server error** feedback), and all-gathers the result.

Signs travel PACKED, 8 per byte (``uint8`` bitwise ops around the
collectives), exactly like the reference's ``compress_by_chunk``
(``cupy.packbits``, ``runtime/comm/nccl.py:78-85``): wire traffic is
~2x size x 1/8 byte + per-chunk f32 scales vs ~2x size x 4 bytes for an
fp32 ring all-reduce — a ~32x wire reduction, expressed with
``lax.all_to_all``/``all_gather`` on packed uint8 inside ``shard_map`` so
XLA moves the small payload over ICI.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

# bit i of a packed byte holds sign of element 8*j + i (1 <-> +1, 0 <-> -1)
_BIT_WEIGHTS = tuple(1 << i for i in range(8))


def _pack_signs(comp):
    """comp [..., c] (c % 8 == 0) -> packed sign bits, uint8 [..., c // 8]."""
    bits = (comp >= 0).astype(jnp.uint8).reshape(comp.shape[:-1] + (-1, 8))
    w = jnp.asarray(_BIT_WEIGHTS, jnp.uint8)
    return jnp.sum(bits * w, axis=-1, dtype=jnp.uint8)


def _unpack_signs(packed):
    """packed uint8 [..., c8] -> signs f32 [..., c8 * 8] in {-1, +1}."""
    shifts = jnp.asarray(range(8), jnp.uint8)
    bits = (packed[..., None] >> shifts) & jnp.uint8(1)
    return (bits.astype(jnp.float32) * 2.0 -
            1.0).reshape(packed.shape[:-1] + (-1,))


def _compress(comp):
    """sign/scale 1-bit quantization per leading chunk: comp [n, c] ->
    (packed sign bits uint8 [n, c/8], scales f32 [n], residual)."""
    scales = jnp.mean(jnp.abs(comp), axis=-1)
    sign_f = jnp.where(comp >= 0, 1.0, -1.0)
    packed = _pack_signs(comp)
    return packed, scales, comp - sign_f * scales[..., None]


def compressed_allreduce(x, worker_error, server_error, axis_name: str):
    """All-reduce-mean of ``x`` over ``axis_name`` with 1-bit compression.

    Must run inside ``shard_map``/``pmap``.  ``worker_error`` has ``x``'s
    (padded, chunked) shape [n, c]; ``server_error`` has one chunk's shape
    [c].  Returns ``(mean, new_worker_error, new_server_error)``; threading
    the errors into the next call keeps the *accumulated* reduction unbiased
    even though each step is lossy (the 1-bit Adam convergence argument).

    Use :func:`error_shapes` to initialize the error buffers.
    """
    n = jax.lax.psum(1, axis_name)
    orig_shape = x.shape
    flat = x.reshape(-1)
    c = error_shapes(orig_shape, n)[0][1]     # 8-aligned chunk length
    pad = n * c - flat.size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    chunks = flat.reshape(n, c)                               # [n, c]

    # stage 1: worker-side compression + all-to-all of PACKED sign bits
    comp = chunks + worker_error
    packed, scales, new_worker_error = _compress(comp)
    # trace-time wire accounting: the comms logger records the packed uint8
    # payloads (the dense equivalent would be 4 bytes/elem both rounds)
    from ...comm.comm import _record

    _record("all_to_all", packed, axis_name, log_name="compressed_allreduce")
    # worker j receives row j of every peer: [n, c/8] rows ordered by source
    recv_packed = jax.lax.all_to_all(packed, axis_name, split_axis=0,
                                     concat_axis=0, tiled=True)
    recv_scales = jax.lax.all_to_all(scales, axis_name, split_axis=0,
                                     concat_axis=0, tiled=True)
    chunk_mean = jnp.mean(
        _unpack_signs(recv_packed) * recv_scales[:, None], axis=0)  # [c]

    # stage 2: server-side compression + all-gather of packed bits
    comp2 = (chunk_mean + server_error)[None, :]
    packed2, scales2, server_residual = _compress(comp2)
    new_server_error = server_residual[0]
    _record("all_gather", packed2[0], axis_name,
            log_name="compressed_allreduce")
    out_packed = jax.lax.all_gather(packed2[0], axis_name)   # [n, c/8] uint8
    out_scales = jax.lax.all_gather(scales2[0], axis_name)   # [n]
    out = (_unpack_signs(out_packed) * out_scales[:, None]).reshape(-1)
    size = int(np.prod(orig_shape))
    return out[:size].reshape(orig_shape), new_worker_error, new_server_error


def error_shapes(x_shape, n: int) -> Tuple[tuple, tuple]:
    """(worker_error_shape, server_error_shape) for a tensor of x_shape
    reduced over n workers; chunk length is 8-aligned for bit packing.

    Format note: the 8-alignment (introduced with the packed wire format)
    changed these shapes wherever ``ceil(size/n) % 8 != 0`` — 1-bit
    checkpoints written by the earlier int8-sign build store unpadded
    error buffers and cannot resume against the new shapes (no released
    version ever shipped the old layout, so no pad-on-load migration is
    provided)."""
    size = int(np.prod(x_shape))
    c = -(-size // n)
    c = (c + 7) // 8 * 8
    return (n, c), (c,)


class CompressedBackend:
    """Stateful convenience wrapper holding per-worker/server error buffers
    (reference ``NcclBackend`` keeps ``worker_error``/``server_error``)."""

    def __init__(self, mesh, axis_name: str = "dp"):
        self.mesh = mesh
        self.axis_name = axis_name
        self.n = mesh.shape[axis_name]
        self._errors = {}
        self._run = self._build_run()  # jitted ONCE; retraces only per shape

    def _build_run(self):
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        @jax.jit
        def run(x, we, se):
            def body(xw, wew, sew):
                m, nwe, nse = compressed_allreduce(
                    xw[0], wew[0], sew[0], self.axis_name)
                return m[None], nwe[None], nse[None]

            return shard_map(
                body, mesh=self.mesh,
                in_specs=(P(self.axis_name),) * 3,
                out_specs=(P(self.axis_name),) * 3)(x, we, se)

        return run

    def allreduce(self, key: str, x_sharded):
        """All-reduce a [n, ...]-stacked per-worker array (leading dim =
        worker) with persistent error feedback keyed by ``key``."""
        n = self.n
        per_shape = x_sharded.shape[1:]
        if key not in self._errors:
            we_s, se_s = error_shapes(per_shape, n)
            self._errors[key] = (jnp.zeros((n,) + we_s, jnp.float32),
                                 jnp.zeros((n,) + se_s, jnp.float32))
        we, se = self._errors[key]
        mean_sh, nwe, nse = self._run(x_sharded, we, se)
        self._errors[key] = (nwe, nse)
        return mean_sh
